#!/usr/bin/env python3
"""Render a --postmortem-dir black-box bundle into a wall-clock
narrative.

The engine/router postmortem sink (cake_tpu/obs/actions.py,
PostmortemSink) dumps one JSON bundle per terminal incident — breaker
stop, poisoned request, failed recovery, SIGTERM — holding every
in-memory observability ring: recent step records, the typed event
ring, request/hop trace spans, anomaly + action history, a stats and
metrics snapshot, and the journal tail. This tool merges those rings
onto ONE wall-clock axis so the incident reads as a story: what the
workload was doing, which anomaly fired, what the control loop tried,
and what the terminal event was.

Usage:
    python tools/postmortem.py BUNDLE.json
    python tools/postmortem.py /path/to/postmortem-dir   # newest bundle
    python tools/postmortem.py BUNDLE.json --limit 500   # longer tail
    python tools/postmortem.py BUNDLE.json --metrics     # +metrics text

The narrative is tail-limited (--limit, default 120 lines) because the
step ring dominates: the interesting lines are at the END, right before
the trigger. The trigger itself is always the last line.

Exit status: 0 = rendered, 2 = bad arguments / unreadable bundle.
"""

from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# one narrative line: (wall_ts, source_tag, text). Sorted by (ts, tag,
# text) so rendering is deterministic even for equal timestamps.
Entry = Tuple[float, str, str]

# scalar event/action fields worth showing inline; everything else
# stays in the bundle (the narrative is a summary, not a re-dump)
_SKIP_FIELDS = ("seq", "ts", "type", "rid", "t", "kind", "action",
                "outcome")


def _fmt_ts(ts: float) -> str:
    try:
        dt = datetime.datetime.fromtimestamp(ts)
        return dt.strftime("%H:%M:%S.") + f"{dt.microsecond // 1000:03d}"
    except (OverflowError, OSError, ValueError):
        return f"{ts:.3f}"


def _kv(d: Dict, skip=_SKIP_FIELDS) -> str:
    parts = [f"{k}={v}" for k, v in d.items()
             if k not in skip and isinstance(v, (str, int, float, bool))]
    return (" " + " ".join(parts)) if parts else ""


def _cause_line(cause) -> str:
    if not isinstance(cause, dict):
        return ""
    keys = ("value", "threshold", "baseline", "ratio", "comparison")
    parts = [f"{k}={cause[k]}" for k in keys if k in cause]
    return (" (" + ", ".join(parts) + ")") if parts else ""


def _step_entries(bundle: Dict) -> List[Entry]:
    out: List[Entry] = []
    for s in bundle.get("steps") or []:
        ts = s.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        txt = (f"step {s.get('step')} {s.get('kind')}"
               f" rows={s.get('rows')} tokens={s.get('tokens')}"
               f" wall={s.get('wall_s')}s")
        if s.get("compiled"):
            txt += "  COMPILED"
        out.append((float(ts), "step", txt))
    return out


def _event_entries(bundle: Dict) -> List[Entry]:
    out: List[Entry] = []
    for e in bundle.get("events") or []:
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if e.get("type") in ("anomaly", "anomaly_action"):
            # the sentinel and action rings render these with richer
            # detail — the bus copies would be duplicate lines
            continue
        rid = f" rid={e['rid']}" if e.get("rid") is not None else ""
        out.append((float(ts), "event",
                    f"{e.get('type')}{rid}{_kv(e)}"))
    return out


def _anomaly_entries(bundle: Dict) -> List[Entry]:
    an = bundle.get("anomalies") or {}
    seen = set()
    out: List[Entry] = []
    for a in list(an.get("active") or []) + list(an.get("anomalies")
                                                 or []):
        key = (a.get("kind"), a.get("fired_at"))
        if key in seen:
            continue
        seen.add(key)
        fired = a.get("fired_at")
        if isinstance(fired, (int, float)):
            out.append((float(fired), "ANOMALY",
                        f"{a.get('kind')} FIRED"
                        f"{_cause_line(a.get('cause'))}"))
        cleared = a.get("cleared_at")
        if isinstance(cleared, (int, float)):
            out.append((float(cleared), "ANOMALY",
                        f"{a.get('kind')} cleared"))
    return out


def _action_entries(bundle: Dict) -> List[Entry]:
    # the action ring carries richer detail than its bus event (the
    # event only rides scalars) — prefer the ring, it is authoritative
    out: List[Entry] = []
    for a in bundle.get("actions") or []:
        t = a.get("t")
        if not isinstance(t, (int, float)):
            continue
        out.append((float(t), "ACTION",
                    f"{a.get('action')} [{a.get('outcome')}] "
                    f"on {a.get('kind')}{_kv(a)}"))
    return out


def _trace_entries(bundle: Dict) -> List[Entry]:
    out: List[Entry] = []
    for r in bundle.get("traces") or []:
        rid = r.get("rid")
        for sp in r.get("spans") or []:
            t = sp.get("t")
            if isinstance(t, (int, float)):
                out.append((float(t), "req",
                            f"rid={rid} {sp.get('name')}"))
    for r in bundle.get("hops") or []:
        trace = r.get("trace")
        for sp in r.get("spans") or []:
            t = sp.get("t")
            if isinstance(t, (int, float)):
                out.append((float(t), "hop",
                            f"{trace} {sp.get('name')}"
                            f"{_kv(sp, skip=('name', 't'))}"))
    return out


def render(bundle: Dict, limit: int = 120,
           show_metrics: bool = False) -> str:
    lines: List[str] = []
    wall = bundle.get("wall_time")
    trigger = bundle.get("trigger", "?")
    lines.append(f"postmortem bundle v{bundle.get('version', '?')} — "
                 f"trigger: {trigger}")
    if isinstance(wall, (int, float)):
        lines.append(f"  at {_fmt_ts(float(wall))} "
                     f"({datetime.datetime.fromtimestamp(wall)})")
    if bundle.get("reason"):
        lines.append(f"  reason: {bundle['reason']}")
    stats = bundle.get("stats")
    if isinstance(stats, dict):
        picks = [f"{k}={stats[k]}" for k in
                 ("steps", "completed", "errors", "preempted",
                  "config_switches", "config_rollbacks", "last_error")
                 if stats.get(k) not in (None, 0, "")]
        if picks:
            lines.append("  stats: " + " ".join(picks))
    an = bundle.get("anomalies") or {}
    active = an.get("active") or []
    if active:
        lines.append("  active anomalies: "
                     + ", ".join(str(a.get("kind")) for a in active))
    jt = bundle.get("journal_tail")
    if jt:
        lines.append(f"  journal tail: {len(jt)} record(s) in bundle")
    lines.append("")

    entries = (_step_entries(bundle) + _event_entries(bundle)
               + _anomaly_entries(bundle) + _action_entries(bundle)
               + _trace_entries(bundle))
    if isinstance(wall, (int, float)):
        reason = f": {bundle['reason']}" if bundle.get("reason") else ""
        entries.append((float(wall), "TRIGGER",
                        f"{trigger}{reason}"))
    entries.sort(key=lambda e: (e[0], e[1], e[2]))
    shown = entries[-max(1, int(limit)):]
    if len(entries) > len(shown):
        lines.append(f"  ... {len(entries) - len(shown)} earlier "
                     f"line(s) elided (--limit {limit})")
    width = max((len(tag) for _, tag, _ in shown), default=0)
    for ts, tag, txt in shown:
        lines.append(f"{_fmt_ts(ts)}  {tag.ljust(width)}  {txt}")

    if show_metrics and bundle.get("metrics"):
        lines.append("")
        lines.append("-- metrics snapshot " + "-" * 40)
        lines.append(str(bundle["metrics"]).rstrip())
    return "\n".join(lines) + "\n"


def _resolve(path: str) -> Optional[str]:
    """A file renders itself; a directory renders its newest bundle."""
    if os.path.isdir(path):
        cands = sorted(glob.glob(os.path.join(path,
                                              "postmortem-*.json")))
        return cands[-1] if cands else None
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle",
                    help="bundle JSON file, or a --postmortem-dir "
                         "(renders the newest bundle in it)")
    ap.add_argument("--limit", type=int, default=120,
                    help="max narrative lines, tail-kept (default 120)")
    ap.add_argument("--metrics", action="store_true",
                    help="append the bundled metrics snapshot")
    args = ap.parse_args(argv)

    path = _resolve(args.bundle)
    if path is None:
        print(f"postmortem: no postmortem-*.json in {args.bundle}",
              file=sys.stderr)
        return 2
    try:
        with open(path) as f:
            bundle = json.load(f)
    except (OSError, ValueError) as e:
        print(f"postmortem: cannot read {path}: {e}", file=sys.stderr)
        return 2
    if not isinstance(bundle, dict):
        print(f"postmortem: {path} is not a bundle object",
              file=sys.stderr)
        return 2
    print(f"postmortem: {path}")
    sys.stdout.write(render(bundle, limit=args.limit,
                            show_metrics=args.metrics))
    return 0


if __name__ == "__main__":
    sys.exit(main())
