#!/usr/bin/env python3
"""Tier-1 wall-budget lint: catch a fast-lane timeout BEFORE it happens.

The tier-1 verify command (ROADMAP.md) runs the fast lane under a hard
870s `timeout`, and the suite already spends most of it — a timeout
zeroes the entire run, so a single newly-heavy test can silently turn a
green lane red. This tool parses a pytest log (the tee'd tier-1 log, or
any run with ``--durations=N`` enabled) and enforces two budgets:

  * no single fast-lane test phase (setup/call/teardown) may exceed
    ``--max-test`` seconds (default 15);
  * the suite total (the ``... in 729.36s ...`` summary line) may not
    exceed ``--max-total`` seconds (default 840 — headroom under the
    870s kill).

A soft warning is printed (stderr) when the total passes
``--warn-frac`` of the budget (default 0.9) so drift is visible before
it fails. Durations lines are optional — without them only the total
is checked (and their absence is noted).

**Per-test cap calibration** (the PR 7/8 false-failure fix): the 15s
per-test cap was tuned on a fast box, and slow sessions of the SAME
environment pushed pre-existing heavy tests (sd txt2img, qwen2 golden
setup) past it without any code change. The cap now scales by a
box-speed factor: ``CAKE_T1_SCALE`` (explicit override), else a cheap
~0.3s timing probe (a fixed pure-Python workload vs its fast-box
nominal), clamped to [1.0, 4.0] — so a slow box relaxes the PER-TEST
cap proportionally while the ABSOLUTE 840s total cap stays untouched
(the 870s kill does not care how slow the box is). Tests that only
pass because of the scale are listed in the warnings ("within the
scaled cap") so the relaxation is always visible, never silent.

Usage:
    python tools/check_t1_budget.py /tmp/_t1.log
    python tools/check_t1_budget.py --max-test 15 --max-total 840 LOG
    python tools/check_t1_budget.py --json /tmp/_t1.log   # one JSON line
    CAKE_T1_SCALE=2 python tools/check_t1_budget.py LOG   # slow box
    python tools/check_t1_budget.py --scale 1 LOG  # no calibration

``--json`` prints ONE machine-readable summary line on stdout
({"rc", "total_s", "violations", "warnings", "n_durations"}) with the
human messages folded into the lists — for CI steps that want to attach
the budget verdict to a build artifact instead of grepping stdout.

Exit status: 0 = within budget, 1 = over budget, 2 = no parseable
pytest summary in the log (a truncated/killed run is itself a failure:
the 870s timeout produces exactly this shape).

tests/test_t1_budget_tool.py lints this tool on fixture logs in tier-1,
per the tools-as-tests policy (lint_metrics.py precedent).
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time
from typing import List, Optional, Tuple

# fast-box nominal for the calibration probe below: the workload runs
# in ~0.10s on the boxes the 15s cap was tuned on, set UNDER that so
# the derived scale carries headroom — the heavy tests the cap guards
# are XLA-compile-bound, which degrades faster than pure-Python on a
# slow box (measured: a session whose probe read ~1.6x ran the qwen2
# golden setup ~1.75x slower), and the probe itself jitters ~10%
# between runs. The resulting ~25% cap relaxation on a reference box
# is acceptable: the absolute 840s total cap stays the hard backstop.
# Bounded so a pathological probe can neither tighten the cap below
# its tuned value nor void it entirely.
PROBE_NOMINAL_S = 0.08
SCALE_MIN, SCALE_MAX = 1.0, 4.0


def probe_seconds() -> float:
    """Best-of-3 timing of a fixed pure-Python workload — CPU-bound,
    allocation-free, deterministic, so it tracks interpreter speed on
    the box (the same thing that stretches every test's wall time)."""
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(2_000_000):
            acc += i * i
        best = min(best, time.perf_counter() - t0)
    return best


def calibrate_scale(env=None) -> Tuple[float, str]:
    """(per-test cap scale, human-readable source). CAKE_T1_SCALE wins
    (CI pins it for reproducible verdicts); else the timing probe."""
    env = os.environ if env is None else env
    raw = env.get("CAKE_T1_SCALE")
    if raw:
        try:
            v = float(raw)
        except ValueError:
            return 1.0, f"ignored unparseable CAKE_T1_SCALE={raw!r}"
        return (max(SCALE_MIN, min(SCALE_MAX, v)),
                f"CAKE_T1_SCALE={raw}")
    t = probe_seconds()
    scale = max(SCALE_MIN, min(SCALE_MAX, t / PROBE_NOMINAL_S))
    return scale, f"probe {t:.3f}s vs {PROBE_NOMINAL_S:.2f}s nominal"

# `1.23s call     tests/test_x.py::test_y` (pytest --durations output)
DURATION_RE = re.compile(
    r"^\s*(?P<secs>\d+(?:\.\d+)?)s\s+"
    r"(?P<phase>call|setup|teardown)\s+"
    r"(?P<test>\S+)\s*$")
# `===== 338 passed, 2 skipped in 729.36s (0:12:09) =====`, and the
# undecorated `pytest -q` form `4 failed, 356 passed in 683.52s
# (0:11:23)` (the tier-1 command runs -q) — the wall number is what we
# budget, from any passed/failed/error/skipped summary
SUMMARY_RE = re.compile(
    r"^(?:=+ )?.*\b(?:passed|failed|errors?|skipped|no tests ran)\b.*"
    r"\bin (?P<secs>\d+(?:\.\d+)?)s(?: \([0-9:]+\))?(?: =+)?\s*$",
    re.MULTILINE)


def parse_log(text: str) -> Tuple[float | None, List[Tuple[float, str, str]]]:
    """(total seconds | None, [(secs, phase, test), ...])."""
    total = None
    for m in SUMMARY_RE.finditer(text):
        total = float(m.group("secs"))   # last summary wins (reruns)
    durations = [
        (float(m.group("secs")), m.group("phase"), m.group("test"))
        for line in text.splitlines()
        if (m := DURATION_RE.match(line))
    ]
    return total, durations


def summarize(text: str, max_test: float, max_total: float,
              warn_frac: float, scale: float = 1.0) -> dict:
    """Pure verdict: {"rc", "total_s", "violations", "warnings",
    "n_durations", "scale", "scaled_tests"} — the single source both
    output modes render. `scale` relaxes the PER-TEST cap only (slow
    boxes run every test proportionally slower); the total cap is
    absolute — the 870s kill does not scale."""
    total, durations = parse_log(text)
    if total is None:
        return {
            "rc": 2, "total_s": None, "n_durations": len(durations),
            "scale": scale, "scaled_tests": [],
            "violations": [
                "no pytest summary line found — truncated or killed "
                "run (the 870s timeout produces exactly this)"],
            "warnings": [],
        }
    scale = max(1.0, float(scale))
    cap = max_test * scale
    violations, warnings, scaled = [], [], []
    for secs, phase, test in durations:
        if secs > cap:
            violations.append(
                f"{test} {phase} took {secs:.1f}s "
                f"(> {cap:.1f}s per-test cap"
                + (f" = {max_test:.0f}s x {scale:.2f} box scale)"
                   if scale > 1.0 else ")"))
        elif secs > max_test:
            # passed ONLY because of the box-speed scale: name it so
            # the relaxation is visible, never silent
            scaled.append(f"{test} {phase}")
            warnings.append(
                f"{test} {phase} took {secs:.1f}s — over the "
                f"{max_test:.0f}s nominal cap, within the scaled "
                f"{cap:.1f}s cap ({scale:.2f}x box scale)")
    if total > max_total:
        violations.append(
            f"suite total {total:.1f}s exceeds {max_total:.0f}s "
            "(the lane is killed at 870s)")
    elif total > warn_frac * max_total:
        warnings.append(
            f"suite total {total:.1f}s is above {warn_frac:.0%} of "
            f"the {max_total:.0f}s budget — move heavy tests to "
            "-m slow before the lane times out")
    if not durations:
        warnings.append(
            "no --durations lines in the log; only the suite total "
            "was checked (run pytest with --durations=25 for per-test "
            "enforcement)")
    return {
        "rc": 1 if violations else 0, "total_s": total,
        "n_durations": len(durations), "scale": scale,
        "scaled_tests": scaled,
        "violations": violations, "warnings": warnings,
    }


def check(text: str, max_test: float, max_total: float,
          warn_frac: float, out=sys.stdout, err=sys.stderr,
          as_json: bool = False, scale: float = 1.0,
          scale_source: str = "") -> int:
    s = summarize(text, max_test, max_total, warn_frac, scale=scale)
    if scale_source:
        s["scale_source"] = scale_source
    if as_json:
        import json
        print(json.dumps(s), file=out)
        return s["rc"]
    if s["rc"] == 2:
        print("BUDGET: " + s["violations"][0], file=err)
        return 2
    for v in s["violations"]:
        print(f"BUDGET FAIL: {v}", file=out)
    for w in s["warnings"]:
        print(f"BUDGET WARN: {w}", file=err)
    if s["rc"] == 0:
        n = s["n_durations"]
        cap = max_test * max(1.0, scale)
        print(f"BUDGET OK: total {s['total_s']:.1f}s <= "
              f"{max_total:.0f}s"
              + (f"; slowest of {n} phases within {cap:.1f}s"
                 + (f" (cap scaled {scale:.2f}x: {scale_source})"
                    if scale > 1.0 and scale_source else "")
                 if n else ""), file=out)
    return s["rc"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", help="pytest log file, or '-' for stdin")
    ap.add_argument("--max-test", type=float, default=15.0,
                    help="per-test phase budget, seconds (default 15)")
    ap.add_argument("--max-total", type=float, default=840.0,
                    help="suite wall budget, seconds (default 840)")
    ap.add_argument("--warn-frac", type=float, default=0.9,
                    help="warn when total exceeds this fraction of "
                         "--max-total (default 0.9)")
    ap.add_argument("--json", action="store_true",
                    help="print one machine-readable JSON summary line "
                         "instead of the human messages")
    ap.add_argument("--scale", type=float, default=None,
                    help="explicit per-test cap scale (skips "
                         "calibration; 1 = the nominal cap). Default: "
                         "CAKE_T1_SCALE env, else a ~0.3s timing probe")
    args = ap.parse_args(argv)
    if args.log == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(args.log, errors="replace") as f:
                text = f.read()
        except OSError as e:
            if args.json:
                import json
                print(json.dumps({
                    "rc": 2, "total_s": None, "n_durations": 0,
                    "violations": [f"cannot read {args.log}: {e}"],
                    "warnings": []}))
            else:
                print(f"BUDGET: cannot read {args.log}: {e}",
                      file=sys.stderr)
            return 2
    if args.scale is not None:
        scale, source = max(1.0, args.scale), f"--scale {args.scale}"
    else:
        scale, source = calibrate_scale()
    return check(text, args.max_test, args.max_total, args.warn_frac,
                 as_json=args.json, scale=scale, scale_source=source)


if __name__ == "__main__":
    sys.exit(main())
