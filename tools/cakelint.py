#!/usr/bin/env python3
"""cakelint — static concurrency & dispatch-discipline gate.

Usage:
    python tools/cakelint.py cake_tpu/ [--json] [--rules r1,r2]
                             [--baseline FILE] [--write-baseline FILE]

Checks (cake_tpu/analysis/, declaration-driven — see that package's
docstrings for the vocabulary grammar):

    affinity     handler-thread entry points only reach declared
                 engine-thread state via _run_on_engine_thread or the
                 attr's declared lock; no direct calls to
                 @engine_thread_only methods
    guards       every optional-plane dereference (_faults, events,
                 _journal, _shed, _control, _host_tier, ...) is
                 `is not None`-guarded
    locks        declared lock order (_switch_lock -> _rid_lock ->
                 _ckpt_lock); no blocking calls under _rid_lock
    jit-purity   jitted step fns don't mutate self/globals or call
                 time.*/random.*/print under trace

Inline suppression (reason required):  # cakelint: skip[rule] reason

Exit codes: 0 clean, 1 findings, 2 usage/internal error. --json emits a
machine-readable report (version/counts/sites/findings) so driver
rounds can diff finding counts like tools/check_t1_budget.py output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

JSON_SCHEMA_VERSION = 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cakelint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppress findings whose fingerprints are "
                         "recorded in FILE")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write current findings' fingerprints to FILE "
                         "and exit 0")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    from cake_tpu.analysis import core

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    baseline = None
    if args.baseline:
        try:
            baseline = core.load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"cakelint: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"cakelint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        report = core.analyze(args.paths, rules=rules, baseline=baseline)
    except ValueError as e:
        print(f"cakelint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        core.write_baseline(args.write_baseline, report["fingerprints"])
        print(f"cakelint: wrote {len(report['fingerprints'])} "
              f"fingerprint(s) to {args.write_baseline}")
        return 0

    findings = report["findings"]
    if args.as_json:
        out = {
            "version": JSON_SCHEMA_VERSION,
            "rc": 1 if findings else 0,
            "files": report["files"],
            "counts": report["counts"],
            "sites": report["sites"],
            "suppressed": report["suppressed"],
            "baselined": report["baselined"],
            "findings": [dict(f.to_dict(), fingerprint=fp)
                         for f, fp in zip(findings,
                                          report["fingerprints"])],
        }
        print(json.dumps(out, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}")
        checked = ", ".join(f"{r}={n}" for r, n in
                            sorted(report["sites"].items()))
        print(f"cakelint: {len(findings)} finding(s) in "
              f"{report['files']} file(s) "
              f"({report['suppressed']} suppressed, "
              f"{report['baselined']} baselined; sites: {checked})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
