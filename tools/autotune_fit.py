#!/usr/bin/env python3
"""Fit an --autotune-policy table from measured serving data.

The offline half of the online autotuner (cake_tpu/autotune, ISSUE 9 /
Sandwich in PAPERS.md): ingest (config, offered load, throughput)
observations from BENCH-style JSON files and/or --step-log flight
recorder captures, bucket the offered-load axis into regimes, pick the
best measured config per regime, and write the piecewise policy file
the live controller consults (--autotune auto --autotune-policy PATH).

Each non-catch-all regime also gets auto-fitted quality guards
(``max_ttft_p99_s`` / ``min_attainment``) derived from the winning
config's own observation windows — live quality drifting past what the
config ever delivered escalates the lookup toward the catch-all.
Disable with ``--no-guards``; tune with ``--ttft-headroom`` /
``--attainment-margin``.

Inputs:

  * ``--bench FILE [FILE ...]`` — JSON documents scanned recursively
    for observation records: any dict carrying ``config`` (EngineConfig
    JSON) plus ``tok_s`` (and optionally ``offered_rps``). The
    ``bench.py --autotune`` tier emits these under
    ``autotune_observations``; hand-built sweep files work the same.
  * ``--step-log PATH --step-config JSON`` — one flight-recorder JSONL
    per engine config (the recorder has no config column): the log is
    sliced into ``--window`` second windows, each contributing one
    observation under the named config. Repeat the pair per config.

Usage:
    python tools/autotune_fit.py --bench BENCH_r*.json \
        --out policy.json
    python tools/autotune_fit.py \
        --step-log s16.jsonl --step-config '{"slots": 16}' \
        --step-log s32.jsonl --step-config '{"slots": 32}' \
        --out policy.json --regimes 3

Exit status: 0 = policy written, 1 = fit failed (no usable
observations), 2 = bad arguments / unreadable input.

tests/test_autotune.py lints this tool on fixture files in tier-1, per
the tools-as-tests policy (lint_metrics.py precedent).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench", nargs="*", default=[],
                    help="BENCH-style JSON files to scan for "
                         "observation records")
    ap.add_argument("--step-log", action="append", default=[],
                    help="--step-log JSONL capture (pair each with a "
                         "--step-config)")
    ap.add_argument("--step-config", action="append", default=[],
                    help="EngineConfig JSON the paired --step-log was "
                         "captured under")
    ap.add_argument("--window", type=float, default=10.0,
                    help="step-log slice width, seconds (default 10)")
    ap.add_argument("--regimes", type=int, default=4,
                    help="max offered-load regimes (default 4)")
    ap.add_argument("--no-guards", action="store_true",
                    help="do not auto-fit per-regime quality guards "
                         "(max_ttft_p99_s / min_attainment) from the "
                         "observation windows")
    ap.add_argument("--ttft-headroom", type=float, default=1.5,
                    help="max_ttft_p99_s guard = headroom x worst "
                         "observed TTFT p99 of the winning config "
                         "(default 1.5)")
    ap.add_argument("--attainment-margin", type=float, default=0.9,
                    help="min_attainment guard = margin x worst "
                         "observed attainment of the winning config "
                         "(default 0.9)")
    ap.add_argument("--out", required=True,
                    help="policy file to write (--autotune-policy)")
    args = ap.parse_args(argv)

    from cake_tpu.autotune import (
        EngineConfig, PolicyTable, extract_observations, fit,
        observations_from_step_log,
    )

    if len(args.step_log) != len(args.step_config):
        print("autotune_fit: each --step-log needs a matching "
              "--step-config (the recorder has no config column)",
              file=sys.stderr)
        return 2
    obs = []
    for path in args.bench:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"autotune_fit: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        found = extract_observations(doc)
        print(f"autotune_fit: {path}: {len(found)} observation(s)")
        obs.extend(found)
    for path, cfg_json in zip(args.step_log, args.step_config):
        try:
            cfg = EngineConfig.from_dict(json.loads(cfg_json))
        except (ValueError, TypeError) as e:
            print(f"autotune_fit: bad --step-config {cfg_json!r}: {e}",
                  file=sys.stderr)
            return 2
        try:
            found = observations_from_step_log(path, cfg,
                                               window_s=args.window)
        except OSError as e:
            print(f"autotune_fit: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        print(f"autotune_fit: {path}: {len(found)} window(s) under "
              f"{cfg.to_dict()}")
        obs.extend(found)

    try:
        policy: PolicyTable = fit(
            obs, max_regimes=args.regimes,
            emit_guards=not args.no_guards,
            ttft_headroom=args.ttft_headroom,
            attainment_margin=args.attainment_margin)
    except ValueError as e:
        print(f"autotune_fit: fit failed: {e}", file=sys.stderr)
        return 1
    policy.save(args.out)
    for r in policy.regimes:
        bound = r.get("max_offered_rps")
        guards = "".join(
            f" [{k} {r[k]}]" for k in ("max_ttft_p99_s",
                                       "min_attainment") if k in r)
        print(f"autotune_fit: regime <= "
              f"{'inf' if bound is None else bound} req/s -> "
              f"{r['config'].to_dict()} "
              f"(~{r.get('expected_tok_s', '?')} tok/s over "
              f"{r.get('n_observations', '?')} obs)" + guards)
    print(f"autotune_fit: wrote {len(policy.regimes)} regime(s) to "
          f"{args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
