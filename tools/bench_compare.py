#!/usr/bin/env python3
"""Diff two BENCH round JSONs per tier — the regression gate.

The perf trajectory lives in BENCH_*.json round files, but reading two
of them side by side is manual and error-prone — worst of all when the
TPU tunnel was down and a round's numbers read 0.0 (the ROADMAP "check
the builder files before calling a regression" footgun). This tool
makes the comparison machine-checkable:

  * tier records are found by walking ANY JSON shape (driver round
    files, builder-captured files, raw `bench.py` line dumps): every
    dict carrying a string ``metric`` and a numeric ``value`` is one
    tier, keyed by its metric name (the last occurrence wins — later
    entries in a file are reruns);
  * tiers marked ``"degraded": true`` (the bench emits this whenever a
    probe fell back off-TPU) are SKIPPED, never compared — a degraded
    0.0 is a tunnel outage, not a regression;
  * within each common tier, throughput-like fields (``*tok_s*``,
    higher is better), TTFT p99 fields (``*ttft_p99*_ms``, lower is
    better) and utilization fields (``mfu`` / ``hbm_util``, higher is
    better) are compared under a relative tolerance (--tol, default
    0.1 = 10%).

Exit status (the rc contract, mirroring tools/autotune_fit.py):
    0  compared cleanly, no regression (skipped-degraded tiers noted)
    1  at least one field regressed beyond tolerance
    2  unusable input (missing/unparseable file, no tier records)

Usage:
    python tools/bench_compare.py OLD.json NEW.json [--tol 0.1] [--json]
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_TOL = 0.1


def extract_tiers(obj, out: Optional[Dict[str, dict]] = None
                  ) -> Dict[str, dict]:
    """Walk any JSON structure and collect tier records: dicts with a
    string ``metric`` plus a numeric ``value``. Later occurrences of
    the same metric replace earlier ones (rerun-wins, matching how the
    builder files append tier reruns after the round start)."""
    if out is None:
        out = {}
    if isinstance(obj, dict):
        m, v = obj.get("metric"), obj.get("value")
        if isinstance(m, str) and isinstance(v, (int, float)) \
                and not isinstance(v, bool):
            out[m] = obj
        for val in obj.values():
            extract_tiers(val, out)
    elif isinstance(obj, (list, tuple)):
        for val in obj:
            extract_tiers(val, out)
    return out


def _field_direction(key: str) -> Optional[bool]:
    """True = higher is better, False = lower is better, None = not a
    compared field. The families the tier contract names: throughput
    (tok/s, incl. goodput_tok_s), TTFT p99, MFU/HBM utilization, and
    scalar SLO attainment fields (per-class dicts are flattened into
    scalars by tools/check_bench_round.py before comparison)."""
    k = key.lower()
    if "tok_s" in k or "tokens_per_s" in k:
        return True
    if "ttft_p99" in k and k.endswith("_ms"):
        return False
    if "attainment" in k:
        return True
    if k == "mfu" or k.endswith("_mfu") or k == "hbm_util" \
            or k.endswith("_hbm_util") or k == "roofline_frac":
        return True
    return None


def compare_tier(name: str, old: dict, new: dict,
                 tol: float) -> Tuple[List[dict], List[dict]]:
    """(regressions, improvements) across the comparable numeric
    fields both records carry. A zero/absent old value is skipped — a
    ratio against 0.0 is noise, and honest zeros come from degraded
    rounds this tool already excludes."""
    regs: List[dict] = []
    wins: List[dict] = []
    for key in sorted(set(old) & set(new)):
        direction = _field_direction(key)
        if direction is None:
            continue
        ov, nv = old[key], new[key]
        if not all(isinstance(x, (int, float)) and not isinstance(x, bool)
                   for x in (ov, nv)):
            continue
        if ov <= 0:
            continue
        delta = (nv - ov) / ov
        entry = {"tier": name, "field": key, "old": ov, "new": nv,
                 "delta": round(delta, 4)}
        worse = (delta < -tol) if direction else (delta > tol)
        better = (delta > tol) if direction else (delta < -tol)
        if worse:
            regs.append(entry)
        elif better:
            wins.append(entry)
    return regs, wins


def compare(old_tiers: Dict[str, dict], new_tiers: Dict[str, dict],
            tol: float = DEFAULT_TOL) -> dict:
    """Full comparison summary over the common tier set."""
    common = sorted(set(old_tiers) & set(new_tiers))
    skipped = [t for t in common
               if old_tiers[t].get("degraded") or
               new_tiers[t].get("degraded")]
    regressions: List[dict] = []
    improvements: List[dict] = []
    compared: List[str] = []
    for t in common:
        if t in skipped:
            continue
        regs, wins = compare_tier(t, old_tiers[t], new_tiers[t], tol)
        compared.append(t)
        regressions.extend(regs)
        improvements.extend(wins)
    return {
        "tol": tol,
        "compared": compared,
        "only_old": sorted(set(old_tiers) - set(new_tiers)),
        "only_new": sorted(set(new_tiers) - set(old_tiers)),
        "skipped_degraded": skipped,
        "regressions": regressions,
        "improvements": improvements,
    }


def main(argv: List[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 2
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    tol = DEFAULT_TOL
    if "--tol" in argv:
        i = argv.index("--tol")
        if i + 1 >= len(argv):
            print("--tol needs a number", file=sys.stderr)
            return 2
        try:
            tol = float(argv[i + 1])
        except ValueError:
            print(f"--tol: {argv[i + 1]!r} is not a number",
                  file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    if len(argv) != 2:
        print("usage: bench_compare.py OLD.json NEW.json "
              "[--tol FRAC] [--json]", file=sys.stderr)
        return 2
    tiers = []
    for path in argv:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{path}: unreadable ({e})", file=sys.stderr)
            return 2
        t = extract_tiers(doc)
        if not t:
            print(f"{path}: no tier records (no dict with a string "
                  "'metric' and numeric 'value' anywhere)",
                  file=sys.stderr)
            return 2
        tiers.append(t)
    summary = compare(tiers[0], tiers[1], tol)
    if as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        for t in summary["skipped_degraded"]:
            print(f"skip {t}: degraded round (off-TPU fallback) — "
                  "not comparable")
        for e in summary["improvements"]:
            print(f"ok   {e['tier']}.{e['field']}: {e['old']} -> "
                  f"{e['new']} ({e['delta']:+.1%})")
        for e in summary["regressions"]:
            print(f"REGR {e['tier']}.{e['field']}: {e['old']} -> "
                  f"{e['new']} ({e['delta']:+.1%}, tol {tol:.0%})")
        if not summary["compared"]:
            print("no common non-degraded tiers to compare")
        elif not summary["regressions"]:
            print(f"ok: {len(summary['compared'])} tier(s) compared, "
                  "no regression beyond "
                  f"{tol:.0%}")
    return 1 if summary["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
