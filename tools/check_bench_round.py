#!/usr/bin/env python3
"""Round-workflow regression gate: diff the newest two BENCH rounds.

tools/bench_compare.py made two round files machine-comparable, but
someone still had to RUN it — so a silent tok/s or attainment
regression waited for a human to diff JSONs (the ROADMAP item 5
leftover). This hook closes the loop: run it after every bench round
(or in CI) and a regression beyond tolerance exits nonzero.

What it does:

  * globs ``BENCH_*.json`` in DIR (default: this repo's root), ordered
    by round number (``BENCH_r07`` > ``BENCH_r06``;
    ``BENCH_r05_builder`` is a rerun of round 5 and outranks
    ``BENCH_r05``; names without a round number sort oldest so they
    never displace a real round from the newest-two comparison);
  * skips files with nothing comparable: unreadable/unparseable files
    and files whose every tier record is ``"degraded": true`` (the
    off-TPU-fallback marker — a degraded 0.0 is a tunnel outage, not a
    regression) are reported and passed over;
  * diffs the newest two survivors with bench_compare's tier walker
    under ``--tol`` (default 0.1 = 10%): tok/s down, TTFT p99 up,
    MFU/HBM-util down, attainment down all count. Per-class attainment
    dicts (``{"interactive": 0.97, ...}``) are flattened to scalar
    ``<path>_attainment_<class>`` fields first, so per-class collapses
    are caught even when the aggregate held;
  * anomaly / action counters (``anomalies_fired``,
    ``anomaly_actions`` and friends — the closed-loop tiers report
    them) are SPLIT OUT before the gate: whether the sentinel fired
    between two clean runs is workload noise, not a perf regression.
    Their changes print as ``info`` lines (and ride the --json summary
    under ``anomaly_fields``) but never affect the exit status.

Exit status:
    0  no regression (including "fewer than two comparable rounds")
    1  at least one field regressed beyond tolerance
    2  unusable input (bad directory / malformed flags)

Usage:
    python tools/check_bench_round.py [DIR] [--tol 0.1] [--json]
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROUND_RE = re.compile(r"BENCH_r(\d+)")


def _load_bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(_HERE, "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def round_key(path: str) -> Tuple[int, str]:
    """Sort key: round number first (BENCH_r10 > BENCH_r9), then name
    (BENCH_r05_builder — a rerun — outranks BENCH_r05). Names without
    a round number sort FIRST (oldest): a stray BENCH_baseline.json
    must never displace a real round from the newest-two comparison."""
    name = os.path.basename(path)
    m = _ROUND_RE.search(name)
    return (int(m.group(1)) if m else -1, name)


def flatten_attainment(rec: Dict) -> Dict:
    """Record copy with per-class attainment dicts lifted into scalar
    fields (``low_attainment_interactive``: 0.97), so bench_compare's
    scalar field comparison sees them. Existing scalar keys win on a
    (pathological) name collision."""
    out = dict(rec)

    def walk(obj, path: str) -> None:
        if not isinstance(obj, dict):
            return
        for k, v in obj.items():
            p = f"{path}_{k}" if path else str(k)
            if isinstance(v, dict):
                walk(v, p)
            elif (isinstance(v, (int, float))
                  and not isinstance(v, bool)
                  and "attainment" in p.lower() and p not in rec):
                out.setdefault(p, v)

    for k, v in rec.items():
        if isinstance(v, dict):
            walk(v, str(k))
    return out


# anomaly / closed-loop action fields (any nesting depth once
# flattened): never gate on these — two clean runs legitimately differ
# in whether a detector fired or an action was taken
_ANOMALY_FIELD_RE = re.compile(r"anomal|(^|_)actions?($|_)", re.I)


def split_anomaly_fields(rec: Dict) -> Tuple[Dict, Dict]:
    """(comparable, informational) copies of one tier record: anomaly
    and action counters are diffed informationally, never gated — a
    count change between clean rounds is detector noise, and a NEW
    field appearing (an older round predating the closed loop) must
    not read as a regression either."""
    keep: Dict = {}
    info: Dict = {}
    for k, v in rec.items():
        (info if _ANOMALY_FIELD_RE.search(str(k)) else keep)[k] = v
    return keep, info


def load_round(path: str, bc) -> Optional[Dict[str, dict]]:
    """Non-degraded tier records of one round file, or None when the
    file holds nothing comparable (unreadable, unparseable, no tier
    records, or every tier degraded)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        # stderr: --json consumers must get ONE parseable stdout doc
        print(f"skip {os.path.basename(path)}: unreadable ({e})",
              file=sys.stderr)
        return None
    tiers = bc.extract_tiers(doc)
    live = {name: flatten_attainment(rec)
            for name, rec in tiers.items() if not rec.get("degraded")}
    if not live:
        why = ("every tier degraded (off-TPU fallback)" if tiers
               else "no tier records")
        print(f"skip {os.path.basename(path)}: {why}",
              file=sys.stderr)
        return None
    return live


def main(argv: List[str]) -> int:
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    tol = 0.1
    if "--tol" in argv:
        i = argv.index("--tol")
        if i + 1 >= len(argv):
            print("--tol needs a number", file=sys.stderr)
            return 2
        try:
            tol = float(argv[i + 1])
        except ValueError:
            print(f"--tol: {argv[i + 1]!r} is not a number",
                  file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    if len(argv) > 1:
        print("usage: check_bench_round.py [DIR] [--tol FRAC] [--json]",
              file=sys.stderr)
        return 2
    root = argv[0] if argv else os.path.dirname(_HERE)
    if not os.path.isdir(root):
        print(f"{root}: not a directory", file=sys.stderr)
        return 2

    bc = _load_bench_compare()
    rounds: List[Tuple[str, Dict[str, dict]]] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json")),
                       key=round_key):
        live = load_round(path, bc)
        if live is not None:
            rounds.append((os.path.basename(path), live))
    if len(rounds) < 2:
        note = (f"nothing to compare: {len(rounds)} comparable round "
                "file(s) (need 2) — not a regression")
        if as_json:
            # a --json consumer always gets one parseable document
            print(json.dumps({"compared": [], "regressions": [],
                              "improvements": [], "note": note}))
        else:
            print(note)
        return 0
    (old_name, old_tiers), (new_name, new_tiers) = rounds[-2:]
    old_cmp, new_cmp = {}, {}
    old_info, new_info = {}, {}
    for name, rec in old_tiers.items():
        old_cmp[name], old_info[name] = split_anomaly_fields(rec)
    for name, rec in new_tiers.items():
        new_cmp[name], new_info[name] = split_anomaly_fields(rec)
    summary = bc.compare(old_cmp, new_cmp, tol)
    summary["old"] = old_name
    summary["new"] = new_name
    # informational (non-gating) anomaly/action field diffs across the
    # tiers that were actually compared
    infos: List[Dict] = []
    for tier in summary["compared"]:
        o, n = old_info.get(tier, {}), new_info.get(tier, {})
        for field in sorted(set(o) | set(n)):
            if o.get(field) != n.get(field):
                infos.append({"tier": tier, "field": field,
                              "old": o.get(field),
                              "new": n.get(field)})
    summary["anomaly_fields"] = infos
    if as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(f"comparing {old_name} -> {new_name} (tol {tol:.0%})")
        for e in summary["anomaly_fields"]:
            print(f"info {e['tier']}.{e['field']}: {e['old']} -> "
                  f"{e['new']} (anomaly/action counter — not gated)")
        for e in summary["improvements"]:
            print(f"ok   {e['tier']}.{e['field']}: {e['old']} -> "
                  f"{e['new']} ({e['delta']:+.1%})")
        for e in summary["regressions"]:
            print(f"REGR {e['tier']}.{e['field']}: {e['old']} -> "
                  f"{e['new']} ({e['delta']:+.1%})")
        if not summary["compared"]:
            print("no common non-degraded tiers between the two rounds")
        elif not summary["regressions"]:
            print(f"ok: {len(summary['compared'])} tier(s) compared, "
                  f"no regression beyond {tol:.0%}")
    return 1 if summary["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
