#!/usr/bin/env python3
"""Operator view of the router's fleet (GET /api/v1/fleet).

Renders the front door's per-replica discovery + placement state as a
table: liveness, how the replica entered the fleet (static seed vs
announce), announce age, load, the composed placement weight and WHY
it is what it is (per-factor provenance — anomaly / headroom /
attainment, router/discovery.py), KV-pool headroom and worst-class
attainment.

Exit status (the rc contract, mirroring tools/journal_check.py):
    0  the fleet can serve: at least one replica is admitting
    2  it cannot: router unreachable, malformed document, or no
       admitting replica (empty fleet / all draining / all departed)

Usage:
    python tools/fleetctl.py http://HOST:PORT [--json] [--timeout S]
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import urllib.error
import urllib.request


def _fmt_weight(entry: dict) -> str:
    w = entry.get("weight")
    return "-" if w is None else f"{float(w):.2f}"


def _fmt_provenance(entry: dict) -> str:
    facs = entry.get("weight_provenance") or {}
    if not facs:
        return "-"
    return ",".join(f"{src}={facs[src].get('weight', 0):.2f}"
                    for src in sorted(facs))


def _fmt_headroom(entry: dict) -> str:
    pool = entry.get("pool") or {}
    total, free = pool.get("pages_total"), pool.get("pages_free")
    if not total:
        return "-"
    return f"{free}/{total}"


def _fmt_attainment(entry: dict) -> str:
    att = entry.get("attainment_1m") or {}
    vals = [v for v in att.values() if isinstance(v, (int, float))]
    return "-" if not vals else f"{min(vals):.3f}"


def _fmt_age(entry: dict) -> str:
    age = entry.get("last_announce_age_s")
    if age is None:
        # poll-only replica (static seed that never announced)
        age = entry.get("last_seen_age_s")
        return "-" if age is None else f"{age:.1f}s(poll)"
    return f"{age:.1f}s"


def render(doc: dict, out=sys.stdout) -> int:
    """The testable core: render one fleet document, return the rc."""
    replicas = doc.get("replicas")
    if not isinstance(replicas, dict):
        print("fleetctl: malformed fleet document (no replicas map)",
              file=sys.stderr)
        return 2
    cols = ("REPLICA", "LIVE", "SOURCE", "ADMIT", "LOAD", "WEIGHT",
            "PROVENANCE", "POOL", "ATTAIN-1M", "ANNOUNCE-AGE")
    rows = []
    admitting = 0
    for name in sorted(replicas):
        e = replicas[name]
        if not isinstance(e, dict):
            continue
        admit = bool(e.get("admitting"))
        admitting += admit
        state = ("departing" if e.get("departing")
                 else "draining" if e.get("draining")
                 else "yes" if admit else "no")
        rows.append((name,
                     "up" if e.get("live") else "DOWN",
                     str(e.get("source") or "-"),
                     state,
                     str(e.get("load", "-")),
                     _fmt_weight(e),
                     _fmt_provenance(e),
                     _fmt_headroom(e),
                     _fmt_attainment(e),
                     _fmt_age(e)))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)),
          file=out)
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)),
              file=out)
    note = doc.get("note")
    if note:
        print(f"note: {note}", file=out)
    if not rows:
        print("fleetctl: fleet is empty (no replica has registered "
              "or been seeded)", file=sys.stderr)
        return 2
    if not admitting:
        print("fleetctl: no replica is admitting — the fleet cannot "
              "serve new work", file=sys.stderr)
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleetctl", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("router", help="router base URL (http://host:port)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="dump the raw fleet document instead of the "
                         "table (same rc contract)")
    ap.add_argument("--timeout", type=float, default=5.0)
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    url = args.router.rstrip("/") + "/api/v1/fleet"
    if "://" not in url:
        url = "http://" + url
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            doc = json.loads(resp.read())
    except (OSError, ValueError, urllib.error.URLError) as e:
        print(f"fleetctl: cannot read {url}: {e}", file=sys.stderr)
        return 2
    if not isinstance(doc, dict):
        print("fleetctl: malformed fleet document", file=sys.stderr)
        return 2
    if args.as_json:
        rc = render(doc, out=io.StringIO())
        print(json.dumps(doc, indent=1, sort_keys=True))
        return rc
    return render(doc)


if __name__ == "__main__":
    sys.exit(main())
