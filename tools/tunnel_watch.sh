#!/bin/bash
# Tunnel watcher: probe the TPU backend every 5 minutes; the moment it
# answers, run the full bench suite and save the output. Exits after a
# successful bench run (or keeps probing forever until killed).
#
# Output: /root/repo/BENCH_WATCH.log (probe history)
#         /root/repo/BENCH_WATCH_RESULT.txt (bench stdout when tunnel was up)
cd /root/repo
LOG=BENCH_WATCH.log
echo "watcher start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if timeout 150 python -c "import jax; d=jax.devices(); assert d; print(d)" >> "$LOG" 2>&1; then
    echo "TUNNEL UP $(date -u +%FT%TZ) — running bench" >> "$LOG"
    timeout 5400 python bench.py > BENCH_WATCH_RESULT.txt 2> BENCH_WATCH_RESULT.err
    rc=$?
    echo "bench rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
    if [ $rc -eq 0 ] && grep -q '"value"' BENCH_WATCH_RESULT.txt && ! grep -q '"error"' BENCH_WATCH_RESULT.txt; then
      echo "BENCH SUCCESS $(date -u +%FT%TZ)" >> "$LOG"
      exit 0
    fi
    # tunnel answered the probe but bench failed/partial — keep looping,
    # a later attempt may do better (partial results are preserved with
    # a timestamp suffix so a failed retry can't clobber them)
    cp BENCH_WATCH_RESULT.txt "BENCH_WATCH_RESULT.$(date -u +%H%M%S).txt" 2>/dev/null
  else
    echo "probe fail $(date -u +%FT%TZ)" >> "$LOG"
  fi
  sleep 300
done
