#!/usr/bin/env python3
"""Offline verifier for the write-ahead request journal (--journal).

Replays a journal file through the SAME reconstruction the server uses
at startup (cake_tpu/serve/journal.replay_state — one implementation,
so the checker can never drift from the recovery semantics) and
reports, per rid: admitted / emitted-token / retired state, plus
whatever the replay flags — orphaned emits, cumulative-count gaps,
duplicate admits, emits after retire, mid-file corruption.

A torn FINAL line is the expected signature of a killed writer
(tolerated, like obs/jsonl.read_jsonl, and like recovery itself);
mid-file corruption is a real finding.

Exit status (the rc contract, mirroring tools/bench_compare.py):
    0  journal replays cleanly (a torn tail alone is still rc 0)
    1  findings: the journal replays, but something is inconsistent
    2  unusable input (missing/unreadable file, bad usage)

Usage:
    python tools/journal_check.py JOURNAL [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

# absolute repo root so the tool works from any cwd (the
# engine_profile.py precedent — no sys.path.insert(0, ".") hack)
REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))


def check(path: str, as_json: bool = False, out=sys.stdout) -> int:
    """The testable core: read + replay + report. Returns the rc."""
    from cake_tpu.serve.journal import read_records, replay_state

    if not os.path.exists(path):
        print(f"journal_check: no such file: {path}", file=sys.stderr)
        return 2
    try:
        records, corrupt, torn = read_records(path)
    except OSError as e:
        print(f"journal_check: cannot read {path}: {e}",
              file=sys.stderr)
        return 2
    recs, findings, header = replay_state(records)
    if corrupt:
        findings = [f"{corrupt} corrupt mid-file line(s) skipped"] \
            + findings
    requests = []
    for r in recs:
        requests.append({
            "rid": r["rid"],
            "prompt_tokens": len(r.get("prompt_ids") or ()),
            "emitted_tokens": (len(r.get("replayed") or ())
                               + len(r.get("out_tokens") or ())),
            "emit_records": r.get("emits", 0),
            "remaining": r.get("remaining"),
            "retired": bool(r.get("finished")),
            "status": r.get("status",
                            "in_flight" if not r.get("finished")
                            else "retired"),
            "priority": r.get("priority"),
            "idempotency_key": r.get("idempotency_key"),
            "error": r.get("error"),
        })
    resumable = sum(1 for q in requests
                    if not q["retired"] and not q["error"]
                    and (q["remaining"] or 0) > 0)
    rc = 1 if findings else 0
    doc = {
        "path": path,
        "records": len(records),
        "corrupt_lines": corrupt,
        "torn_tail": torn,
        "version": (header or {}).get("v"),
        "requests": requests,
        "resumable": resumable,
        "findings": findings,
        "rc": rc,
    }
    if as_json:
        print(json.dumps(doc), file=out)
        return rc
    print(f"journal: {path}", file=out)
    print(f"  {len(records)} record(s), {corrupt} corrupt line(s), "
          f"torn tail: {torn}", file=out)
    for q in requests:
        print(f"  rid {q['rid']}: {q['prompt_tokens']} prompt + "
              f"{q['emitted_tokens']} emitted tokens in "
              f"{q['emit_records']} batch(es), "
              f"{q['status']}"
              + (f" [{q['error']}]" if q["error"] else "")
              + (f" key={q['idempotency_key']}"
                 if q["idempotency_key"] else ""),
              file=out)
    print(f"  {resumable} request(s) would resume", file=out)
    if findings:
        print("FINDINGS:", file=out)
        for f in findings:
            print(f"  - {f}", file=out)
        return rc
    print("JOURNAL OK" + (" (torn tail tolerated)" if torn else ""),
          file=out)
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Replay a --journal file offline and report "
                    "per-request state + inconsistencies")
    p.add_argument("journal", help="journal file path")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON document")
    try:
        args = p.parse_args(argv)
    except SystemExit:
        return 2
    return check(args.journal, as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
