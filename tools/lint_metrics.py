#!/usr/bin/env python3
"""Validate a Prometheus text exposition (the /api/v1/metrics contract).

Checks, per tools-as-tests policy (tests/test_metrics_lint.py runs this
against the live registry output in tier-1, so a malformed metric can
never ship):

  * every sample line parses as ``name{labels} value``;
  * metric and label names match the Prometheus charsets;
  * every sample's family has a preceding ``# TYPE`` line, and at most
    one TYPE per family;
  * label values are properly quoted/escaped;
  * histogram families expose ``_bucket`` series with monotonically
    non-decreasing cumulative counts in increasing ``le`` order, ending
    at ``le="+Inf"``, plus ``_sum`` and ``_count`` with
    ``_count == +Inf bucket``;
  * counter samples are finite and non-negative;
  * label cardinality is bounded: no family may expose more than
    ``--series-cap`` live series (default 64; histograms count one
    series per distinct label set, not per bucket) — an unbounded
    label (a rid, a raw URL, a user id) grows the scrape without limit
    and this catches it before production does;
  * ``host``-labeled (federated, obs/federation.py) and
    ``replica``-labeled (the router's announce listener,
    router/discovery.py) families may carry
    at most ``--host-cap`` distinct host values (default 64, matching
    the collector's max_hosts default): the host dimension is bounded
    by TOPOLOGY size, not traffic — more values means something is
    inventing host names;
  * ``rid``-valued labels are banned outright, whatever the count:
    request identity belongs on the event bus / request traces
    (obs/events.py, obs/tracing.py), never on a metric series — and
    so are ``trace``/``trace_id`` labels (x-cake-trace ids are one
    value per request: the identical unbounded-cardinality footgun;
    they ride events and hop records instead).

Additionally, telemetry metric families (``cake_step_*``,
``cake_steps_*``, ``cake_jit_*``, ``cake_device_*``, the paged
prefix-sharing ``cake_prefix_*``, and the mixed continuous-batching
``cake_mixed_*``) must carry real help text (not just
an echoed name) and appear in the README metrics table — pass
``--readme README.md`` to enforce it (the tier-1 hook in
tests/test_metrics_lint.py does, so an undocumented telemetry metric
fails the fast lane).

Usage:
    python tools/lint_metrics.py FILE          # or '-' for stdin
    python tools/lint_metrics.py FILE --readme README.md
    python tools/lint_metrics.py FILE --series-cap 128
    python tools/lint_metrics.py FILE --host-cap 32
    python tools/lint_metrics.py --url http://HOST:PORT/api/v1/metrics

Exit status 0 = clean, 1 = violations (printed one per line).
"""

from __future__ import annotations

import math
import re
import sys
from typing import Dict, List, Tuple

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)$")
LABEL_PAIR_RE = re.compile(
    r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$')
VALID_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")

# telemetry families that MUST be documented (help text + README
# metrics table row) — the obs/steps.py surface, the paged
# prefix-sharing families (serve/engine.py cake_prefix_*), the SLO
# scheduling families (cake_tpu/sched: preemption / shed / per-class
# TTFT), the KV tiering + transfer families (cake_tpu/kv: quantized
# pool bytes, host spill tier, disaggregated page shipments —
# cake_kv_ship_* / cake_kv_adopt_*), and the fault-injection /
# crash-recovery families (cake_tpu/faults + serve/engine recovery:
# injections, recovery outcomes + latency, poison quarantines)
DOCUMENTED_PREFIXES = ("cake_step_", "cake_steps_", "cake_jit_",
                       "cake_device_", "cake_prefix_", "cake_sched_",
                       "cake_shed_", "cake_preemptions_", "cake_mixed_",
                       "cake_kv_", "cake_fault_",
                       "cake_engine_recoveries_",
                       "cake_engine_recovery_", "cake_poison_",
                       "cake_requests_", "cake_heartbeat_",
                       "cake_autotune_",
                       # goodput-first observability (obs/events.py +
                       # obs/slo.py): the event bus + SLO attainment /
                       # goodput families
                       "cake_slo_", "cake_goodput_", "cake_events_",
                       # fleet observability (serve/control.py wire
                       # metrics + obs/federation.py telemetry
                       # federation + /api/v1/fleet gauges)
                       "cake_control_", "cake_telemetry_",
                       "cake_fleet_",
                       # durable serving (serve/journal.py): the
                       # write-ahead request journal's append/fsync/
                       # replay families
                       "cake_journal_",
                       # front-door router (cake_tpu/router): routed
                       # requests, affinity hits/misses, sheds,
                       # failovers, replica-state gauge, proxy TTFT,
                       # traced hop latency
                       "cake_router_",
                       # fleet discovery at the front door
                       # (router/discovery.py): announce frames /
                       # departures per replica plus the fleet-size /
                       # composed-weight / staleness gauges. Already
                       # inside cake_router_, listed explicitly so the
                       # discovery surface stays documented even if
                       # the umbrella prefix is ever narrowed.
                       "cake_router_fleet_", "cake_router_announce_",
                       # online regression sentinel (obs/sentinel.py):
                       # per-kind anomaly firings + active gauge —
                       # cake_anomaly_ also covers the closed-loop
                       # action counter (obs/actions.py,
                       # cake_anomaly_actions_total)
                       "cake_anomaly_",
                       # black-box postmortem bundles (obs/actions.py
                       # PostmortemSink): bundles written per trigger
                       # + best-effort write failures
                       "cake_postmortem_",
                       # paged speculative decoding (cake_tpu/spec):
                       # acceptance / tokens-per-round EMAs, round
                       # counter, degrade actions
                       "cake_spec_")

# label names that may NEVER appear on a metric series, whatever the
# live count: per-request identity makes cardinality proportional to
# traffic — it belongs on the event bus / request traces instead.
# Trace ids (x-cake-trace, ISSUE 15) are the same footgun with a
# different spelling: one value per request, unbounded cardinality —
# they ride events and hop/trace records, never a label.
BANNED_LABELS = ("rid", "trace", "trace_id")

# default live-series cap per family (histograms count one series per
# distinct label set, not per le bucket)
DEFAULT_SERIES_CAP = 64

# distinct `host` label values per family (telemetry federation adds a
# host dimension to remote families — obs/federation.py): bounded by
# TOPOLOGY size, not traffic. The default matches the collector's own
# max_hosts default (TelemetryCollector max_hosts=64) so a fleet the
# collector accepts never false-fails the lint; a family whose host
# values exceed it means something is inventing host names (or the
# collector's guard was bypassed). Raise --host-cap alongside
# max_hosts on larger topologies.
DEFAULT_HOST_CAP = 64


def _split_labels(raw: str) -> List[Tuple[str, str]]:
    """Split a label body on unescaped commas; raises ValueError."""
    parts: List[str] = []
    i, cur, in_str = 0, "", False
    while i < len(raw):
        ch = raw[i]
        if ch == "\\" and in_str:
            cur += raw[i:i + 2]
            i += 2
            continue
        if ch == '"':
            in_str = not in_str
        if ch == "," and not in_str:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
        i += 1
    if in_str:
        raise ValueError("unterminated label value")
    if cur:
        parts.append(cur)
    pairs: List[Tuple[str, str]] = []
    for part in parts:
        m = LABEL_PAIR_RE.match(part)
        if m is None:
            raise ValueError(f"bad label pair {part!r}")
        pairs.append((m.group("k"), m.group("v")))
    return pairs


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def _family_of(name: str) -> str:
    for suf in HIST_SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def lint(text: str,
         series_cap: int = DEFAULT_SERIES_CAP,
         host_cap: int = DEFAULT_HOST_CAP) -> List[str]:
    """Return a list of human-readable violations (empty = clean)."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, int] = {}
    # family -> labelkey (labels minus le) -> [(le, cum_count)]
    buckets: Dict[str, Dict[Tuple, List[Tuple[float, float]]]] = {}
    sums: Dict[str, Dict[Tuple, float]] = {}
    counts: Dict[str, Dict[Tuple, float]] = {}
    seen_families: List[str] = []
    # family -> distinct label sets (minus le) — the live-series count
    # behind the cardinality cap
    live_series: Dict[str, set] = {}
    # family -> distinct `host`/`replica` label values (federated
    # families must stay topology-sized; the router's announce
    # listener re-labels federated series `replica` —
    # router/discovery.py — so both spellings share the cap)
    host_values: Dict[str, set] = {}

    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                errors.append(f"line {ln}: malformed TYPE line")
                continue
            _, _, name, typ = parts
            if not NAME_RE.match(name):
                errors.append(f"line {ln}: invalid metric name {name!r}")
            if typ not in VALID_TYPES:
                errors.append(f"line {ln}: invalid type {typ!r}")
            if name in types:
                errors.append(
                    f"line {ln}: duplicate TYPE for {name!r}")
            types[name] = typ
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                errors.append(f"line {ln}: malformed HELP line")
                continue
            name = parts[2]
            if name in helps:
                errors.append(f"line {ln}: duplicate HELP for {name!r}")
            helps[name] = ln
            if name in types:
                errors.append(
                    f"line {ln}: HELP for {name!r} after its TYPE "
                    "(HELP must come first)")
            continue
        if line.startswith("#"):
            continue  # comments are legal

        m = SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {ln}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        fam = _family_of(name)
        try:
            pairs = _split_labels(m.group("labels") or "")
        except ValueError as e:
            errors.append(f"line {ln}: {e}")
            continue
        for k, _v in pairs:
            if not LABEL_RE.match(k) or k.startswith("__"):
                errors.append(f"line {ln}: invalid label name {k!r}")
            elif k in BANNED_LABELS:
                errors.append(
                    f"line {ln}: banned label {k!r} on {name!r} — "
                    "per-request identity belongs on the event bus / "
                    "request traces, never a metric series")
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            errors.append(
                f"line {ln}: unparseable value {m.group('value')!r}")
            continue

        typ = types.get(fam) or types.get(name)
        if typ is None:
            errors.append(
                f"line {ln}: sample {name!r} has no preceding # TYPE")
            continue
        if fam not in seen_families:
            seen_families.append(fam)
        live_series.setdefault(fam, set()).add(
            tuple(sorted((k, v) for k, v in pairs if k != "le")))
        for k, v in pairs:
            if k in ("host", "replica"):
                host_values.setdefault(fam, set()).add(v)

        if typ == "counter":
            if not (value >= 0):
                errors.append(
                    f"line {ln}: counter {name!r} is negative/NaN")
        if typ == "histogram":
            key = tuple(sorted((k, v) for k, v in pairs if k != "le"))
            if name.endswith("_bucket"):
                le = dict(pairs).get("le")
                if le is None:
                    errors.append(
                        f"line {ln}: bucket sample without le label")
                    continue
                buckets.setdefault(fam, {}).setdefault(key, []).append(
                    (_parse_value(le), value))
            elif name.endswith("_sum"):
                sums.setdefault(fam, {})[key] = value
            elif name.endswith("_count"):
                counts.setdefault(fam, {})[key] = value
            else:
                errors.append(
                    f"line {ln}: histogram sample {name!r} is not "
                    "_bucket/_sum/_count")

    for fam, typ in types.items():
        if typ != "histogram":
            continue
        for key, series in buckets.get(fam, {}).items():
            lbl = dict(key)
            les = [le for le, _ in series]
            if les != sorted(les):
                errors.append(
                    f"{fam}{lbl}: bucket le values not increasing")
            if not les or les[-1] != math.inf:
                errors.append(
                    f"{fam}{lbl}: bucket series does not end at +Inf")
            cums = [c for _, c in series]
            if any(b < a for a, b in zip(cums, cums[1:])):
                errors.append(
                    f"{fam}{lbl}: cumulative bucket counts decrease")
            if key not in sums.get(fam, {}):
                errors.append(f"{fam}{lbl}: missing _sum")
            cnt = counts.get(fam, {}).get(key)
            if cnt is None:
                errors.append(f"{fam}{lbl}: missing _count")
            elif cums and cnt != cums[-1]:
                errors.append(
                    f"{fam}{lbl}: _count {cnt} != +Inf bucket "
                    f"{cums[-1]}")
        if fam not in buckets and (fam in sums or fam in counts):
            # a family with zero samples is legal (no children yet);
            # _sum/_count without buckets is not
            errors.append(f"{fam}: histogram with no _bucket samples")

    if series_cap and series_cap > 0:
        for fam, sets in sorted(live_series.items()):
            if len(sets) > series_cap:
                errors.append(
                    f"{fam}: {len(sets)} live series exceeds the "
                    f"label-cardinality cap {series_cap} — an "
                    "unbounded label value set; aggregate it or move "
                    "the identity to the event bus")
    if host_cap and host_cap > 0:
        for fam, vals in sorted(host_values.items()):
            if len(vals) > host_cap:
                errors.append(
                    f"{fam}: {len(vals)} distinct host label values "
                    f"(host/replica) exceeds the topology-size cap "
                    f"{host_cap} — federated families carry one value "
                    "per fleet host; something is inventing host names")
    return errors


def lint_readme_coverage(text: str, readme_text: str,
                         prefixes=DOCUMENTED_PREFIXES) -> List[str]:
    """Documentation lint for the step-telemetry families: every
    ``# TYPE`` family matching `prefixes` must (a) have a HELP line
    whose text is more than the echoed metric name — the registry
    defaults help to the name, so an undocumented registration is
    detectable — and (b) appear verbatim somewhere in the README (the
    metrics table). Returns human-readable violations (empty = clean).
    """
    errors: List[str] = []
    helps: Dict[str, str] = {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
    for line in text.splitlines():
        if not line.startswith("# TYPE "):
            continue
        parts = line.split()
        if len(parts) != 4:
            continue
        name = parts[2]
        if not name.startswith(prefixes):
            continue
        help_text = helps.get(name, "")
        if not help_text or help_text.strip() == name:
            errors.append(
                f"{name}: telemetry metric registered without help "
                "text (pass help= to counter()/gauge()/histogram())")
        if name not in readme_text:
            errors.append(
                f"{name}: telemetry metric missing from the README "
                "metrics table (document every cake_step_*/cake_jit_*/"
                "cake_device_*/cake_prefix_* series)")
    return errors


def main(argv: List[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0 if argv else 1
    readme_path = None
    series_cap = DEFAULT_SERIES_CAP
    host_cap = DEFAULT_HOST_CAP
    for flag in ("--series-cap", "--host-cap"):
        if flag not in argv:
            continue
        i = argv.index(flag)
        if i + 1 >= len(argv):
            print(f"{flag} needs a number", file=sys.stderr)
            return 2
        try:
            val = int(argv[i + 1])
        except ValueError:
            print(f"{flag}: {argv[i + 1]!r} is not an integer",
                  file=sys.stderr)
            return 2
        if flag == "--series-cap":
            series_cap = val
        else:
            host_cap = val
        argv = argv[:i] + argv[i + 2:]
    if "--readme" in argv:
        i = argv.index("--readme")
        if i + 1 >= len(argv):
            print("--readme needs a path", file=sys.stderr)
            return 2
        readme_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if not argv:
        print("--readme/--series-cap need an exposition input too "
              "(FILE, '-', or --url URL)", file=sys.stderr)
        return 2
    if argv[0] == "--url":
        import urllib.request
        text = urllib.request.urlopen(argv[1], timeout=10).read().decode()
    elif argv[0] == "-":
        text = sys.stdin.read()
    else:
        with open(argv[0]) as f:
            text = f.read()
    errors = lint(text, series_cap=series_cap, host_cap=host_cap)
    if readme_path is not None:
        with open(readme_path) as f:
            errors += lint_readme_coverage(text, f.read())
    for e in errors:
        print(e)
    if not errors:
        print("ok: exposition is well-formed")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
