#!/usr/bin/env python3
"""On-chip engine dispatch profiler, driven by the step flight recorder.

Times the pieces the aggregate engine number is made of, to attribute
throughput between device compute and host<->device dispatch latency
(the axon tunnel adds a round-trip per engine dispatch; the batch-1
tier's on-device `lax.scan` loop pays it once, the engine pays it per
step/scan):

  - raw dispatch RTT: a trivial jitted op, timed per round-trip
  - per-kind step timing (prefill / decode / decode_scan) straight from
    the engine's own flight recorder (obs/steps.py) — no hand-timed
    monkeypatching of dispatch internals, so the numbers are exactly
    what GET /api/v1/steps would report for the same run
  - per-step MFU / HBM utilization and jit compile counts
  - decode token accounting: tokens from scans vs single steps

Usage:
    python tools/engine_profile.py [model] [slots] [gen_tokens] [quant]
    python tools/engine_profile.py 8b 16 64 int8 --json

With --json the report is ONE machine-readable JSON line on stdout
(human narration stays on stderr); without it, everything goes to
stderr as before.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial
from pathlib import Path

# resolve the repo root from this file, not the caller's cwd — the old
# sys.path.insert(0, ".") hack broke the tool whenever it was launched
# from anywhere but the repo root
REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402

import bench                                                # noqa: E402
from cake_tpu.models.llama.generator import ByteTokenizer   # noqa: E402
from cake_tpu.obs import metrics as obs_metrics             # noqa: E402
from cake_tpu.ops.sampling import SamplingConfig            # noqa: E402
from cake_tpu.serve.engine import InferenceEngine           # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _measure_rtt(n_rtt: int = 20) -> tuple[float, float]:
    """(blocking RTT, async chained dispatch) of a trivial jitted op."""
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    x = f(x)
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    for _ in range(n_rtt):
        x = f(x)
        jax.block_until_ready(x)
    rtt = (time.perf_counter() - t0) / n_rtt
    t0 = time.perf_counter()
    for _ in range(n_rtt):
        x = f(x)
    jax.block_until_ready(x)
    async_rtt = (time.perf_counter() - t0) / n_rtt
    return rtt, async_rtt


def _jit_compile_counts() -> dict:
    """Current cake_jit_compiles_total{fn} values from the registry."""
    fam = obs_metrics.REGISTRY.get("cake_jit_compiles_total")
    if fam is None:
        return {}
    return {labels[0]: value
            for labels, value in fam.samples().items() if labels}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Engine dispatch profiler over the step flight "
                    "recorder")
    ap.add_argument("model", nargs="?", default="8b",
                    help="model size (8b|3b|1b|tiny; default 8b)")
    ap.add_argument("slots", nargs="?", type=int, default=16)
    ap.add_argument("gen_tokens", nargs="?", type=int, default=64)
    ap.add_argument("quant", nargs="?", default=None,
                    choices=("int8", "int4", "bf16"),
                    help="weight quant; default int8 for 8b, bf16 else")
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--max-seq", type=int, default=512)
    ap.add_argument("--decode-scan", type=int, default=8)
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON summary line on stdout")
    args = ap.parse_args(argv)

    quant_s = args.quant or ("int8" if args.model == "8b" else "bf16")
    quant = False if quant_s == "bf16" else quant_s

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    rtt, async_rtt = _measure_rtt()
    log(f"raw dispatch RTT (tiny jit, block each): {rtt * 1e3:.1f} ms")
    log(f"async chained dispatch (block once): {async_rtt * 1e3:.1f} "
        "ms/op")

    cfg = bench.make_config(args.model)
    init, desc = bench._init_fn(quant)
    log(f"weights: {desc}")
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size),
        max_slots=args.slots, max_seq_len=args.max_seq,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        decode_scan_steps=args.decode_scan,
        # the measured run must fit in the ring (one record per step)
        step_ring=max(4096, args.slots * args.gen_tokens + 64),
    )

    prompt = list(range(3, 3 + args.prompt_len))
    with engine:
        t0 = time.perf_counter()
        warm = engine.submit(prompt, max_new_tokens=32)
        assert warm.wait(timeout=900)
        log(f"warmup: {time.perf_counter() - t0:.1f}s")
        warm_steps = engine.flight.summary()["recorded_steps"]
        base = engine.stats.tokens_generated
        t0 = time.perf_counter()
        handles = [engine.submit(prompt, max_new_tokens=args.gen_tokens)
                   for _ in range(args.slots)]
        assert all(h.wait(timeout=900) for h in handles)
        wall = time.perf_counter() - t0
        toks = engine.stats.tokens_generated - base
        # measured window = everything the recorder saw after warmup;
        # utilization uses the same window (compile steps excluded), so
        # the JSON's mfu agrees with its own per-kind table
        recs = [r for r in engine.flight.dump()
                if r["step"] > warm_steps]
        summary = engine.flight.summary()
        util = engine.flight.utilization(since_step=warm_steps)

    by_kind: dict = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    for kind, rs in sorted(by_kind.items()):
        d = [r["dispatch_s"] for r in rs]
        tot = sum(d)
        log(f"{kind:12s}: {len(rs):4d} steps, total {tot:6.2f}s, "
            f"mean {tot / len(rs) * 1e3:7.1f} ms, "
            f"min {min(d) * 1e3:7.1f} ms, max {max(d) * 1e3:7.1f} ms, "
            f"{sum(r['tokens'] for r in rs)} tokens")
    scan_tokens = sum(r["tokens"] for r in by_kind.get("decode_scan", []))
    single_tokens = sum(r["tokens"] for r in by_kind.get("decode", []))
    log(f"tokens: {toks} ({scan_tokens} scanned, {single_tokens} single)")
    log(f"wall: {wall:.2f}s -> {toks / wall:.1f} tok/s incl. prefill")
    log(f"utilization: mfu {util['mfu']:.4f}, "
        f"hbm_util {util['hbm_util']:.4f}")
    compiles = _jit_compile_counts()
    log(f"jit compiles: {compiles}")
    ttfts = sorted(h.ttft for h in handles)
    p50 = ttfts[len(ttfts) // 2]
    log(f"TTFT p50 {p50 * 1e3:.0f} ms")

    if args.json:
        print(json.dumps({
            "device_kind": dev.device_kind,
            "model": args.model,
            "quant": quant_s,
            "slots": args.slots,
            "gen_tokens": args.gen_tokens,
            "raw_rtt_ms": round(rtt * 1e3, 2),
            "async_rtt_ms": round(async_rtt * 1e3, 2),
            "tokens": toks,
            "tok_s_incl_prefill": round(toks / wall, 2),
            "ttft_p50_ms": round(p50 * 1e3, 1),
            "scan_tokens": scan_tokens,
            "single_tokens": single_tokens,
            "kinds": {
                kind: {
                    "steps": len(rs),
                    "mean_dispatch_ms": round(
                        sum(r["dispatch_s"] for r in rs) / len(rs) * 1e3,
                        2),
                    "tokens": sum(r["tokens"] for r in rs),
                } for kind, rs in sorted(by_kind.items())
            },
            "mfu": util["mfu"],
            "hbm_util": util["hbm_util"],
            "jit_compiles": compiles,
            "flight_summary": summary,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
