"""On-chip engine dispatch profiler.

Times the pieces the aggregate engine number is made of, to attribute
throughput between device compute and host<->device dispatch latency
(the axon tunnel adds a round-trip per engine dispatch; the batch-1
tier's on-device `lax.scan` loop pays it once, the engine pays it per
step/scan):

  - raw dispatch RTT: a trivial jitted op, timed per round-trip
  - per-prefill dispatch time
  - per-scan (K-step) and per-single-step decode dispatch time
  - decode token accounting: how many tokens came from scans vs singles

Usage:  python tools/engine_profile.py [model] [slots] [gen_tokens] \
            [int8|int4|bf16]      # weight quant; default int8 for 8b
"""
from __future__ import annotations

import sys
import time
from functools import partial

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

import bench
from cake_tpu.models.llama.generator import ByteTokenizer
from cake_tpu.ops.sampling import SamplingConfig
from cake_tpu.serve.engine import InferenceEngine


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "8b"
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    gen_tokens = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    quant_s = sys.argv[4] if len(sys.argv) > 4 else (
        "int8" if model == "8b" else "bf16")
    if quant_s not in ("int8", "int4", "bf16"):
        raise SystemExit(f"quant must be int8|int4|bf16, got {quant_s!r}")
    quant = False if quant_s == "bf16" else quant_s

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")

    # --- raw dispatch RTT ---
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    x = f(x)
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    n_rtt = 20
    for _ in range(n_rtt):
        x = f(x)
        jax.block_until_ready(x)
    rtt = (time.perf_counter() - t0) / n_rtt
    log(f"raw dispatch RTT (tiny jit, block each): {rtt * 1e3:.1f} ms")

    # async dispatch depth: issue 20 without blocking, then block once
    t0 = time.perf_counter()
    for _ in range(n_rtt):
        x = f(x)
    jax.block_until_ready(x)
    async_rtt = (time.perf_counter() - t0) / n_rtt
    log(f"async chained dispatch (block once): {async_rtt * 1e3:.1f} ms/op")

    cfg = bench.make_config(model)
    init, desc = bench._init_fn(quant)
    log(f"weights: {desc}")
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size), max_slots=slots,
        max_seq_len=512,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        decode_scan_steps=8,
    )

    # spy on the DISPATCH/FETCH primitives, not the high-level wrappers:
    # single-host multi-step decode routes through _decode_burst (which
    # calls _dispatch_scan_device/_fetch_scan directly), and prefill
    # admission goes through _do_prefill(..., defer=True)
    times = {"prefill": [], "scan_dispatch": [], "scan_fetch": [],
             "single": []}
    counts = {"scan_tokens": 0, "single_tokens": 0}

    orig_prefill = engine._do_prefill
    orig_dispatch = engine._dispatch_scan_device
    orig_fetch = engine._fetch_scan
    orig_dec = engine._do_decode

    def prefill(rid, slot, defer=False):
        t = time.perf_counter()
        r = orig_prefill(rid, slot, defer=defer)
        times["prefill"].append(time.perf_counter() - t)
        return r

    def dispatch(rows, n, n_top, budget, state=None):
        t = time.perf_counter()
        r = orig_dispatch(rows, n, n_top, budget, state=state)
        times["scan_dispatch"].append(time.perf_counter() - t)
        counts["scan_tokens"] += int(sum(budget))
        return r

    def fetch(outs):
        t = time.perf_counter()
        r = orig_fetch(outs)
        times["scan_fetch"].append(time.perf_counter() - t)
        return r

    def dec(plan):
        t = time.perf_counter()
        r = orig_dec(plan)
        times["single"].append(time.perf_counter() - t)
        counts["single_tokens"] += len(plan)
        return r

    engine._do_prefill = prefill
    engine._dispatch_scan_device = dispatch
    engine._fetch_scan = fetch
    engine._do_decode = dec

    prompt = list(range(3, 3 + 64))
    with engine:
        t0 = time.perf_counter()
        warm = engine.submit(prompt, max_new_tokens=32)
        assert warm.wait(timeout=900)
        log(f"warmup: {time.perf_counter() - t0:.1f}s")
        for k in times:
            times[k].clear()
        counts["scan_tokens"] = counts["single_tokens"] = 0
        base = engine.stats.tokens_generated
        t0 = time.perf_counter()
        handles = [engine.submit(prompt, max_new_tokens=gen_tokens)
                   for _ in range(slots)]
        assert all(h.wait(timeout=900) for h in handles)
        wall = time.perf_counter() - t0
        toks = engine.stats.tokens_generated - base

    for k, v in times.items():
        if not v:
            log(f"{k:8s}: 0 dispatches")
            continue
        tot = sum(v)
        log(f"{k:8s}: {len(v):4d} dispatches, total {tot:6.2f}s, "
            f"mean {tot / len(v) * 1e3:7.1f} ms, "
            f"min {min(v) * 1e3:7.1f} ms, max {max(v) * 1e3:7.1f} ms")
    log(f"tokens: {toks} ({counts['scan_tokens']} scanned, "
        f"{counts['single_tokens']} single)")
    log(f"wall: {wall:.2f}s -> {toks / wall:.1f} tok/s incl. prefill")
    ttfts = sorted(h.ttft for h in handles)
    log(f"TTFT p50 {ttfts[len(ttfts) // 2] * 1e3:.0f} ms")


if __name__ == "__main__":
    main()
