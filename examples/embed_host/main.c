/* embed_host — a complete C host application for the cake-tpu embed
 * library, mirroring the reference's iOS worker app shell
 * (cake-ios-worker-app/Cake Worker/ContentView.swift:10-62): the user
 * points it at a base directory holding `model/` and `topology.yml`,
 * picks a model type, and the app runs a cake node inside its own
 * process. Where the SwiftUI app calls the uniffi-exported
 * startWorker(name:modelPath:topologyPath:modelType:), this calls the
 * C ABI's cake_tpu_start_worker — same contract, any language that can
 * speak C (Swift included: declare the three externs below in a
 * bridging header and the Swift body is a direct transliteration).
 *
 * Modes:
 *   embed_host <base_dir>                          # run a node (blocks)
 *   embed_host <base_dir> --type image             # image-model node
 *   embed_host <base_dir> --prompt "..." [--n N]   # one-shot generation
 *
 * Build: `make` here (uses the library built by cake_tpu.native), or see
 * tests/test_embed.py for the exact compile line the CI uses.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

long cake_tpu_version(char *buf, long cap);
long cake_tpu_generate(const char *model_dir, const char *prompt,
                       int sample_len, char *buf, long cap);
int cake_tpu_start_worker(const char *name, const char *model_path,
                          const char *topology_path, const char *model_type,
                          const char *address);
long cake_tpu_last_error(char *buf, long cap);

static void print_last_error(const char *what) {
  char err[2048];
  err[0] = '\0';
  cake_tpu_last_error(err, (long)sizeof err);
  fprintf(stderr, "embed_host: %s failed: %s\n", what, err);
}

int main(int argc, char **argv) {
  const char *base = NULL, *prompt = NULL, *type = "text";
  int sample_len = 16;
  for (int i = 1; i < argc; i++) {
    if (strcmp(argv[i], "--prompt") == 0 && i + 1 < argc) {
      prompt = argv[++i];
    } else if (strcmp(argv[i], "--type") == 0 && i + 1 < argc) {
      type = argv[++i];
    } else if (strcmp(argv[i], "--n") == 0 && i + 1 < argc) {
      sample_len = atoi(argv[++i]);
    } else if (base == NULL) {
      base = argv[i];
    } else {
      fprintf(stderr, "usage: %s <base_dir> [--type text|image] "
                      "[--prompt P [--n N]]\n", argv[0]);
      return 64;
    }
  }
  if (base == NULL) {
    fprintf(stderr, "usage: %s <base_dir> [--type text|image] "
                    "[--prompt P [--n N]]\n", argv[0]);
    return 64;
  }

  char ver[64];
  if (cake_tpu_version(ver, (long)sizeof ver) != 0) {
    print_last_error("version");
    return 1;
  }
  printf("cake-tpu embed host, library v%s\n", ver);

  /* The reference app resolves <picked folder>/model and
   * <picked folder>/topology.yml (ContentView.swift:40-42). */
  char model_path[4096], topology_path[4096];
  snprintf(model_path, sizeof model_path, "%s/model", base);
  snprintf(topology_path, sizeof topology_path, "%s/topology.yml", base);

  if (prompt != NULL) {
    char out[65536];
    printf("[%s] generating %d tokens...\n", model_path, sample_len);
    if (cake_tpu_generate(model_path, prompt, sample_len, out,
                          (long)sizeof out) != 0) {
      print_last_error("generate");
      return 2;
    }
    printf("%s\n", out);
    printf("embed_host: done\n");
    return 0;
  }

  printf("starting %s-model node (model=%s topology=%s)...\n",
         type, model_path, topology_path);
  /* Blocks for the life of the node, like the app's startWorker call. */
  if (cake_tpu_start_worker("embed-host", model_path, topology_path, type,
                            NULL) != 0) {
    print_last_error("start_worker");
    return 3;
  }
  return 0;
}
