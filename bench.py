"""Benchmark: Llama-3 single-chip decode throughput (BASELINE.md config #1).

Prints ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Method mirrors the reference's instrumentation (master.rs:93-121): steady-
state decode tokens/s, excluding compile/warmup. The model is the real
Llama-3-8B architecture (random weights — no checkpoint egress in this
environment; throughput is weight-value independent). The whole
prefill+decode loop runs on-device (`lax.scan`), so the number is chip
throughput, not host dispatch.

vs_baseline: the reference publishes no numbers (BASELINE.md). We compare
against the chip's HBM-bandwidth roofline for **bf16** decode (params_bytes
/ bandwidth), the fundamental limit for batch-1 decode in the reference's
best dtype — so vs_baseline > 1.0 means beating the physical ceiling of
any f16/bf16 implementation on this chip (achievable with int8 weights,
which halve the streamed bytes; the reference has no quantization).

Isolation: every tier runs in a FRESH SUBPROCESS. TPU HBM, the jit
executable cache, and allocator state die with the tier's process, so one
OOM tier cannot poison the next (the round-2 failure mode: all four tiers
reported RESOURCE_EXHAUSTED after the first one filled the chip). The
orchestrator process never imports jax — TPU access is exclusive, and a
parent holding the device would starve the per-tier children.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ORCH_ENV = "CAKE_BENCH_TIER"
PROBE_ENV = "CAKE_BENCH_PROBE"
# A healthy backend answers the probe in ~5-15 s (tunnel handshake +
# device enumeration); 120 s is generous. A hung tunnel (the round-3
# failure: jax.devices() blocks forever) must not cost more than this.
try:
    PROBE_TIMEOUT_S = int(os.environ.get("CAKE_BENCH_PROBE_TIMEOUT", "120"))
except ValueError:
    PROBE_TIMEOUT_S = 120


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# (name, builder kwargs). Order = preference; the first tier that produces
# a number is the headline. int8 8B is the flagship: ~8.5 GiB resident on a
# 16 GiB v5e vs ~15 GiB params alone for bf16 8B.
TIERS = [
    # int8 beats int4 at batch-1 on v5e (80.9 vs 61.3 tok/s): the int4
    # kernel's nibble unpack is VPU-bound and cannot amortize over one
    # row; int4 wins in the batched engine tier below instead
    ("llama3_8b_int8", dict(model="8b", quant="int8", max_seq=1024)),
    ("llama3_8b_int4", dict(model="8b", quant="int4", max_seq=1024)),
    ("llama3_8b", dict(model="8b", quant=False, max_seq=1024)),
    ("llama3_3b-ish", dict(model="3b", quant=False, max_seq=1024)),
    ("llama3_1b-ish", dict(model="1b", quant=False, max_seq=512)),
]

# Engine-path tiers (BASELINE config #5): p50 TTFT + batched decode tok/s
# through the real continuous-batching engine — the API serving path — with
# the reference master.rs:93-121 timing semantics (compile excluded via a
# warmup request). Merged into the headline JSON as extra keys.
ENGINE_TIERS = [
    # 16 slots measured as the v5e throughput sweet spot: 408 tok/s agg
    # vs 215 at 8 slots and 151 at 32 (32-slot cache + weights thrash HBM)
    ("engine_8b_int8", dict(model="8b", quant=True, max_seq=512, slots=16)),
    ("engine_1b", dict(model="1b", quant=False, max_seq=512, slots=16)),
    # speculation INSIDE the engine (spec_round_batched: all slots per round):
    # the spec tier merged into the engine tier — acceptance + batched
    # tok/s with concurrent speculating streams. Random weights make
    # the measured acceptance a FLOOR (see SPEC_TIERS note).
    ("engine_spec_8b_draft1b", dict(model="8b", quant=True, max_seq=512,
                                    slots=8, draft="1b", gamma=4)),
]

# Peak-throughput tier: 32 slots doubles tokens per weight-stream pass.
# The old dense-cache engine thrashed here (151 tok/s round-3) because
# per-dispatch host overhead scaled with slot count; the burst engine
# measures 1229 tok/s at 32 slots vs 819 at 16 (same chip, same day).
# Kept separate from the headline 16-slot tier: TTFT p50 roughly doubles
# with the admission wave, so 16 is the balanced default, 32 the
# throughput configuration.
ENGINE_PEAK_TIERS = [
    ("engine_8b_int8_b32", dict(model="8b", quant=True, max_seq=512,
                                slots=32)),
]

# SD tier (BASELINE config #4 analog on one chip): per-denoise-step
# latency — the metric the reference itself logs (sd.rs:469, 506-507) —
# plus the 20-step txt2img wall time. Merged into the headline JSON as
# extra keys; random-init weights (latency is weight-value independent).
SD_TIERS = [
    ("sd15_txt2img", dict(version="v1-5", height=512, width=512)),
]

# Speculative-decoding tier (BASELINE batch-1 latency axis): acceptance
# rate + end-to-end tok/s vs the target-only generator, through the real
# SpeculativeGenerator. Random weights (no checkpoint egress here) make
# the draft disagree with the target far more than a distilled draft
# would, so the measured acceptance is a FLOOR and the speedup typically
# < 1 on random weights; on real checkpoints the same tier reports the
# real acceptance/speedup (instrumentation parity: the mechanism and
# measurement are what this tier pins down).
SPEC_TIERS = [
    # int8 TARGET (bf16 8B + draft would blow the 16 GiB v5e HBM:
    # ~15 + 2.5 GiB); the draft stays bf16
    ("spec_8b_draft1b", dict(target="8b", draft="1b", max_seq=1024,
                             gamma=4, quant="int8")),
]

# Paged speculative decoding tiers (bench.py --spec-paged): spec as a
# row KIND of the paged engine (cake_tpu/spec) — the tier pins greedy
# spec-paged output token-identical to plain greedy paged decode, with
# acceptance > 0 and > 1 token emitted per round. draft_seed=0 shares
# the target's init (a self-draft), making acceptance deterministically
# full: the tier verifies the round/paging MECHANICS; the dense --spec
# tier owns the random-weight acceptance-floor measurement.
SPEC_PAGED_TIERS = {
    "spec_paged_1b": dict(model="1b", quant=False, max_seq=512,
                          slots=4, kv_pages=64, kv_page_size=64,
                          prompt_len=64, gen_tokens=48, draft="1b",
                          draft_seed=0, gamma=3),
}

# Paged-decode microbench tiers (bench.py --paged-attn fold|pallas):
# aggregate decode tok/s through a --kv-pages engine, isolating the
# paged-attention kernel choice — the fold-vs-pallas delta is the
# number the ragged_paged_attention kernel exists for. One tier per
# impl so the two paths are measured in identical fresh subprocesses.
PAGED_TIERS = {
    # 64 pages x 128 tokens == the dense 16-slot x 512 cache budget
    # (~1 GiB bf16 at 8B), so the fold/pallas delta is attention cost,
    # not a capacity change
    "paged_8b_int8_fold": dict(model="8b", quant="int8", max_seq=512,
                               slots=16, kv_pages=64, kv_page_size=128,
                               paged_attn="fold"),
    "paged_8b_int8_pallas": dict(model="8b", quant="int8", max_seq=512,
                                 slots=16, kv_pages=64,
                                 kv_page_size=128, paged_attn="pallas"),
}

# Paged prefix-sharing tiers (bench.py --paged-prefix): N streams share
# a 1k-token system prompt through a --kv-pages engine — the tier
# measures the page-granular prefix-sharing win on BOTH axes: TTFT
# (suffix-only prefill vs whole-prompt prefill, same engine) and pool
# capacity (pages_shared = prefix pages the pool did not have to spend
# per slot). One engine, two measured phases (unshared first, then
# register + shared), each phase warmed so compiles stay out of TTFT.
PAGED_PREFIX_TIERS = {
    # 1024-token prefix = 8 x 128-token pages; 8 streams would cost 64
    # prefix pages unshared, 8 shared — the pool is sized so BOTH
    # phases fit, making the delta pure sharing, not admission stalls
    "paged_prefix_8b_int8": dict(model="8b", quant="int8", max_seq=2048,
                                 slots=8, kv_pages=96, kv_page_size=128,
                                 paged_attn="pallas", prefix_len=1024,
                                 suffix_len=64, gen_tokens=16),
}

# Token-level continuous batching tiers (bench.py --mixed): the same
# interleaved-admission load served twice through one paged engine
# config — --mixed-batch off (phase-split prefill-then-decode loop)
# then on (one mixed ragged step, decode rows + prefill-chunk rows in
# the same launch) — reporting aggregate tok/s, flight-recorder step
# MFU, and TTFT p50/p99 of the mid-decode arrivals. The number this
# tier exists for: with mixed batching on, step MFU rises and arrival
# TTFT p99 falls under the same offered load, because admissions stop
# pausing decode and prefill stops running at batch-1 occupancy.
MIXED_TIERS = {
    "mixed_8b_int8": dict(model="8b", quant="int8", max_seq=512,
                          slots=8, kv_pages=64, kv_page_size=128,
                          paged_attn="pallas", prompt_len=256,
                          prefill_chunk=128, base_gen=128, wave_n=8,
                          wave_gen=16, stagger_s=0.05),
}

# KV tiering tiers (bench.py --kv-tier): the same offered load at f32
# vs int8 vs int4 KV, each phase's page pool sized to the SAME byte
# budget — int8 pages + per-page scales cost ~1/4 the bytes and
# nibble-packed int4 pages ~1/8, so the identical budget holds ~4x /
# ~8x the pages and the pool admits more concurrent streams. Each
# phase also exercises the host tier: a registered prefix goes cold,
# the oversubscribed wave spills it for admission pages, and a final
# prefix-matching request restores it.
KV_TIER_TIERS = {
    # 16 f32 pages x 128 tokens at 8B is ~512 MiB of pool budget; the
    # same budget holds ~64 int8 / ~128 int4 pages. 24 streams of 2
    # pages each oversubscribe every phase, so f32 caps at ~7 resident
    # streams (prefix spilled) while int8/int4 reach the 16-slot cap.
    "kvtier_8b": dict(model="8b", quant="int8", max_seq=512, slots=16,
                      pool_bytes=16 * 2 * 32 * 128 * 8 * 128 * 4,
                      kv_page_size=128, paged_attn="pallas",
                      prompt_len=128, gen_tokens=32, prefix_tokens=256,
                      host_pages=8, wave=24),
}

# Disaggregated prefill/decode tiers (bench.py --disagg): the same
# offered load served colocated and then split across a prefill engine
# + decode engine pair wired over loopback (cake_tpu/kv/transfer.py),
# at f32 and int8 KV. The contracts this tier exists for: the
# disaggregated greedy streams are TOKEN-IDENTICAL to colocated at f32
# KV, pages actually ship (pages_shipped > 0), and an int8 shipment
# moves ~4x fewer bytes than f32 for the same prefix (the
# serving-economics reason to quantize the transfer unit).
DISAGG_TIERS = {
    "disagg_8b_int8": dict(model="8b", quant="int8", max_seq=1024,
                           slots=8, kv_pages=512, kv_page_size=128,
                           paged_attn="pallas", prompt_len=512,
                           gen_tokens=64, wave=12),
}

# SLO scheduling tiers (bench.py --slo): a mixed-priority saturation
# run through a --priority-classes engine, measured TWICE — preemption
# off then on, same offered load — reporting per-class TTFT p50/p99
# and the preemption count. The number this tier exists for: with
# preemption on, interactive-class p99 TTFT must sit strictly below
# the preemption-off phase (batch slots are reclaimed instead of
# head-of-line-blocking the interactive arrivals).
SLO_TIERS = {
    "slo_8b_int8": dict(model="8b", quant="int8", max_seq=512, slots=4,
                        prompt_len=128, prefill_chunk=128,
                        batch_gen=128, inter_n=8, inter_gen=8,
                        standard_n=2, standard_gen=16, stagger_s=0.25),
}

# Crash-resilience tiers (bench.py --chaos): the same offered load
# served clean and then under a seeded --fault-plan — two transient
# crashes injected mid-decode plus one poison request whose prefill
# keeps failing (match_len keys the rule to its unique prompt length).
# The contract this tier exists for: the injected transient crashes
# cost ZERO requests (everything in flight recovers via the
# fold-tokens-into-prompt resubmit), the poison request alone is
# quarantined, and recovery latency stays bounded (reported p50/p99).
CHAOS_TIERS = {
    # nth= decode-call indices land the two crashes mid-wave (the
    # 4-token warmup consumes the first ~4 decode calls); the poison
    # prompt is 96 tokens — shorter than every wave prompt, so no
    # folded resubmit prefill can ever collide with its match_len
    "chaos_8b_int8": dict(model="8b", quant="int8", max_seq=512,
                          slots=4, prompt_len=128, prefill_chunk=128,
                          gen_tokens=64, wave=6, poison_len=96,
                          fault_plan=("seed=11"
                                      ";engine.decode:nth=20:transient"
                                      ";engine.decode:nth=48:transient"
                                      ";engine.prefill:always:transient"
                                      ":match_len=96:times=3")),
}

# Restart tiers (bench.py --restart): the durable-serving crash drill
# (serve/journal.py). Phase 1 runs the offered load uninterrupted for
# the token oracle; phase 2 re-execs this file as a CHILD serving the
# same load with --journal armed and a fault-plan `abort` staged
# mid-decode (os._exit — a true kill -9, no flushes beyond what hit
# the OS); phase 3 replays the child's journal into a fresh engine and
# measures RTO (recovery wall time: replay + resubmit + finish). The
# numbers this tier exists for: requests lost MUST be 0, and the
# recovered greedy streams must be token-identical to the
# uninterrupted run at f32 KV.
RESTART_TIERS = {
    # abort_step lands mid-decode of the wave (the 4-token warmup
    # consumes ~5 steps; the wave's prefills + early decodes follow)
    "restart_8b_int8": dict(model="8b", quant="int8", max_seq=512,
                            slots=4, prompt_len=128, prefill_chunk=128,
                            gen_tokens=64, wave=6, abort_step=30,
                            journal_fsync="batch", cache_f32=True),
}

# Autotune tiers (bench.py --autotune): one mid-run offered-load shift
# served twice — pinned at the low-load config, then with the online
# autotuner armed (--autotune auto semantics: a two-regime policy whose
# boundary the load shift crosses) — reporting per-phase tok/s and
# arrival TTFT p99, the switch/rollback counts, and a greedy
# token-identity flag (the hot switch folds every in-flight stream into
# its prompt, so at f32 KV the autotuned run must emit EXACTLY the
# pinned run's tokens). The number this tier exists for: >= 1
# autonomous switch under the shift with zero streams lost.
AUTOTUNE_TIERS = {
    # low phase fits 8 slots; the burst wants 32 (the BENCH_MEASURED
    # migration) — pool sized so both configs admit everything
    "autotune_8b_int8": dict(model="8b", quant="int8", max_seq=512,
                             kv_pages=96, kv_page_size=128,
                             slots_lo=8, slots_hi=32, prompt_len=128,
                             prefill_chunk=128, lo_n=4, lo_gen=32,
                             lo_stagger_s=0.5, hi_n=24, hi_gen=16,
                             hi_stagger_s=0.01, boundary_rps=4.0,
                             interval_s=0.5, cooldown_s=120.0),
}

# Fleet telemetry federation tiers (bench.py --fleet): the wire cost
# of fleet observability, no model required — a coordinator-side
# TelemetryCollector + one threaded TelemetryExporter posing as a
# follower host, alongside a token-gated control channel exchanging
# seq-stamped ops over localhost. Reports export batches shipped,
# collector ingest lag p50/p99, and control-channel bytes per op. The
# numbers this tier exists for: batches > 0 with finite ingest lag
# (the federation plane works end to end) and a per-op wire cost small
# enough to ignore next to a device step.
FLEET_TIERS = {
    "fleet_wire": dict(ops=400, frames=12, interval_s=0.05,
                       events_per_frame=4, payload_ints=64),
}

ROUTER_TIERS = {
    # 520-char system prompts (~590 rendered-head tokens = 4 x 128-token
    # pages aligned) x 4 tenants x 6 requests over 2 replicas: affinity
    # registers each tenant's prefix ONCE fleet-wide, round-robin once
    # PER replica and still whole-prefills each tenant's first visit to
    # the other replica
    "router_8b_int8": dict(model="8b", quant="int8", max_seq=2048,
                           slots=8, kv_pages=96, kv_page_size=128,
                           n_tenants=4, reqs_per_tenant=6,
                           system_chars=520, user_chars=32,
                           gen_tokens=16, watermark=64),
}

# CPU-runnable smoke tiers (tests/test_bench.py exercises each via
# CAKE_BENCH_TIER=<name>); never part of the real fallback chain.
SMOKE_TIERS = {
    "fleet_tiny": dict(ops=120, frames=6, interval_s=0.05,
                       events_per_frame=3, payload_ints=16),
    # 90-char system prompts render to 149-token heads (ByteTokenizer)
    # = 9 aligned 16-token pages; whole prompts are ~260 tokens, so 384
    # max_seq leaves decode room. The watermark stays high so the
    # phases measure AFFINITY, not spill
    "router_tiny": dict(model="tiny", quant=False, max_seq=384,
                        slots=2, kv_pages=80, kv_page_size=16,
                        n_tenants=2, reqs_per_tenant=4,
                        system_chars=90, user_chars=8, gen_tokens=4,
                        watermark=64),
    # f32 cache so the autotuned phase's greedy streams must come back
    # token-identical to the pinned phase (the hot-switch contract,
    # not bf16 tie-breaks); the 0.01s burst crosses the 5 req/s
    # boundary inside one 0.2s controller interval -> one deterministic
    # lo->hi switch, and the long cooldown forbids a switch-back
    # hi_gen x hi_n must outlast interval_s on a 2-slot engine, or the
    # burst can retire before the controller's next sample sees it
    "autotune_tiny": dict(model="tiny", quant=False, max_seq=128,
                          kv_pages=24, kv_page_size=16, slots_lo=2,
                          slots_hi=4, prompt_len=24, prefill_chunk=8,
                          lo_n=2, lo_gen=8, lo_stagger_s=0.3, hi_n=6,
                          hi_gen=24, hi_stagger_s=0.01,
                          boundary_rps=5.0, interval_s=0.1,
                          cooldown_s=120.0, cache_f32=True),
    # 4 f32 pages of budget -> ~15 int8 / ~31 int4 pages: streams of 2
    # pages each give f32 ~2 resident vs int8 ~7 vs int4 ~15 (the
    # >= 1.8x acceptance bars at BOTH narrowing steps), and the 2-page
    # prefix spills/restores in every phase
    "kvtier_tiny": dict(model="tiny", quant=False, max_seq=128, slots=16,
                        pool_bytes=4 * 2 * 4 * 16 * 2 * 16 * 4,
                        kv_page_size=16, paged_attn="fold",
                        prompt_len=24, gen_tokens=8, prefix_tokens=32,
                        host_pages=6, wave=18),
    # f32-vs-int8 phases are built inside run_disagg_tier itself (the
    # byte-ratio headline needs both pools over the same loopback
    # channel); 4-slot engines + a 4-request wave keep the CPU smoke
    # under a minute while still overlapping shipments in flight.
    # 60-token streams on 16-token pages = 4 shipped pages/request
    "disagg_tiny": dict(model="tiny", quant=False, max_seq=128, slots=4,
                        kv_pages=48, kv_page_size=16, paged_attn="fold",
                        prompt_len=48, gen_tokens=12, wave=4),
    "mixed_tiny": dict(model="tiny", quant=False, max_seq=128, slots=3,
                       kv_pages=24, kv_page_size=16, paged_attn="fold",
                       prompt_len=24, prefill_chunk=8, base_gen=64,
                       wave_n=4, wave_gen=6, stagger_s=0.02),
    "slo_tiny": dict(model="tiny", quant=False, max_seq=128, slots=2,
                     prompt_len=24, prefill_chunk=16, batch_gen=64,
                     inter_n=6, inter_gen=4, standard_n=1,
                     standard_gen=6, stagger_s=0.05),
    # f32 cache so the chaos phase's greedy streams must come back
    # token-identical to the clean phase (the recovery contract, not
    # bf16 tie-breaks); poison_len 11 < prompt_len 16, so no folded
    # resubmit prefill can collide with the poison rule's match_len
    "chaos_tiny": dict(model="tiny", quant=False, max_seq=128, slots=2,
                       prompt_len=16, prefill_chunk=16, gen_tokens=16,
                       wave=4, poison_len=11, cache_f32=True,
                       fault_plan=("seed=11"
                                   ";engine.decode:nth=8:transient"
                                   ";engine.decode:nth=14:transient"
                                   ";engine.prefill:always:transient"
                                   ":match_len=11:times=3")),
    # f32 cache so the replayed streams must come back token-identical
    # to the uninterrupted run (the durability contract, not bf16
    # tie-breaks); abort_step 10 lands mid-decode of the 3-request
    # wave on a 2-slot engine (warmup ~5 steps + prefills)
    "restart_tiny": dict(model="tiny", quant=False, max_seq=128,
                         slots=2, prompt_len=16, prefill_chunk=16,
                         gen_tokens=16, wave=3, abort_step=10,
                         journal_fsync="batch", cache_f32=True),
    "paged_prefix_tiny": dict(model="tiny", quant=False, max_seq=128,
                              slots=2, kv_pages=16, kv_page_size=16,
                              paged_attn="fold", prefix_len=32,
                              suffix_len=8, gen_tokens=4),
    "paged_tiny_fold": dict(model="tiny", quant=False, max_seq=128,
                            slots=2, kv_pages=16, kv_page_size=16,
                            paged_attn="fold", prompt_len=16,
                            gen_tokens=8),
    "paged_tiny_pallas": dict(model="tiny", quant=False, max_seq=128,
                              slots=2, kv_pages=16, kv_page_size=16,
                              paged_attn="pallas", prompt_len=16,
                              gen_tokens=8),
    "tiny": dict(model="tiny", quant=False, max_seq=128,
                 prompt_len=16, gen_tokens=8),
    "tiny_int8": dict(model="tiny", quant="int8", max_seq=128,
                      prompt_len=16, gen_tokens=8),
    "tiny_int4": dict(model="tiny", quant="int4", max_seq=128,
                      prompt_len=16, gen_tokens=8),
    "engine_tiny": dict(model="tiny", quant=False, max_seq=128,
                        slots=2, prompt_len=16, gen_tokens=8),
    "engine_spec_tiny": dict(model="tiny", quant=False, max_seq=256,
                             slots=2, prompt_len=16, gen_tokens=8,
                             draft="tiny", gamma=3),
    "spec_paged_tiny": dict(model="tiny", quant=False, max_seq=256,
                            slots=2, kv_pages=96, kv_page_size=8,
                            prompt_len=16, gen_tokens=24,
                            draft="tiny", draft_seed=0, gamma=3),
    # steps_b - steps_a must dwarf timing noise: with a tiny unet the
    # fixed CLIP/VAE/PNG overhead dominates a 2-step delta
    "sd_tiny": dict(version="tiny", steps_a=2, steps_b=12),
    # chat-template overhead is ~115 tokens; keep headroom
    # int8 target like the production spec_8b_draft1b tier, so the CPU
    # smoke lane keeps exercising the quantized-target verify path
    "spec_tiny": dict(target="tiny", draft="tiny", max_seq=256,
                      gamma=4, prompt_len=8, gen_tokens=24,
                      quant="int8"),
}

def device_bandwidth(kind: str) -> float:
    """HBM bytes/s for a device kind — delegates to the ONE table in
    cake_tpu/obs/steps.py so the analytic rooflines here and the
    flight recorder's measured hbm_util share hardware constants.
    (Imported lazily: only tier children import cake_tpu/jax; the
    orchestrator process never does.)"""
    from cake_tpu.obs.steps import hbm_bps_for
    return hbm_bps_for(kind)


def make_config(model: str):
    from cake_tpu.models.llama.config import LlamaConfig
    if model == "8b":
        return LlamaConfig.llama3_8b()
    if model == "3b":
        return LlamaConfig(
            vocab_size=128256, hidden_size=3072, intermediate_size=8192,
            num_hidden_layers=28, num_attention_heads=24,
            num_key_value_heads=8, rope_theta=500000.0)
    if model == "1b":
        return LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=8192,
            num_hidden_layers=16, num_attention_heads=32,
            num_key_value_heads=8, rope_theta=500000.0)
    if model == "tiny":
        return LlamaConfig.tiny()
    raise ValueError(model)


def param_bytes(params) -> tuple[int, int]:
    """(logical param count, resident bytes) over a maybe-quantized tree."""
    import jax
    from cake_tpu.ops.quant import QTensor, is_groupwise
    n = b = 0
    for leaf in jax.tree.leaves(params, is_leaf=lambda x: isinstance(x, QTensor)):
        if isinstance(leaf, QTensor):
            # packed int4 stores two logical weights per byte
            n += leaf.q.size * (2 if is_groupwise(leaf) else 1)
            b += leaf.q.size * leaf.q.dtype.itemsize
            b += leaf.scale.size * leaf.scale.dtype.itemsize
        else:
            n += leaf.size
            b += leaf.size * leaf.dtype.itemsize
    return n, b


def _settle_decode_stats(engine, base_decode_s: float,
                         deadline_s: float = 2.0) -> None:
    """Wait for the engine thread to land its decode-time accrual.

    The burst decode path (`_decode_burst`) sets a request's done event
    from inside the burst, BEFORE adding the burst's wall time to
    stats.decode_time_s — so a reader woken by handle.wait() can see
    all the tokens but a decode_s delta of exactly 0.0 (the
    engine_tiny 0.0-tok/s tier-1 flake). Poll briefly until the
    accrual lands; the window is sub-millisecond in practice."""
    t0 = time.perf_counter()
    while (engine.stats.decode_time_s <= base_decode_s
           and time.perf_counter() - t0 < deadline_s):
        time.sleep(0.01)
    time.sleep(0.05)    # let any still-in-flight accrual land too


def _synth_prompt(seed: int, prompt_len: int, vocab: int) -> list:
    """Deterministic synthetic prompt shared by the A/B serving tiers."""
    return [(7 * seed + 3 * j) % vocab + 3 for j in range(prompt_len)]


def _pct(xs, q):
    """Nearest-rank percentile over a small latency sample."""
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def _init_fn(quant):
    """quant: False/None = full precision, True/"int8" = int8, "int4"."""
    from functools import partial

    from cake_tpu.models.llama.params import init_params, init_params_quantized
    if not quant:
        return init_params, "bf16"
    bits = 4 if quant == "int4" else 8
    return (partial(init_params_quantized, bits=bits),
            f"int{bits} weight-only")


def run_tier(name: str, model: str, quant, max_seq: int,
             batch_size: int = 1, prompt_len: int = 128,
             gen_tokens: int = 128) -> dict:
    from functools import partial

    import jax
    import numpy as np

    from cake_tpu.models.llama.generator import ByteTokenizer, LlamaGenerator
    from cake_tpu.ops.sampling import SamplingConfig

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    hbm_bps = device_bandwidth(dev.device_kind)

    cfg = make_config(model)
    init, qdesc = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    n_params, resident = param_bytes(params)
    log(f"params: {n_params/1e9:.2f}B logical, {resident/2**30:.1f} GiB "
        f"resident ({qdesc})")

    gen = LlamaGenerator(
        cfg, params, ByteTokenizer(cfg.vocab_size),
        max_seq_len=max_seq, batch_size=batch_size,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    prompt = np.ones((batch_size, prompt_len), np.int32)
    plen = np.full((batch_size,), prompt_len, np.int32)

    t0 = time.perf_counter()
    out = gen.generate_on_device(prompt, plen, gen_tokens)
    log(f"first call (compile+run): {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    out = gen.generate_on_device(prompt, plen, gen_tokens)
    dt = time.perf_counter() - t0
    total = batch_size * gen_tokens
    tok_s = total / dt
    assert out.shape == (batch_size, gen_tokens)

    # bf16 roofline: best-case tok/s for any 2-byte-weight implementation
    bf16_roofline = hbm_bps / (n_params * 2)
    # achieved fraction of *this* config's own bandwidth ceiling
    own_roofline = hbm_bps / resident
    log(f"steady state: {total} tokens in {dt:.2f}s -> {tok_s:.2f} tok/s "
        f"(bf16 roofline {bf16_roofline:.1f}, own roofline {own_roofline:.1f})")
    # utilization (BENCH trajectory finally carries it, not just tok/s):
    # analytic MFU for a batch-B decode = 2 FLOPs per param per token,
    # and hbm_util = achieved fraction of this config's own bandwidth
    # ceiling (= roofline_frac by construction)
    from cake_tpu.obs.steps import peak_flops_for
    peak = peak_flops_for(dev.device_kind)
    mfu = min(1.0, tok_s * 2 * n_params / peak)
    hbm_util = min(1.0, tok_s * resident / hbm_bps)
    return {
        "metric": f"{name}_decode_tok_s_per_chip",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tok_s / bf16_roofline, 3),
        "roofline_frac": round(tok_s / own_roofline, 3),
        "mfu": round(mfu, 6),
        "hbm_util": round(hbm_util, 6),
        "device_kind": dev.device_kind,
    }


def run_engine_tier(name: str, model: str, quant, max_seq: int,
                    slots: int = 8, prompt_len: int = 128,
                    gen_tokens: int = 64, draft: str | None = None,
                    gamma: int = 4) -> dict:
    """p50 TTFT + decode tok/s through InferenceEngine (the API path).

    `slots` concurrent streaming requests share the batched KV cache;
    TTFT includes prefill but not compile (a warmup request triggers the
    prefill-bucket and decode compilations first). draft: run the engine
    in speculative mode (per-slot draft/verify rounds) and report the
    acceptance rate alongside the throughput."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    spec_kw = {}
    if draft is not None:
        d_cfg = make_config(draft)
        d_params = jax.jit(partial(init, d_cfg))(jax.random.PRNGKey(1))
        jax.block_until_ready(d_params)
        spec_kw = dict(draft_params=d_params, draft_config=d_cfg,
                       spec_gamma=gamma)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size), max_slots=slots,
        max_seq_len=max_seq,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        # 8 tokens per host round-trip once all streams are admitted —
        # the dispatch-amortized serving configuration (spec rounds
        # amortize gamma+1 tokens per dispatch instead)
        decode_scan_steps=1 if draft is not None else 8,
        **spec_kw,
    )
    prompt = list(range(3, 3 + prompt_len))
    with engine:
        t0 = time.perf_counter()
        # 32 = 3 full 8-step scans + a <8 single-step tail: compiles BOTH
        # decode programs (a shorter warmup never reaches the scan path —
        # _scan_steps_for falls back to single-step when the remaining
        # budget is under decode_scan_steps — and the scan's compile would
        # then land inside the measured decode_time_s)
        warm = engine.submit(prompt, max_new_tokens=32)
        assert warm.wait(timeout=900), "warmup request timed out"
        log(f"warmup (compile): {time.perf_counter() - t0:.1f}s")
        _settle_decode_stats(engine, 0.0)
        base_tokens = engine.stats.tokens_generated
        base_decode_s = engine.stats.decode_time_s
        # utilization window starts AFTER warmup: compile-inflated step
        # walls must not weight the reported mfu/hbm_util toward zero
        warm_steps = engine.flight.summary()["recorded_steps"]

        handles = [engine.submit(prompt, max_new_tokens=gen_tokens)
                   for _ in range(slots)]
        assert all(h.wait(timeout=900) for h in handles)
        _settle_decode_stats(engine, base_decode_s)
        # each request's FIRST token is emitted by prefill (counted in
        # prefill_time_s, not decode_time_s) — exclude it from the decode
        # numerator so the ratio is tokens-from-decode / decode time
        tokens = engine.stats.tokens_generated - base_tokens - slots
        decode_s = engine.stats.decode_time_s - base_decode_s

    ttfts = sorted(h.ttft for h in handles)
    p50 = ttfts[len(ttfts) // 2]
    tok_s = tokens / decode_s if decode_s > 0 else 0.0
    # decode-side utilization from the step flight recorder (obs/steps:
    # cost_analysis FLOPs/bytes over measured step walls, warmup and
    # compile steps excluded) — 0.0 when no record carried cost info,
    # so the keys always exist for the trajectory parser
    util = engine.flight.utilization(since_step=warm_steps)
    log(f"engine: {tokens} tokens, decode {decode_s:.2f}s -> "
        f"{tok_s:.1f} tok/s aggregate; TTFT p50 {p50 * 1e3:.1f}ms "
        f"({slots} concurrent streams); mfu {util['mfu']:.4f}, "
        f"hbm_util {util['hbm_util']:.4f}")
    out = {
        "metric": f"{name}_ttft_and_throughput",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # merged into the headline line by the orchestrator
        "ttft_p50_ms": round(p50 * 1e3, 1),
        "engine_decode_tok_s": round(tok_s, 2),
        "engine_streams": slots,
        "mfu": util["mfu"],
        "hbm_util": util["hbm_util"],
    }
    if draft is not None:
        out["spec_acceptance"] = round(engine.stats.spec_acceptance, 4)
        out["spec_gamma"] = gamma
        log(f"spec: acceptance {engine.stats.spec_acceptance:.3f} "
            f"(gamma={gamma}, random-weight floor)")
    return out


def run_spec_paged_tier(name: str, model: str, quant, max_seq: int,
                        slots: int, kv_pages: int, kv_page_size: int,
                        prompt_len: int = 16, gen_tokens: int = 24,
                        draft: str = "tiny", draft_seed: int = 0,
                        gamma: int = 3) -> dict:
    """Paged speculative decoding smoke (cake_tpu/spec): the same
    greedy prompts through a plain --kv-pages engine and a --spec-draft
    engine must emit IDENTICAL tokens, with acceptance > 0, more than
    one token per round, and the page pool fully conserved at the end
    (free_pages == n_pages once every stream retired). Failures raise
    — the orchestrator reports the tier failed rather than printing a
    plausible-looking number for a broken mechanism."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    d_cfg = make_config(draft)
    if draft == model and draft_seed == 0 and not quant:
        d_params = params   # self-draft: share the tree, full acceptance
    else:
        d_init, _ = _init_fn(False)   # the draft stays unquantized
        d_params = jax.jit(partial(d_init, d_cfg))(
            jax.random.PRNGKey(draft_seed))
        jax.block_until_ready(d_params)

    prompts = [list(range(3 + i, 3 + i + prompt_len))
               for i in range(slots)]
    common = dict(max_slots=slots, max_seq_len=max_seq,
                  sampling=SamplingConfig(temperature=0.0,
                                          repeat_penalty=1.0),
                  kv_pages=kv_pages, kv_page_size=kv_page_size)

    def drive(spec: bool):
        kw = (dict(spec_draft_params=d_params, spec_draft_config=d_cfg,
                   spec_gamma=gamma) if spec else {})
        eng = InferenceEngine(cfg, params, ByteTokenizer(cfg.vocab_size),
                              **common, **kw)
        with eng:
            t0 = time.perf_counter()
            hs = [eng.submit(p, max_new_tokens=gen_tokens)
                  for p in prompts]
            assert all(h.wait(timeout=900) for h in hs), \
                f"{'spec' if spec else 'plain'} request timed out"
            wall = time.perf_counter() - t0
            outs = [list(h._req.out_tokens) for h in hs]
            stats = eng.stats
            pool = (eng._pager.free_pages, eng._pager.live_pages,
                    eng._pager.n_pages)
        return outs, stats, wall, pool

    plain_out, _stats, plain_wall, _pool = drive(False)
    spec_out, stats, spec_wall, (free, live, n_pages) = drive(True)

    rounds = stats.spec_proposed // max(gamma, 1)
    acceptance = (stats.spec_accepted / stats.spec_proposed
                  if stats.spec_proposed else 0.0)
    tokens_per_round = ((stats.spec_accepted + rounds) / rounds
                        if rounds else 0.0)
    log(f"spec-paged: {rounds} rounds, acceptance {acceptance:.3f}, "
        f"{tokens_per_round:.2f} tok/round; wall {spec_wall:.2f}s vs "
        f"plain {plain_wall:.2f}s; pool free={free} live={live} "
        f"n={n_pages}")
    if plain_out != spec_out:
        raise AssertionError(
            f"greedy spec-paged output diverged from plain paged "
            f"decode: {spec_out} != {plain_out}")
    if not acceptance > 0:
        raise AssertionError("spec-paged acceptance was 0 with a "
                             "self-draft (verify/draft misalignment)")
    if not tokens_per_round > 1:
        raise AssertionError(
            f"spec-paged emitted {tokens_per_round:.2f} <= 1 tokens "
            "per round (speculation paid nothing)")
    if free != n_pages or live != 0:
        raise AssertionError(
            f"page pool not conserved after retirement: free={free} "
            f"live={live} n={n_pages}")
    return {
        "metric": f"{name}_spec_paged_tok_per_round",
        "value": round(tokens_per_round, 3),
        "unit": "tokens/round",
        "vs_baseline": 0.0,
        "spec_acceptance": round(acceptance, 4),
        "spec_rounds": rounds,
        "spec_gamma": gamma,
        "identical_to_plain": True,
        "spec_wall_s": round(spec_wall, 3),
        "plain_wall_s": round(plain_wall, 3),
        "pool_conserved": True,
    }


def run_paged_tier(name: str, model: str, quant, max_seq: int,
                   slots: int, kv_pages: int, kv_page_size: int,
                   paged_attn: str, prompt_len: int = 128,
                   gen_tokens: int = 64) -> dict:
    """Paged-decode microbench: aggregate decode tok/s through a
    --kv-pages InferenceEngine with the given paged-attention impl
    (fold = the XLA reference, pallas = the ragged paged-attention
    kernel). Same warmup/measure discipline as run_engine_tier, so the
    fold-vs-pallas delta is directly comparable per chip."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size), max_slots=slots,
        max_seq_len=max_seq,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        kv_pages=kv_pages, kv_page_size=kv_page_size,
        paged_attn=paged_attn,
        # phase-split on purpose: this microbench isolates the
        # fold-vs-pallas DECODE kernel; the mixed step is benched by
        # run_mixed_tier (bench.py --mixed)
        mixed_batch="off",
    )
    prompt = list(range(3, 3 + prompt_len))
    with engine:
        t0 = time.perf_counter()
        warm = engine.submit(prompt, max_new_tokens=8)
        assert warm.wait(timeout=900), "warmup request timed out"
        log(f"warmup (compile): {time.perf_counter() - t0:.1f}s")
        _settle_decode_stats(engine, 0.0)
        base_tokens = engine.stats.tokens_generated
        base_decode_s = engine.stats.decode_time_s
        warm_steps = engine.flight.summary()["recorded_steps"]

        handles = [engine.submit(prompt, max_new_tokens=gen_tokens)
                   for _ in range(slots)]
        assert all(h.wait(timeout=900) for h in handles)
        _settle_decode_stats(engine, base_decode_s)
        tokens = engine.stats.tokens_generated - base_tokens - slots
        decode_s = engine.stats.decode_time_s - base_decode_s

    tok_s = tokens / decode_s if decode_s > 0 else 0.0
    util = engine.flight.utilization(since_step=warm_steps)
    log(f"paged[{paged_attn}]: {tokens} tokens, decode {decode_s:.2f}s "
        f"-> {tok_s:.1f} tok/s aggregate ({slots} streams, "
        f"{kv_pages} x {kv_page_size}-token pages); "
        f"mfu {util['mfu']:.4f}, hbm_util {util['hbm_util']:.4f}")
    return {
        "metric": f"{name}_paged_decode_tok_s",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "paged_attn": paged_attn,
        "paged_decode_tok_s": round(tok_s, 2),
        "paged_streams": slots,
        "kv_pages": kv_pages,
        "kv_page_size": kv_page_size,
        "mfu": util["mfu"],
        "hbm_util": util["hbm_util"],
        "device_kind": dev.device_kind,
    }


def run_paged_prefix_tier(name: str, model: str, quant, max_seq: int,
                          slots: int, kv_pages: int, kv_page_size: int,
                          paged_attn: str, prefix_len: int,
                          suffix_len: int, gen_tokens: int) -> dict:
    """Page-granular prefix sharing: N streams share a long system
    prompt through one --kv-pages engine. Phase 1 serves them unshared
    (whole-prompt prefill); phase 2 registers the prefix and serves the
    same workload suffix-only with the prefix pages mapped shared.
    Reports TTFT p50 for both phases, whole vs suffix-only prefill
    tok/s, and pages_shared (prefix pages the pool did not re-spend
    per slot). Each phase is warmed with one request so jit compiles
    stay out of the measured TTFT."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    engine = InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size), max_slots=slots,
        max_seq_len=max_seq,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        kv_pages=kv_pages, kv_page_size=kv_page_size,
        paged_attn=paged_attn,
        # phase-split on purpose: the tier's prefill tok/s numbers come
        # from stats.prefill_time_s, which the mixed step folds into
        # one combined launch — the sharing win is measured on the
        # phase path where prefill wall is separable
        mixed_batch="off",
    )
    V = cfg.vocab_size - 4
    prefix = [(7 * i) % V + 3 for i in range(prefix_len)]

    def suffix(stream: int):
        return [(31 * stream + j) % V + 3 for j in range(suffix_len)]

    def phase(tag: str, prefilled: int) -> tuple:
        """Warm once, then serve `slots` concurrent streams; returns
        (ttft_p50_s, prefill_tok_s, prefix_hits_delta). `prefilled` is
        the tokens the engine actually COMPUTES per prompt — the whole
        prompt unshared, only the suffix when the prefix pages are
        mapped shared — so the tok/s numerator matches the work done."""
        t0 = time.perf_counter()
        warm = engine.submit(prefix + suffix(99), max_new_tokens=4)
        assert warm.wait(timeout=900), f"{tag} warmup timed out"
        log(f"{tag} warmup (compile): {time.perf_counter() - t0:.1f}s")
        base_prefill_s = engine.stats.prefill_time_s
        base_hits = engine.stats.prefix_hits
        handles = [engine.submit(prefix + suffix(i),
                                 max_new_tokens=gen_tokens)
                   for i in range(slots)]
        assert all(h.wait(timeout=900) for h in handles)
        prefill_s = engine.stats.prefill_time_s - base_prefill_s
        ttfts = sorted(h.ttft for h in handles)
        p50 = ttfts[len(ttfts) // 2]
        tokens = slots * prefilled
        return (p50, tokens / prefill_s if prefill_s > 0 else 0.0,
                engine.stats.prefix_hits - base_hits)

    with engine:
        p50_full, full_tok_s, _ = phase("unshared",
                                        prefix_len + suffix_len)
        engine.register_prefix(prefix)
        p50_suffix, suffix_tok_s, hits = phase("shared", suffix_len)

    n_pp = prefix_len // kv_page_size
    pages_shared = hits * n_pp
    log(f"prefix sharing[{paged_attn}]: TTFT p50 {p50_suffix*1e3:.1f}ms "
        f"suffix-only vs {p50_full*1e3:.1f}ms whole-prompt; prefill "
        f"{suffix_tok_s:.0f} vs {full_tok_s:.0f} tok/s; {hits} hits x "
        f"{n_pp} prefix pages = {pages_shared} pages shared")
    return {
        "metric": f"{name}_prefix_ttft_p50_ms",
        "value": round(p50_suffix * 1e3, 1),
        "unit": "ms",
        "vs_baseline": 0.0,
        "paged_attn": paged_attn,
        "ttft_p50_shared_ms": round(p50_suffix * 1e3, 1),
        "ttft_p50_unshared_ms": round(p50_full * 1e3, 1),
        "prefill_suffix_tok_s": round(suffix_tok_s, 1),
        "prefill_full_tok_s": round(full_tok_s, 1),
        "pages_shared": pages_shared,
        "prefix_hits": hits,
        "prefix_tokens": prefix_len,
        "kv_pages": kv_pages,
        "kv_page_size": kv_page_size,
        "prefix_streams": slots,
        "device_kind": dev.device_kind,
    }


def run_mixed_tier(name: str, model: str, quant, max_seq: int,
                   slots: int, kv_pages: int, kv_page_size: int,
                   paged_attn: str, prompt_len: int, prefill_chunk: int,
                   base_gen: int, wave_n: int, wave_gen: int,
                   stagger_s: float) -> dict:
    """Token-level continuous batching A/B: slots-1 base streams decode
    while wave_n staggered arrivals admit mid-decode; measured once
    with --mixed-batch off (phase-split loop) and once on (one mixed
    ragged step). Reports aggregate tok/s, flight-recorder step MFU,
    and arrival TTFT p50/p99 for both phases, plus the count of mixed
    steps that carried BOTH row kinds (the no-decode-pause observable
    the test_bench smoke asserts). Each phase warms its jit programs
    first so compiles stay out of the measured load."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)
    pct = _pct

    def phase(mixed: str) -> dict:
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            kv_pages=kv_pages, kv_page_size=kv_page_size,
            paged_attn=paged_attn, prefill_chunk=prefill_chunk,
            mixed_batch=mixed,
        )
        with engine:
            t0 = time.perf_counter()
            warm = engine.submit(prompt(99), max_new_tokens=8)
            assert warm.wait(timeout=900), f"mixed[{mixed}] warmup timed out"
            log(f"mixed[{mixed}] warmup (compile): "
                f"{time.perf_counter() - t0:.1f}s")
            _settle_decode_stats(engine, 0.0)
            warm_steps = engine.flight.summary()["recorded_steps"]
            base_decode_s = engine.stats.decode_time_s
            # slots-1 base streams so one slot stays free: an arrival's
            # chunks must be able to join the next step immediately
            base = [engine.submit(prompt(i), max_new_tokens=base_gen)
                    for i in range(slots - 1)]
            t0 = time.perf_counter()
            while (any(len(h._req.out_tokens) < 2 for h in base)
                   and time.perf_counter() - t0 < 300):
                time.sleep(0.005)
            # snapshot AT the window start: tokens the base streams
            # emitted while saturating must not inflate tokens/wall
            t_load = time.perf_counter()
            base_tokens = engine.stats.tokens_generated
            wave = []
            for i in range(wave_n):
                wave.append(engine.submit(prompt(100 + i),
                                          max_new_tokens=wave_gen))
                time.sleep(stagger_s)
            assert all(h.wait(timeout=900) for h in base + wave), \
                f"mixed[{mixed}] load timed out"
            wall = time.perf_counter() - t_load
            _settle_decode_stats(engine, base_decode_s)
            tokens = engine.stats.tokens_generated - base_tokens
            # include_prefill: the OFF phase does its chunk prefills in
            # dedicated `prefill` steps while the ON phase folds the
            # same FLOPs into `mixed` records — counting both sides'
            # full launches makes the A/B measure occupancy, not which
            # records the aggregate happens to weight
            util = engine.flight.utilization(since_step=warm_steps,
                                             include_prefill=True)
            both = sum(
                1 for r in engine.flight.dump()
                if r["kind"] == "mixed"
                and r.get("rows_decode", 0) > 0
                and r.get("rows_prefill", 0) > 0)
            ttfts = [h.ttft for h in wave]
        return {"tok_s": tokens / wall if wall > 0 else 0.0,
                "mfu": util["mfu"], "hbm_util": util["hbm_util"],
                "ttft_p50": pct(ttfts, 0.5), "ttft_p99": pct(ttfts, 0.99),
                "both_kinds": both}

    off = phase("off")
    on = phase("on")
    log(f"mixed: on {on['tok_s']:.1f} tok/s mfu {on['mfu']:.4f} "
        f"TTFT p99 {on['ttft_p99']*1e3:.1f}ms "
        f"({on['both_kinds']} both-kind mixed steps) vs off "
        f"{off['tok_s']:.1f} tok/s mfu {off['mfu']:.4f} "
        f"TTFT p99 {off['ttft_p99']*1e3:.1f}ms")
    return {
        "metric": f"{name}_mixed_ttft_p99_ms",
        "value": round(on["ttft_p99"] * 1e3, 1),
        "unit": "ms",
        "vs_baseline": 0.0,
        "paged_attn": paged_attn,
        "mixed_streams": slots - 1 + wave_n,
        "mixed_steps_both_kinds": on["both_kinds"],
        "mixed_tok_s_on": round(on["tok_s"], 2),
        "mixed_tok_s_off": round(off["tok_s"], 2),
        "mixed_step_mfu_on": on["mfu"],
        "mixed_step_mfu_off": off["mfu"],
        "mixed_ttft_p50_on_ms": round(on["ttft_p50"] * 1e3, 1),
        "mixed_ttft_p50_off_ms": round(off["ttft_p50"] * 1e3, 1),
        "mixed_ttft_p99_on_ms": round(on["ttft_p99"] * 1e3, 1),
        "mixed_ttft_p99_off_ms": round(off["ttft_p99"] * 1e3, 1),
        "kv_pages": kv_pages,
        "kv_page_size": kv_page_size,
        "device_kind": dev.device_kind,
    }


def run_kv_tier(name: str, model: str, quant, max_seq: int, slots: int,
                pool_bytes: int, kv_page_size: int, paged_attn: str,
                prompt_len: int, gen_tokens: int, prefix_tokens: int,
                host_pages: int, wave: int) -> dict:
    """KV tiering three-way (cake_tpu/kv): the same offered load
    served at f32, int8 and nibble-packed int4 KV, each phase's page
    pool sized to the SAME byte budget (pool_bytes -> pages per dtype
    via the one page_bytes source, so int8 gets ~4x and int4 ~8x the
    pages). Reports max RESIDENT streams per phase (peak
    concurrently-admitted requests — the capacity win quantized pages
    exist for), aggregate decode tok/s, and host-tier spill/restore
    counts (decode-resident parks included): each phase registers a
    shared prefix, oversubscribes the pool so the cold prefix SPILLS
    to the host tier under admission pressure, then sends one
    prefix-matching request so it RESTORES. The headline value stays
    the int8/f32 resident-stream ratio (round-diffable across PRs);
    the int4 columns carry their own ratio key."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from cake_tpu.kv.quantized_pool import page_bytes as kv_page_bytes
    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)
    prefix_ids = _synth_prompt(777, prefix_tokens, V)

    def phase(kv_dtype: str) -> dict:
        # ONE page_bytes source for all three dtypes: the byte budget
        # and the engine's memory_bytes() cannot drift (page_bytes
        # takes the storage NAME for quantized pools — values + scales)
        per_page = kv_page_bytes(
            cfg, kv_page_size,
            kv_dtype if kv_dtype in ("int8", "int4") else jnp.float32)
        pages = max(2, pool_bytes // per_page)
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            kv_pages=pages, kv_page_size=kv_page_size,
            paged_attn=paged_attn, kv_dtype=kv_dtype,
            kv_host_pages=host_pages,
        )
        with engine:
            t0 = time.perf_counter()
            warm = engine.submit(prompt(99), max_new_tokens=4)
            assert warm.wait(timeout=900), \
                f"kv[{kv_dtype}] warmup timed out"
            log(f"kv[{kv_dtype}] warmup (compile): "
                f"{time.perf_counter() - t0:.1f}s ({pages} pages)")
            _settle_decode_stats(engine, 0.0)
            base_tokens = engine.stats.tokens_generated
            base_decode = engine.stats.decode_time_s
            engine.register_prefix(prefix_ids)
            handles = [engine.submit(prompt(i), max_new_tokens=gen_tokens)
                       for i in range(wave)]
            # peak RESIDENT streams: poll slots actually HOLDING pool
            # pages while the oversubscribed wave drains (scheduler
            # .active would transiently count a page-starved admission
            # between its plan and its requeue; _slot_pages entries
            # exist only after a successful page mapping)
            peak = 0
            t0 = time.perf_counter()
            while (any(not h._req.done.is_set() for h in handles)
                   and time.perf_counter() - t0 < 900):
                peak = max(peak, len(engine._slot_pages))
                time.sleep(0.001)
            assert all(h.wait(timeout=60) for h in handles), \
                f"kv[{kv_dtype}] wave timed out"
            # a prefix-matching tail request streams the (by now
            # spilled) prefix back from the host tier
            hp = engine.submit(prefix_ids + prompt(1234)[:8],
                               max_new_tokens=4)
            assert hp.wait(timeout=900), \
                f"kv[{kv_dtype}] prefix-restore request timed out"
            _settle_decode_stats(engine, base_decode)
            tokens = engine.stats.tokens_generated - base_tokens
            decode_s = engine.stats.decode_time_s - base_decode
            out = {
                "streams": peak, "pages": pages,
                "pool_bytes": engine.cache.memory_bytes(),
                "tok_s": tokens / decode_s if decode_s > 0 else 0.0,
                "spills": engine.stats.kv_spills,
                "restores": engine.stats.kv_restores,
                "resident_spills": engine.stats.kv_resident_spills,
            }
        log(f"kv[{kv_dtype}]: {out['streams']} resident streams, "
            f"{out['tok_s']:.1f} tok/s, {out['spills']} spills "
            f"({out['resident_spills']} resident) / "
            f"{out['restores']} restores ({pages} pages, "
            f"{out['pool_bytes'] / 2**20:.1f} MiB pool)")
        return out

    f32 = phase("f32")
    q8 = phase("int8")
    q4 = phase("int4")
    ratio = q8["streams"] / max(1, f32["streams"])
    ratio4 = q4["streams"] / max(1, f32["streams"])
    log(f"kv tiering: int4 {q4['streams']} vs int8 {q8['streams']} vs "
        f"f32 {f32['streams']} resident streams at "
        f"~{pool_bytes / 2**20:.0f} MiB pool budget -> "
        f"{ratio4:.2f}x / {ratio:.2f}x")
    return {
        "metric": f"{name}_kv_resident_streams_ratio",
        "value": round(ratio, 2),
        "unit": "x",
        "vs_baseline": 0.0,
        "paged_attn": paged_attn,
        "kv_pool_budget_bytes": pool_bytes,
        "kv_streams_ratio_int4": round(ratio4, 2),
        "kv_streams_int4": q4["streams"],
        "kv_streams_int8": q8["streams"],
        "kv_streams_f32": f32["streams"],
        "kv_pages_int4": q4["pages"],
        "kv_pages_int8": q8["pages"],
        "kv_pages_f32": f32["pages"],
        "kv_pool_bytes_int4": q4["pool_bytes"],
        "kv_pool_bytes_int8": q8["pool_bytes"],
        "kv_pool_bytes_f32": f32["pool_bytes"],
        "kv_tok_s_int4": round(q4["tok_s"], 2),
        "kv_tok_s_int8": round(q8["tok_s"], 2),
        "kv_tok_s_f32": round(f32["tok_s"], 2),
        "kv_spills_int4": q4["spills"],
        "kv_spills_int8": q8["spills"],
        "kv_spills_f32": f32["spills"],
        "kv_restores_int4": q4["restores"],
        "kv_restores_int8": q8["restores"],
        "kv_restores_f32": f32["restores"],
        "kv_resident_spills_int4": q4["resident_spills"],
        "kv_resident_spills_int8": q8["resident_spills"],
        "kv_resident_spills_f32": f32["resident_spills"],
        "kv_host_pages": host_pages,
        "device_kind": dev.device_kind,
    }


def run_disagg_tier(name: str, model: str, quant, max_seq: int,
                    slots: int, kv_pages: int, kv_page_size: int,
                    paged_attn: str, prompt_len: int, gen_tokens: int,
                    wave: int) -> dict:
    """Disaggregated prefill/decode (cake_tpu/kv/transfer.py): the
    same offered load served three ways — colocated at f32 KV, then
    split across a prefill engine + decode engine pair over loopback
    at f32, then the same split at int8. The decode host is the front
    door in both split phases: submit defers scheduler entry, the
    prefill peer runs the prompt and ships pool pages + the first
    token, and the decode host adopts them token-identically (the f32
    phase ASSERTS identity against colocated — the handoff contract,
    not a throughput estimate). Reports decode tok/s and arrival TTFT
    p50/p99 per phase (disagg TTFT includes the ship round trip),
    pages/bytes shipped, and the headline: the int8/f32 ship-bytes
    ratio for the same prefix — quantized pages cross the wire at the
    pool's storage dtype, so ~4x fewer bytes buy the same decode."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)
    token = "bench-disagg-loopback"

    def build(kv_dtype: str, **disagg_kw) -> InferenceEngine:
        return InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            kv_pages=kv_pages, kv_page_size=kv_page_size,
            paged_attn=paged_attn, kv_dtype=kv_dtype, **disagg_kw)

    def drive(engine: InferenceEngine, label: str) -> dict:
        t0 = time.perf_counter()
        warm = engine.submit(prompt(99), max_new_tokens=4)
        assert warm.wait(timeout=900), f"{label} warmup timed out"
        log(f"{label} warmup (compile): {time.perf_counter() - t0:.1f}s")
        _settle_decode_stats(engine, 0.0)
        base_tokens = engine.stats.tokens_generated
        base_decode = engine.stats.decode_time_s
        handles = [engine.submit(prompt(i), max_new_tokens=gen_tokens)
                   for i in range(wave)]
        assert all(h.wait(timeout=900) for h in handles), \
            f"{label} wave timed out"
        _settle_decode_stats(engine, base_decode)
        ttfts = sorted(h.ttft * 1000.0 for h in handles)
        tokens = engine.stats.tokens_generated - base_tokens
        decode_s = engine.stats.decode_time_s - base_decode
        return {
            "tok_s": tokens / decode_s if decode_s > 0 else 0.0,
            "ttft_p50_ms": _pct(ttfts, 0.50),
            "ttft_p99_ms": _pct(ttfts, 0.99),
            "streams": [h.token_ids for h in handles],
        }

    def colocated() -> dict:
        engine = build("f32")
        with engine:
            out = drive(engine, "colocated[f32]")
        log(f"colocated[f32]: {out['tok_s']:.1f} tok/s, TTFT p50 "
            f"{out['ttft_p50_ms']:.0f}ms p99 {out['ttft_p99_ms']:.0f}ms")
        return out

    def disagg(kv_dtype: str) -> dict:
        # prefill engine binds port 0; the decode engine dials the real
        # port. The channel token rides the engine kwarg (no env var
        # needed in-process), and the long adopt timeout absorbs the
        # peer's first-prefill compile on cold CPU backends
        pre = build(kv_dtype, disagg="prefill",
                    disagg_peer="127.0.0.1:0", disagg_token=token)
        pre.start()
        try:
            dec = build(kv_dtype, disagg="decode",
                        disagg_peer=f"127.0.0.1:{pre._disagg.port}",
                        disagg_token=token, disagg_timeout_s=600.0)
            dec.start()
            try:
                assert dec._disagg._connected.wait(30), \
                    f"disagg[{kv_dtype}] channel never connected"
                out = drive(dec, f"disagg[{kv_dtype}]")
                out.update(
                    pages_shipped=pre._disagg.stats["pages"],
                    ship_bytes=pre._disagg.stats["bytes"],
                    shipments=pre._disagg.stats["shipments"],
                    adopted=dec.stats.kv_adopts,
                    degraded=dec._disagg.stats["degraded"],
                )
            finally:
                dec.stop()
        finally:
            pre.stop()
        log(f"disagg[{kv_dtype}]: {out['tok_s']:.1f} tok/s, TTFT p50 "
            f"{out['ttft_p50_ms']:.0f}ms p99 {out['ttft_p99_ms']:.0f}ms, "
            f"{out['pages_shipped']} pages / {out['ship_bytes']} B "
            f"shipped in {out['shipments']} shipments, "
            f"{out['adopted']} adopted, {out['degraded']} degraded")
        return out

    base = colocated()
    d32 = disagg("f32")
    # the handoff contract: greedy decode-host streams at f32 KV are
    # token-identical to colocated — the shipped pages ARE the prefill
    assert d32["streams"] == base["streams"], \
        "disagg f32 streams diverged from colocated"
    q8 = disagg("int8")
    ratio = (q8["ship_bytes"] / d32["ship_bytes"]
             if d32["ship_bytes"] else 0.0)
    log(f"disagg shipping: int8 {q8['ship_bytes']} B vs f32 "
        f"{d32['ship_bytes']} B for the same prefix -> {ratio:.3f}x")
    return {
        "metric": f"{name}_disagg_ship_bytes_ratio_int8",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": 0.0,
        "paged_attn": paged_attn,
        "disagg_token_identical_f32": d32["streams"] == base["streams"],
        "disagg_pages_shipped_f32": d32["pages_shipped"],
        "disagg_pages_shipped_int8": q8["pages_shipped"],
        "disagg_ship_bytes_f32": d32["ship_bytes"],
        "disagg_ship_bytes_int8": q8["ship_bytes"],
        "disagg_shipments_f32": d32["shipments"],
        "disagg_shipments_int8": q8["shipments"],
        "disagg_adopted_f32": d32["adopted"],
        "disagg_adopted_int8": q8["adopted"],
        "disagg_degraded_f32": d32["degraded"],
        "disagg_degraded_int8": q8["degraded"],
        "disagg_tok_s_colocated_f32": round(base["tok_s"], 2),
        "disagg_tok_s_f32": round(d32["tok_s"], 2),
        "disagg_tok_s_int8": round(q8["tok_s"], 2),
        "disagg_ttft_p50_ms_colocated_f32": round(base["ttft_p50_ms"], 1),
        "disagg_ttft_p50_ms_f32": round(d32["ttft_p50_ms"], 1),
        "disagg_ttft_p50_ms_int8": round(q8["ttft_p50_ms"], 1),
        "disagg_ttft_p99_ms_colocated_f32": round(base["ttft_p99_ms"], 1),
        "disagg_ttft_p99_ms_f32": round(d32["ttft_p99_ms"], 1),
        "disagg_ttft_p99_ms_int8": round(q8["ttft_p99_ms"], 1),
        "device_kind": dev.device_kind,
    }


def run_slo_tier(name: str, model: str, quant, max_seq: int,
                 slots: int, prompt_len: int, prefill_chunk: int,
                 batch_gen: int, inter_n: int, inter_gen: int,
                 standard_n: int, standard_gen: int,
                 stagger_s: float) -> dict:
    """Mixed-priority saturation through the SLO scheduler
    (cake_tpu/sched): fill every slot with batch-class requests, then
    offer a staggered stream of interactive (plus a little standard)
    traffic, and measure per-class TTFT p50/p99 — once with preemption
    OFF (interactive head-of-line-blocks behind decoding batch slots)
    and once ON (batch slots are reclaimed, generated tokens fold into
    their prompts, they re-prefill later). Both phases warm their jit
    programs first; prefill_chunk keeps every prefill — including the
    folded resume prefills, whose lengths vary — on ONE compiled
    window program, so no phase pays a mid-load compile."""
    from functools import partial

    import jax

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.sched import SchedConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)
    pct = _pct

    def phase(preempt: bool) -> dict:
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            prefill_chunk=prefill_chunk,
            priority_classes=True, preemption=preempt,
            # the tier measures steady preemption under sustained
            # interactive load, not the budget backstop — lift it so
            # every interactive arrival can reclaim a slot
            sched_config=SchedConfig(preempt_budget=1_000_000),
        )
        with engine:
            t0 = time.perf_counter()
            warm = engine.submit(prompt(99), max_new_tokens=8,
                                 priority="interactive")
            assert warm.wait(timeout=900), "slo warmup timed out"
            log(f"slo[{'on' if preempt else 'off'}] warmup (compile): "
                f"{time.perf_counter() - t0:.1f}s")
            # goodput accounting baseline AFTER warmup: the phase's
            # goodput/raw tok/s diffs the load window only
            tg0 = engine.stats.tokens_generated
            good0 = engine.slo.goodput_total()
            t_load = time.perf_counter()
            batch = [engine.submit(prompt(i), max_new_tokens=batch_gen,
                                   priority="batch")
                     for i in range(slots)]
            # saturation point: every slot decoding batch work before
            # the interactive stream arrives
            t0 = time.perf_counter()
            while (any(len(h._req.out_tokens) < 2 for h in batch)
                   and time.perf_counter() - t0 < 300):
                time.sleep(0.005)
            inter, std = [], []
            for i in range(inter_n):
                inter.append(engine.submit(
                    prompt(100 + i), max_new_tokens=inter_gen,
                    priority="interactive"))
                if standard_n and i == inter_n // 2:
                    std = [engine.submit(prompt(200 + k),
                                         max_new_tokens=standard_gen,
                                         priority="standard")
                           for k in range(standard_n)]
                time.sleep(stagger_s)
            assert all(h.wait(timeout=900)
                       for h in batch + inter + std), "slo load timed out"
            dt = max(1e-6, time.perf_counter() - t_load)
            return {"preemptions": engine.stats.preemptions,
                    "interactive": [h.ttft for h in inter],
                    "standard": [h.ttft for h in std],
                    "batch": [h.ttft for h in batch],
                    # goodput vs raw throughput (obs/slo.py): tokens
                    # from requests that met their class SLO targets,
                    # over the same wall window — goodput <= raw by
                    # construction; attainment is the 10m window (the
                    # whole phase fits inside it)
                    "tok_s": (engine.stats.tokens_generated - tg0) / dt,
                    "goodput_tok_s":
                        (engine.slo.goodput_total() - good0) / dt,
                    "attainment":
                        engine.slo.attainment_by_class("10m")}

    off = phase(False)
    on = phase(True)
    result = {
        "metric": f"{name}_interactive_ttft_p99_ms",
        "value": 0.0, "unit": "ms", "vs_baseline": 0.0,
        "preemptions_total": on["preemptions"],
        "preemptions_total_off": off["preemptions"],
        "slo_streams": slots + inter_n + standard_n,
        "device_kind": dev.device_kind,
    }
    for cls in ("interactive", "standard", "batch"):
        for tag, ph in (("on", on), ("off", off)):
            xs = ph[cls]
            if xs:
                result[f"{cls}_ttft_p50_{tag}_ms"] = round(
                    pct(xs, 0.5) * 1e3, 1)
                result[f"{cls}_ttft_p99_{tag}_ms"] = round(
                    pct(xs, 0.99) * 1e3, 1)
    for tag, ph in (("on", on), ("off", off)):
        result[f"tok_s_{tag}"] = round(ph["tok_s"], 2)
        result[f"goodput_tok_s_{tag}"] = round(ph["goodput_tok_s"], 2)
        result[f"attainment_{tag}"] = {
            c: round(v, 4) for c, v in sorted(ph["attainment"].items())}
    result["value"] = result["interactive_ttft_p99_on_ms"]
    log(f"slo: interactive TTFT p99 {result['value']:.1f}ms with "
        f"preemption ({on['preemptions']} preemptions) vs "
        f"{result['interactive_ttft_p99_off_ms']:.1f}ms without; "
        f"batch p99 {result.get('batch_ttft_p99_on_ms')}ms on / "
        f"{result.get('batch_ttft_p99_off_ms')}ms off")
    return result


def run_chaos_tier(name: str, model: str, quant, max_seq: int,
                   slots: int, prompt_len: int, prefill_chunk: int,
                   gen_tokens: int, wave: int, fault_plan: str,
                   poison_len: int = 0,
                   cache_f32: bool = False) -> dict:
    """Crash-resilience A/B (cake_tpu/faults + serve/engine recovery):
    the same offered load served clean, then under a seeded transient
    -crash --fault-plan (plus one poison request whose prefill keeps
    failing, when poison_len > 0). Reports recovered / failed /
    quarantined request counts, recovery-latency p50/p99, and whether
    the chaos phase's greedy streams stayed token-identical to the
    clean phase. prefill_chunk keeps the folded resubmit prefills —
    whose lengths vary with how many tokens each victim had generated
    — on ONE compiled window program, so recovery latency measures
    the reset + resubmit loop, not mid-chaos compiles."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine
    from cake_tpu.serve.errors import RecoveryConfig

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)

    def phase(plan) -> dict:
        kw = {"cache_dtype": jnp.float32} if cache_f32 else {}
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            prefill_chunk=prefill_chunk, fault_plan=plan,
            # quick consecutive-reset backoff; a storm cap well above
            # the planned injection count (the tier measures recovery,
            # not the breaker)
            recovery_config=RecoveryConfig(backoff_base_s=0.05,
                                           storm_resets=16), **kw)
        with engine:
            t0 = time.perf_counter()
            warm = engine.submit(prompt(99), max_new_tokens=4)
            assert warm.wait(timeout=900), "chaos warmup timed out"
            log(f"chaos[{'faulty' if plan else 'clean'}] warmup "
                f"(compile): {time.perf_counter() - t0:.1f}s")
            handles = [engine.submit(prompt(i), max_new_tokens=gen_tokens)
                       for i in range(wave)]
            if poison_len:
                handles.append(engine.submit(prompt(7777)[:poison_len],
                                             max_new_tokens=gen_tokens))
            assert all(h.wait(timeout=900) for h in handles), \
                "chaos wave timed out"
            failed = [h for h in handles if h._req.error is not None]
            out = {
                "tokens": [list(h._req.out_tokens)
                           for h in handles[:wave]],
                "failed": len(failed),
                "recoveries": engine.stats.recoveries,
                "recovered": engine.stats.requests_recovered,
                "quarantined": engine.stats.poisoned,
                "injections": (engine._faults.total
                               if engine._faults is not None else 0),
                "recovery_s": list(engine.recovery_seconds),
            }
        log(f"chaos[{'faulty' if plan else 'clean'}]: "
            f"{out['injections']} injections, {out['recoveries']} "
            f"recoveries, {out['recovered']} requests recovered, "
            f"{out['quarantined']} quarantined, {out['failed']} failed")
        return out

    clean = phase(None)
    chaos = phase(fault_plan)
    rec = chaos["recovery_s"]
    result = {
        "metric": f"{name}_recovered_requests",
        "value": chaos["recovered"],
        "unit": "requests",
        "vs_baseline": 0.0,
        "chaos_plan": fault_plan,
        "chaos_injections": chaos["injections"],
        "chaos_recoveries": chaos["recoveries"],
        "chaos_recovered": chaos["recovered"],
        "chaos_quarantined": chaos["quarantined"],
        "chaos_failed": chaos["failed"],
        "chaos_clean_failed": clean["failed"],
        "chaos_tokens_match": chaos["tokens"] == clean["tokens"],
        "device_kind": dev.device_kind,
    }
    if rec:
        result["chaos_recovery_p50_ms"] = round(_pct(rec, 0.5) * 1e3, 1)
        result["chaos_recovery_p99_ms"] = round(_pct(rec, 0.99) * 1e3, 1)
    log(f"chaos: {chaos['recovered']} recovered / "
        f"{chaos['quarantined']} quarantined / {chaos['failed']} failed "
        f"(clean failed {clean['failed']}); tokens_match="
        f"{result['chaos_tokens_match']}, recovery p50/p99 "
        f"{result.get('chaos_recovery_p50_ms')}/"
        f"{result.get('chaos_recovery_p99_ms')}ms")
    return result


RESTART_CHILD_ENV = "CAKE_BENCH_RESTART_CHILD"


def _restart_engine(cfg, params, max_seq, slots, prefill_chunk,
                    cache_f32, journal=None, journal_fsync="batch",
                    fault_plan=None):
    import jax.numpy as jnp

    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine
    kw = {"cache_dtype": jnp.float32} if cache_f32 else {}
    return InferenceEngine(
        cfg, params, ByteTokenizer(cfg.vocab_size),
        max_slots=slots, max_seq_len=max_seq,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        prefill_chunk=prefill_chunk, journal=journal,
        journal_fsync=journal_fsync, fault_plan=fault_plan, **kw)


def _restart_load(engine, prompt, wave: int, gen_tokens: int,
                  wait: bool):
    """The shared offered load: one 4-token warmup (compile + a
    retired journal record), then the wave. wait=False is the doomed
    child — it submits and blocks until the staged abort kills it."""
    warm = engine.submit(prompt(99), max_new_tokens=4)
    assert warm.wait(timeout=900), "restart warmup timed out"
    handles = [engine.submit(prompt(i), max_new_tokens=gen_tokens)
               for i in range(wave)]
    if wait:
        assert all(h.wait(timeout=900) for h in handles), \
            "restart wave timed out"
    else:
        for h in handles:
            h.wait(timeout=900)   # the abort fires first; never returns
    return handles


def restart_child_main() -> None:
    """Child-process entry (CAKE_BENCH_RESTART_CHILD=<json>): serve
    the tier's load with --journal armed and a fault-plan `abort`
    staged at a fixed engine step — the process dies there with
    ABORT_EXIT_CODE, mid-decode, exactly like a kill -9. rc 3 means
    the abort never fired (a tier misconfiguration, not a drill)."""
    from functools import partial

    import jax

    c = json.loads(os.environ[RESTART_CHILD_ENV])
    cfg = make_config(c["model"])
    init, _ = _init_fn(c["quant"])
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=c["prompt_len"], vocab=V)
    engine = _restart_engine(
        cfg, params, c["max_seq"], c["slots"], c["prefill_chunk"],
        c["cache_f32"], journal=c["journal"],
        journal_fsync=c["journal_fsync"],
        fault_plan=f"engine.step:step={c['abort_step']}:abort")
    engine.start()
    _restart_load(engine, prompt, c["wave"], c["gen_tokens"],
                  wait=False)
    sys.exit(3)


def run_restart_tier(name: str, model: str, quant, max_seq: int,
                     slots: int, prompt_len: int, prefill_chunk: int,
                     gen_tokens: int, wave: int, abort_step: int,
                     journal_fsync: str = "batch",
                     cache_f32: bool = False) -> dict:
    """Durable-serving crash drill (serve/journal.py): uninterrupted
    oracle run, then a journaled child killed mid-decode by a
    fault-plan `abort` (os._exit — a staged kill -9), then journal
    replay into a fresh engine. Reports RTO (recovery wall time),
    requests replayed vs LOST (must be 0), and a token-identity flag
    vs the oracle. prefill_chunk keeps the folded replay prefills —
    whose lengths vary with how many tokens each stream had at death —
    on ONE compiled window program."""
    import tempfile
    from functools import partial

    import jax

    from cake_tpu.faults import ABORT_EXIT_CODE
    from cake_tpu.serve import checkpoint as ckpt
    from cake_tpu.serve import journal as jr

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)

    # phase 1: the uninterrupted oracle (also warms this process's jit
    # cache, so phase-3 RTO measures replay, not compiles)
    engine = _restart_engine(cfg, params, max_seq, slots, prefill_chunk,
                             cache_f32)
    with engine:
        handles = _restart_load(engine, prompt, wave, gen_tokens,
                                wait=True)
        oracle = [list(h._req.out_tokens) for h in handles]
        oracle_rids = [h._req.rid for h in handles]
    log(f"restart[oracle]: {wave} streams complete")

    # phase 2: the doomed child — same load, --journal armed, staged
    # abort at a fixed engine step
    jpath = os.path.join(tempfile.mkdtemp(prefix="cake_restart_"),
                         "requests.journal")
    child_cfg = dict(model=model, quant=quant, max_seq=max_seq,
                     slots=slots, prompt_len=prompt_len,
                     prefill_chunk=prefill_chunk,
                     gen_tokens=gen_tokens, wave=wave,
                     abort_step=abort_step, journal=jpath,
                     journal_fsync=journal_fsync, cache_f32=cache_f32)
    t_child = time.perf_counter()
    proc, _line = _spawn_self(RESTART_CHILD_ENV, json.dumps(child_cfg),
                              1500, f"{name}-child")
    if proc is None or proc.returncode != ABORT_EXIT_CODE:
        rc = None if proc is None else proc.returncode
        raise RuntimeError(
            f"restart child did not die by planned abort (rc={rc}, "
            f"want {ABORT_EXIT_CODE})")
    log(f"restart[child]: killed by planned abort in "
        f"{time.perf_counter() - t_child:.1f}s (rc={proc.returncode})")

    # phase 3: replay the journal into a fresh engine and finish
    records, bad, torn = jr.read_records(jpath)
    recs, findings, _hdr = jr.replay_state(records)
    resumable_rids = sorted(r["rid"] for r in recs
                            if ckpt.is_resumable(r))
    finished_at_death = {r["rid"]: list(r["out_tokens"]) for r in recs
                         if r.get("finished")
                         and r.get("status") == "retired"}
    engine2 = _restart_engine(cfg, params, max_seq, slots,
                              prefill_chunk, cache_f32, journal=jpath,
                              journal_fsync=journal_fsync)
    t0 = time.perf_counter()
    with engine2:
        handles2, _finished = jr.recover(engine2)
        assert all(h.wait(timeout=900) for h in handles2), \
            "restart replay wave timed out"
        rto = time.perf_counter() - t0
        by_old_rid = dict(finished_at_death)
        for old_rid, h in zip(resumable_rids, handles2):
            by_old_rid[old_rid] = (list(h._req.replayed_tokens)
                                   + list(h._req.out_tokens))
        replay_s = (engine2._journal.last_replay or {}).get("seconds")
    full = [by_old_rid.get(rid) for rid in oracle_rids]
    lost = sum(1 for t in full if t is None)
    tokens_match = all(t == o for t, o in zip(full, oracle)
                       if t is not None)
    result = {
        "metric": f"{name}_rto_s",
        "value": round(rto, 3),
        "unit": "s",
        "vs_baseline": 0.0,
        "restart_abort_step": abort_step,
        "restart_journal_fsync": journal_fsync,
        "restart_journal_records": len(records),
        "restart_journal_corrupt_lines": bad,
        "restart_journal_torn_tail": torn,
        "restart_journal_findings": len(findings),
        "restart_replayed": len(handles2),
        "restart_finished_before_crash": len(finished_at_death),
        "restart_lost": lost,
        "restart_tokens_match": tokens_match,
        "restart_replay_s": replay_s,
        "device_kind": dev.device_kind,
    }
    log(f"restart: RTO {rto:.3f}s, {len(handles2)} replayed + "
        f"{len(finished_at_death)} finished pre-crash, {lost} lost, "
        f"tokens_match={tokens_match} (journal: {len(records)} "
        f"records, torn_tail={torn})")
    return result


def run_autotune_tier(name: str, model: str, quant, max_seq: int,
                      kv_pages: int, kv_page_size: int, slots_lo: int,
                      slots_hi: int, prompt_len: int,
                      prefill_chunk: int, lo_n: int, lo_gen: int,
                      lo_stagger_s: float, hi_n: int, hi_gen: int,
                      hi_stagger_s: float, boundary_rps: float,
                      interval_s: float, cooldown_s: float,
                      cache_f32: bool = False) -> dict:
    """Online-autotuner A/B (cake_tpu/autotune + engine.reconfigure):
    the same two-phase offered load — a slow trickle, then a burst that
    crosses the policy boundary — served pinned at the low-load config,
    then with --autotune auto semantics armed (a two-regime policy:
    slots_lo below boundary_rps, slots_hi above). Reports per-phase
    tok/s + arrival TTFT p99 for both runs, the autonomous
    switch/rollback counts, whether every stream completed, and whether
    the autotuned run's greedy tokens matched the pinned run's
    (token-identity across the hot switch). prefill_chunk keeps every
    prefill — including the folded post-switch resubmits, whose lengths
    vary — on ONE compiled window program per config."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from cake_tpu.autotune import ControllerConfig, PolicyTable
    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    V = cfg.vocab_size - 4
    prompt = partial(_synth_prompt, prompt_len=prompt_len, vocab=V)

    def cfg_point(slots: int) -> dict:
        return {"slots": slots, "kv_pages": kv_pages,
                "kv_page_size": kv_page_size, "paged_attn": "fold"}

    lo, hi = cfg_point(slots_lo), cfg_point(slots_hi)
    policy = {"version": 1, "regimes": [
        {"max_offered_rps": boundary_rps, "config": lo},
        {"max_offered_rps": None, "config": hi}]}

    def phase(tag: str, engine, handles, n, gen, stagger, base) -> dict:
        st0 = (engine.stats.tokens_generated, time.perf_counter(),
               engine.slo.goodput_total())
        batch = []
        for i in range(n):
            batch.append(engine.submit(prompt(base + i),
                                       max_new_tokens=gen))
            time.sleep(stagger)
        assert all(h.wait(timeout=900) for h in batch), \
            f"autotune {tag} phase timed out"
        dt = time.perf_counter() - st0[1]
        handles.extend(batch)
        ttfts = [h.ttft for h in batch]
        return {"tok_s": (engine.stats.tokens_generated - st0[0]) / dt,
                "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 1),
                # goodput (obs/slo.py): tokens from requests that met
                # their class SLO, same wall window — <= tok_s always
                "goodput_tok_s":
                    (engine.slo.goodput_total() - st0[2]) / dt,
                "attainment":
                    engine.slo.attainment_by_class("10m")}

    def run(autotuned: bool) -> dict:
        kw = {"cache_dtype": jnp.float32} if cache_f32 else {}
        if autotuned:
            kw.update(
                autotune="auto", autotune_policy=policy,
                # hair-trigger controller for a bounded tier: one
                # sample over the boundary proposes the switch, the
                # long cooldown forbids a thrash back, and the guard
                # is disarmed (rollback_frac=0: the tier measures the
                # switch, not the guard — test_autotune covers it)
                autotune_config=ControllerConfig(
                    interval_s=interval_s, window=2, hold=1,
                    cooldown_s=cooldown_s, rollback_window=1,
                    rollback_frac=0.0))
        engine = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots_lo, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0,
                                    repeat_penalty=1.0),
            prefill_chunk=prefill_chunk, kv_pages=kv_pages,
            kv_page_size=kv_page_size, paged_attn="fold", **kw)
        with engine:
            t0 = time.perf_counter()
            warm = engine.submit(prompt(99), max_new_tokens=4)
            assert warm.wait(timeout=900), "autotune warmup timed out"
            log(f"autotune[{'auto' if autotuned else 'pinned'}] warmup "
                f"(compile): {time.perf_counter() - t0:.1f}s")
            handles: list = []
            low = phase("low", engine, handles, lo_n, lo_gen,
                        lo_stagger_s, base=1000)
            high = phase("high", engine, handles, hi_n, hi_gen,
                         hi_stagger_s, base=2000)
            lost = sum(1 for h in handles if h._req.error is not None)
            out = {
                "low": low, "high": high, "lost": lost,
                "switches": engine.stats.config_switches,
                "rollbacks": engine.stats.config_rollbacks,
                "epoch": engine.config_epoch,
                "final_slots": engine.max_slots,
                "tokens": [list(h._req.out_tokens) for h in handles],
            }
        log(f"autotune[{'auto' if autotuned else 'pinned'}]: "
            f"low {low['tok_s']:.1f} tok/s p99 {low['ttft_p99_ms']}ms; "
            f"high {high['tok_s']:.1f} tok/s p99 "
            f"{high['ttft_p99_ms']}ms; {out['switches']} switch(es), "
            f"{out['rollbacks']} rollback(s), {lost} lost, final "
            f"slots {out['final_slots']}")
        return out

    def closed_loop_smoke() -> dict:
        """The ISSUE 16 closed-loop phase: with --sentinel-act armed, a
        clean window records ZERO actions; a seeded recompile storm
        right after the autonomous switch triggers exactly ONE
        anomaly-pinned rollback through the existing reconfigure seam;
        serving recovers on the reverted config. Deterministic: the
        sentinel daemon is parked (interval 3600s) and the smoke drives
        tick() by hand; rollback_window=10_000 keeps the rate verdict
        out of reach so only the anomaly can rule the guard."""
        eng = InferenceEngine(
            cfg, params, ByteTokenizer(cfg.vocab_size),
            max_slots=slots_lo, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0,
                                    repeat_penalty=1.0),
            prefill_chunk=prefill_chunk, kv_pages=kv_pages,
            kv_page_size=kv_page_size, paged_attn="fold",
            autotune="auto",
            autotune_policy={"version": 1, "regimes": [
                {"max_offered_rps": None, "config": hi}]},
            autotune_config=ControllerConfig(
                interval_s=0.05, hold=1, cooldown_s=3600.0,
                rollback_window=10_000),
            sentinel=True, sentinel_interval=3600.0,
            sentinel_act=True)

        def wait(cond, timeout=120.0):
            t0 = time.perf_counter()
            while not cond() and time.perf_counter() - t0 < timeout:
                time.sleep(0.01)
            assert cond(), "closed-loop smoke: condition never held"

        with eng:
            h = eng.submit(prompt(4001), max_new_tokens=4)
            assert h.wait(timeout=900), "closed-loop warmup timed out"
            wait(lambda: eng.config_epoch == 1)
            wait(lambda: eng._autotuner.guard_armed)
            clean_actions = eng._actions.total
            assert clean_actions == 0, eng._actions.history()
            # two over-threshold recompile windows (fire_after=2)
            for _ in range(2):
                for _ in range(4):
                    eng.flight.record("decode", rows=1, tokens=1,
                                      wall_s=0.01, compiled=True)
                eng.sentinel.tick()
            wait(lambda: eng.stats.config_rollbacks == 1)
            assert eng.max_slots == slots_lo, eng.max_slots
            # goodput recovers: a fresh stream completes on the
            # reverted config, and nothing switches again (pin +
            # anomaly hold + cooldown)
            h2 = eng.submit(prompt(4002), max_new_tokens=4)
            assert h2.wait(timeout=900) and h2._req.error is None
            assert eng.config_epoch == 2, eng.config_epoch
            acts = eng._actions.history()
            return {
                "closed_loop_anomaly_clean_actions": int(clean_actions),
                "closed_loop_anomaly_rollbacks":
                    int(eng.stats.config_rollbacks),
                "closed_loop_anomaly_actions_total":
                    int(eng._actions.total),
                "closed_loop_anomaly_last_action":
                    acts[0]["action"] if acts else None,
            }

    pinned = run(False)
    auto = run(True)
    closed = closed_loop_smoke()
    log(f"closed-loop smoke: clean actions "
        f"{closed['closed_loop_anomaly_clean_actions']}, anomaly "
        f"rollbacks {closed['closed_loop_anomaly_rollbacks']} "
        f"(last action {closed['closed_loop_anomaly_last_action']})")
    result = {
        **closed,
        "metric": f"{name}_switches",
        "value": auto["switches"],
        "unit": "switches", "vs_baseline": 0.0,
        "autotune_switches": auto["switches"],
        "autotune_rollbacks": auto["rollbacks"],
        "autotune_final_slots": auto["final_slots"],
        "autotune_streams_lost": auto["lost"] + pinned["lost"],
        "autotune_tokens_match": auto["tokens"] == pinned["tokens"],
        "device_kind": dev.device_kind,
        # observation records the offline fitter ingests as-is
        # (tools/autotune_fit.py --bench THIS_FILE)
        "autotune_observations": [
            {"config": lo, "offered_rps": lo_n * 1.0
             / max(1e-3, lo_n * lo_stagger_s),
             "tok_s": round(auto["low"]["tok_s"], 2)},
            {"config": {**lo, "slots": auto["final_slots"]},
             "offered_rps": hi_n * 1.0
             / max(1e-3, hi_n * hi_stagger_s),
             "tok_s": round(auto["high"]["tok_s"], 2)},
        ],
    }
    for tag, run_out in (("pinned", pinned), ("auto", auto)):
        for ph in ("low", "high"):
            result[f"{ph}_tok_s_{tag}"] = round(
                run_out[ph]["tok_s"], 2)
            result[f"{ph}_ttft_p99_{tag}_ms"] = \
                run_out[ph]["ttft_p99_ms"]
            result[f"{ph}_goodput_tok_s_{tag}"] = round(
                run_out[ph]["goodput_tok_s"], 2)
            result[f"{ph}_attainment_{tag}"] = {
                c: round(v, 4) for c, v in
                sorted(run_out[ph]["attainment"].items())}
    log(f"autotune: {auto['switches']} switch(es) under the load "
        f"shift, tokens_match={result['autotune_tokens_match']}, "
        f"high-phase {result['high_tok_s_auto']} tok/s auto vs "
        f"{result['high_tok_s_pinned']} pinned")
    return result


def run_sd_tier(name: str, version: str, height: int | None = None,
                width: int | None = None, steps_a: int = 20,
                steps_b: int = 40) -> dict:
    """Per-denoise-step latency via two-point differencing: running the
    same prompt at steps_a and steps_b isolates the step cost from the
    fixed CLIP-encode + VAE-decode + PNG overhead, with no timing hooks
    inside the generator (same quantity the reference logs per step,
    sd.rs:469, 506-507)."""
    import jax

    from cake_tpu.args import ImageGenerationArgs, SDVersion
    from cake_tpu.models.sd.clip import init_clip_params
    from cake_tpu.models.sd.config import get_sd_config, tiny_sd_config
    from cake_tpu.models.sd.sd import SDGenerator, SimpleClipTokenizer
    from cake_tpu.models.sd.unet import init_unet_params
    from cake_tpu.models.sd.vae import init_vae_params

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    if version == "tiny":
        cfg = tiny_sd_config()
    else:
        cfg = get_sd_config(SDVersion(version), height=height, width=width)
    params = {
        "clip": init_clip_params(cfg.clip, jax.random.PRNGKey(0)),
        "unet": init_unet_params(cfg.unet, jax.random.PRNGKey(1)),
        "vae": init_vae_params(cfg.vae, jax.random.PRNGKey(2)),
    }
    toks = [SimpleClipTokenizer(cfg.clip.vocab_size)]
    if cfg.clip2 is not None:
        toks.append(SimpleClipTokenizer(cfg.clip2.vocab_size))
    gen = SDGenerator(cfg, params, toks)

    def run(n):
        out = []
        gen.generate_image(
            ImageGenerationArgs(image_prompt="a robot painting a sunset",
                                sd_n_steps=n, sd_num_samples=1, sd_seed=7),
            lambda imgs: out.extend(imgs))
        assert out and out[0][:4] == b"\x89PNG"[:4]

    t0 = time.perf_counter()
    run(steps_a)
    log(f"first image (compile+run, {steps_a} steps): "
        f"{time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    run(steps_a)
    t_a = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(steps_b)
    t_b = time.perf_counter() - t0
    step_ms = (t_b - t_a) / (steps_b - steps_a) * 1e3
    log(f"{steps_a}-step image {t_a:.2f}s, {steps_b}-step {t_b:.2f}s -> "
        f"{step_ms:.1f} ms/denoise-step")
    return {
        "metric": f"{name}_denoise_step",
        "value": round(step_ms, 1),
        "unit": "ms/step",
        "vs_baseline": 0.0,
        "sd_step_ms": round(step_ms, 1),
        "sd_image_s": round(t_a, 2),
        "sd_steps": steps_a,
    }


def run_spec_tier(name: str, target: str, draft: str, max_seq: int,
                  gamma: int = 4, prompt_len: int = 128,
                  gen_tokens: int = 128, quant=False) -> dict:
    """Speculative decoding vs target-only: acceptance rate + tok/s.

    quant applies to the TARGET only (8B bf16 + draft would blow the
    16 GiB v5e HBM: ~15 + 2.5 GiB; int8 target + bf16 draft fits)."""
    from functools import partial

    import jax
    import numpy as np

    from cake_tpu.models.llama.generator import ByteTokenizer, LlamaGenerator
    from cake_tpu.models.llama.params import init_params
    from cake_tpu.models.llama.speculative import SpeculativeGenerator
    from cake_tpu.ops.sampling import SamplingConfig

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    t_cfg, d_cfg = make_config(target), make_config(draft)
    t_init, t_desc = _init_fn(quant)
    log(f"target weights: {t_desc}")
    t_params = jax.jit(partial(t_init, t_cfg))(jax.random.PRNGKey(0))
    d_params = jax.jit(partial(init_params, d_cfg))(jax.random.PRNGKey(1))
    jax.block_until_ready((t_params, d_params))
    sampling = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    tok = ByteTokenizer(t_cfg.vocab_size)
    prompt_txt = "x" * prompt_len

    def run_n_tokens(gen):
        from cake_tpu.models.chat import Message
        gen.reset()
        gen.add_message(Message.user(prompt_txt))
        t0 = time.perf_counter()
        n = 0
        for i in range(gen_tokens):
            t = gen.next_token(i)
            if i == 0:
                t0 = time.perf_counter()  # exclude compile
            else:
                n += 1
            if t.is_end_of_stream:
                break
        dt = time.perf_counter() - t0
        return n / dt if dt > 0 and n else 0.0

    def best_of(gen, runs: int = 2):
        # identical warm discipline for both generators: discard the
        # compile-heavy first run, report the best steady-state run —
        # asymmetric warm-up would tilt the speedup comparison
        run_n_tokens(gen)
        return max(run_n_tokens(gen) for _ in range(runs))

    base_gen = LlamaGenerator(t_cfg, t_params, tok, max_seq_len=max_seq,
                              sampling=sampling)
    base_tps = best_of(base_gen)

    spec = SpeculativeGenerator(t_cfg, t_params, d_cfg, d_params, tok,
                                gamma=gamma, max_seq_len=max_seq,
                                sampling=sampling)
    spec_tps = best_of(spec)
    accept = spec.acceptance_rate
    log(f"speculative: {spec_tps:.1f} tok/s (target-only {base_tps:.1f}), "
        f"acceptance {accept:.2%} over {spec.proposed} proposals")
    return {
        "metric": f"{name}_speculative",
        "value": round(spec_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "spec_tok_s": round(spec_tps, 2),
        "spec_baseline_tok_s": round(base_tps, 2),
        "spec_accept_rate": round(accept, 4),
        "spec_gamma": gamma,
    }


def run_fleet_tier(name: str, ops: int, frames: int, interval_s: float,
                   events_per_frame: int, payload_ints: int) -> dict:
    """Fleet telemetry federation wire smoke (obs/federation.py +
    serve/control.py): coordinator-side collector + one threaded
    exporter posing as host proc1 + a token-gated control channel
    exchanging `ops` seq-stamped ops over localhost. No model — the
    tier measures the telemetry/control plane itself: export batches
    shipped, collector ingest lag p50/p99, control bytes per op, and
    that the drained follower reports zero applied-seq lag."""
    import threading

    from cake_tpu.obs import metrics as m
    from cake_tpu.obs.events import EventBus
    from cake_tpu.obs.federation import (
        TelemetryCollector, TelemetryExporter,
    )
    from cake_tpu.serve.control import ControlClient, ControlServer

    token = "bench-fleet-token"
    server = ControlServer(1, host="127.0.0.1", token=token)
    collector = TelemetryCollector(host="127.0.0.1", token=token,
                                   control=server, local_host="proc0")
    applied = {"seq": 0}

    def follower():
        client = ControlClient(f"127.0.0.1:{server.port}", token=token)
        try:
            while True:
                op = client.recv()
                if op is None:
                    return
                if isinstance(op.get("seq"), int):
                    applied["seq"] = op["seq"]
                if op.get("op") == "stop":
                    return
        finally:
            client.close()

    t = threading.Thread(target=follower, daemon=True)
    t.start()
    server.accept_followers()

    # the "remote host's" telemetry: its own registry + event bus, so
    # the frame content is what a real follower would ship
    remote_reg = m.Registry()
    remote_ops = m.Counter("bench_fleet_remote_ops_total",
                           "ops the bench follower replayed",
                           registry=remote_reg)
    bus = EventBus(capacity=4096, observe_metrics=False)
    exporter = TelemetryExporter(
        f"127.0.0.1:{collector.port}", host="proc1", token=token,
        interval_s=interval_s, registry=remote_reg, events=bus,
        applied_seq=lambda: applied["seq"], start=False)

    tx0 = m.REGISTRY.get("cake_control_bytes_total") \
        .labels(dir="tx").value
    t0 = time.perf_counter()
    payload = list(range(payload_ints))
    for _ in range(ops):
        server.publish({"op": "decode", "rows": payload})
        remote_ops.inc()
    publish_wall = time.perf_counter() - t0
    for f in range(frames):
        for j in range(events_per_frame):
            bus.publish("kv_spill", rid=f * events_per_frame + j,
                        pages=2)
        exporter.flush()
        time.sleep(interval_s)
    server.publish({"op": "stop"})
    t.join(timeout=10)
    assert not t.is_alive(), "bench follower never drained"
    # terminal frame: the drained follower's applied seq reaches the
    # collector, so the fleet view must read lag 0
    assert exporter.flush(), "terminal telemetry flush failed"
    tx_bytes = m.REGISTRY.get("cake_control_bytes_total") \
        .labels(dir="tx").value - tx0

    # ingest runs on the collector's connection thread: wait for every
    # sent frame to land before reading the fleet view
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        fleet = collector.fleet()
        got = fleet["hosts"].get("proc1", {}).get("frames", 0)
        if got >= exporter.frames_sent:
            break
        time.sleep(0.005)
    fleet = collector.fleet()
    view = fleet["hosts"]["proc1"]
    lags = collector.ingest_lags("proc1")
    remote_events = collector.events_for(host="proc1")
    exporter.close(flush=False)
    collector.close()
    server.close()

    result = {
        "metric": f"{name}_export_batches",
        "value": exporter.frames_sent,
        "unit": "frames",
        "vs_baseline": 0.0,
        "fleet_export_batches": exporter.frames_sent,
        "fleet_ingest_frames": view["frames"],
        "fleet_events_shipped": len(remote_events),
        "fleet_control_ops": ops,
        "fleet_control_bytes_per_op": round(tx_bytes / (ops + 1), 1),
        "fleet_publish_us_per_op": round(publish_wall / ops * 1e6, 2),
        "fleet_applied_seq": view["applied_seq"],
        "fleet_lag_ops": view["lag_ops"],
        "fleet_host_live": bool(view["live"]),
        "fleet_clock_offset_ms": round(
            (view["clock_offset_s"] or 0.0) * 1e3, 3),
    }
    if lags:
        result["fleet_ingest_lag_p50_ms"] = round(
            _pct(lags, 0.5) * 1e3, 3)
        result["fleet_ingest_lag_p99_ms"] = round(
            _pct(lags, 0.99) * 1e3, 3)
    log(f"fleet: {result['fleet_export_batches']} batches shipped, "
        f"{result['fleet_events_shipped']} events, ingest lag p50/p99 "
        f"{result.get('fleet_ingest_lag_p50_ms')}/"
        f"{result.get('fleet_ingest_lag_p99_ms')}ms, "
        f"{result['fleet_control_bytes_per_op']} B/op, "
        f"{result['fleet_publish_us_per_op']}us/op publish, lag "
        f"{result['fleet_lag_ops']} after drain")
    return result


def run_router_tier(name: str, model: str, quant, max_seq: int,
                    slots: int, kv_pages: int, kv_page_size: int,
                    n_tenants: int, reqs_per_tenant: int,
                    system_chars: int, user_chars: int,
                    gen_tokens: int, watermark: int) -> dict:
    """Aggregate-goodput A/B over 2 in-process engine replicas behind
    the REAL router front door (cake_tpu/router), same offered load
    with repeated shared system prompts per tenant: phase 1 routes
    round-robin (the strawman — every tenant's prefix registers and
    warms on EVERY replica), phase 2 prefix-affinity (each tenant's
    conversations land on the replica already holding its pages).
    Reports aggregate goodput tok/s, fleet prefix-hit rate, TTFT
    p50/p99 per policy and router failovers (must be 0)."""
    import http.client
    import threading
    from functools import partial

    import jax

    from cake_tpu.api.server import ApiServer, make_handler
    from cake_tpu.args import Args
    from cake_tpu.master import Master
    from cake_tpu.models.llama.generator import ByteTokenizer
    from cake_tpu.obs import metrics as obs_m
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.router import start_router
    from cake_tpu.serve.engine import InferenceEngine
    from http.server import ThreadingHTTPServer

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")
    cfg = make_config(model)
    init, _ = _init_fn(quant)
    params = jax.jit(partial(init, cfg))(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    tok = ByteTokenizer(cfg.vocab_size)

    def tenant_messages(tenant: int, i: int) -> list:
        # one long shared system prompt per tenant + a distinct user
        # turn per request — the population prefix affinity exists for
        sys_txt = f"You are tenant {tenant}'s assistant. " \
            + "policy " * ((system_chars - 40) // 7)
        return [
            {"role": "system", "content": sys_txt[:system_chars]},
            {"role": "user", "content": f"q{i} " + "w" * user_chars},
        ]

    def phase(policy: str) -> dict:
        engines, httpds = [], []
        for _ in range(2):
            eng = InferenceEngine(
                cfg, params, tok, max_slots=slots,
                max_seq_len=max_seq,
                sampling=SamplingConfig(temperature=0.0,
                                        repeat_penalty=1.0),
                kv_pages=kv_pages, kv_page_size=kv_page_size,
                paged_attn="fold", auto_prefix_system=True)
            master = Master(Args(sample_len=gen_tokens),
                            text_generator=None)
            master.llm = object()
            api = ApiServer(master, engine=eng,
                            replica_id=f"bench-{len(engines)}")
            httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                        make_handler(api))
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            api.replica_id = f"127.0.0.1:{httpd.server_address[1]}"
            engines.append(eng)
            httpds.append(httpd)
        replicas = [f"127.0.0.1:{h.server_address[1]}" for h in httpds]
        rhttpd, router = start_router(
            replicas, address="127.0.0.1:0", block=False,
            tokenizer=tok, poll_interval_s=0.05,
            load_watermark=watermark, policy_mode=policy)
        raddr = f"127.0.0.1:{rhttpd.server_address[1]}"
        router.tracker.poll_once()

        # warm each ENGINE directly with a CHAT-shaped request (same
        # bucket + decode shapes as the measured load, so each phase
        # pays its jit compiles here, outside the measured window —
        # engines rebuild per phase, so compiles repeat per phase and
        # would otherwise all land in whichever phase runs first)
        from cake_tpu.models.chat import Message
        warm_msgs = tenant_messages(99, 0)
        for eng in engines:
            h = eng.chat([Message.from_json(m) for m in warm_msgs],
                         max_new_tokens=gen_tokens)
            assert h.wait(timeout=900), "warmup timed out"
        warm_regs = sum(len(e._prefixes) for e in engines)
        warm_done = [e.stats.requests_completed for e in engines]

        f0 = obs_m.REGISTRY.get("cake_router_failovers_total")
        fail0 = sum(f0.samples().values()) if f0 is not None else 0
        ttfts, errors = [], []
        lock = threading.Lock()

        def one(tenant: int, i: int):
            body = json.dumps({
                "messages": tenant_messages(tenant, i),
                "stream": True, "max_tokens": gen_tokens})
            conn = http.client.HTTPConnection(raddr, timeout=900)
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/api/v1/chat/completions",
                             body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                if resp.status != 200:
                    with lock:
                        errors.append(resp.status)
                    resp.read()
                    return
                ttft = None
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:") and ttft is None:
                        ttft = time.perf_counter() - t0
                    if line.strip() == b"data: [DONE]":
                        break
                with lock:
                    ttfts.append(ttft if ttft is not None else -1.0)
            except OSError as e:
                with lock:
                    errors.append(str(e))
            finally:
                conn.close()

        t0 = time.perf_counter()
        threads = []
        # tenant-major launch: one tenant's requests arrive back to
        # back, so the round-robin strawman genuinely alternates each
        # tenant across BOTH replicas (request-major interleaving would
        # accidentally pin tenant i to replica i%2)
        for tenant in range(n_tenants):
            for i in range(reqs_per_tenant):
                t = threading.Thread(target=one, args=(tenant, i))
                t.start()
                threads.append(t)
                time.sleep(0.01)
        for t in threads:
            t.join(timeout=900)
        wall = time.perf_counter() - t0
        n_req = n_tenants * reqs_per_tenant
        # fleet prefix-hit rate: a request "hits" when its tenant's
        # prefix was ALREADY registered on its replica — i.e. requests
        # minus the NEW registrations this load forced. (Per-engine
        # stats.prefix_hits can't tell: a request that just registered
        # its own prefix counts a hit there.) Round-robin re-registers
        # every tenant on every replica; affinity registers each once.
        new_regs = sum(len(e._prefixes) for e in engines) - warm_regs
        hits = n_req - new_regs
        toks = sum(e.stats.tokens_generated for e in engines)
        fail1 = sum(f0.samples().values()) if f0 is not None else 0
        per_replica = [e.stats.requests_completed - w
                       for e, w in zip(engines, warm_done)]
        # trace-sampled hop latencies per replica (ISSUE 15): walk
        # each hop record's span chain pick -> connect -> first_byte
        # (intermediate spans — admitted — pass through)
        hop_pc = {r: [] for r in replicas}
        hop_fb = {r: [] for r in replicas}
        for rec in (router.hops.dump() if router.hops is not None
                    else ()):
            last = None   # (stage, t, replica)
            for sp in rec["spans"]:
                nm, rep = sp["name"], sp.get("replica")
                if nm == "pick":
                    last = ("pick", sp["t"], rep)
                elif nm == "connect" and last is not None \
                        and last[0] == "pick" and last[2] == rep \
                        and rep in hop_pc:
                    hop_pc[rep].append(sp["t"] - last[1])
                    last = ("connect", sp["t"], rep)
                elif nm == "first_byte" and last is not None \
                        and last[0] == "connect" and last[2] == rep \
                        and rep in hop_fb:
                    hop_fb[rep].append(sp["t"] - last[1])
                    last = None

        def _hop_ms(samples, q):
            return [round(_pct(sorted(samples[r]), q) * 1e3, 3)
                    if samples[r] else None for r in replicas]
        rhttpd.shutdown()
        router.close()
        for h in httpds:
            h.shutdown()
        for e in engines:
            e.stop(timeout=30)
        assert not errors, f"router phase {policy} errors: {errors[:4]}"
        good = sorted(t for t in ttfts if t >= 0)
        return {
            "goodput_tok_s": round(
                n_req * gen_tokens / wall, 2) if wall > 0 else 0.0,
            "hit_rate": round(hits / n_req, 4),
            "hits": hits,
            "new_regs": new_regs,
            "requests": n_req,
            "per_replica_completed": per_replica,
            "ttft_p50_ms": round(_pct(good, 0.5) * 1e3, 1)
            if good else None,
            "ttft_p99_ms": round(_pct(good, 0.99) * 1e3, 1)
            if good else None,
            "hop_pick_connect_p50_ms": _hop_ms(hop_pc, 0.5),
            "hop_pick_connect_p99_ms": _hop_ms(hop_pc, 0.99),
            "hop_connect_first_byte_p50_ms": _hop_ms(hop_fb, 0.5),
            "hop_connect_first_byte_p99_ms": _hop_ms(hop_fb, 0.99),
            "failovers": int(fail1 - fail0),
            "tokens": int(toks),
            "wall_s": round(wall, 3),
        }

    rr = phase("round_robin")
    log(f"router[round_robin]: {rr['goodput_tok_s']} tok/s goodput, "
        f"hit rate {rr['hit_rate']}, TTFT p50/p99 "
        f"{rr['ttft_p50_ms']}/{rr['ttft_p99_ms']}ms, per-replica "
        f"{rr['per_replica_completed']}")
    aff = phase("affinity")
    log(f"router[affinity]: {aff['goodput_tok_s']} tok/s goodput, "
        f"hit rate {aff['hit_rate']}, TTFT p50/p99 "
        f"{aff['ttft_p50_ms']}/{aff['ttft_p99_ms']}ms, per-replica "
        f"{aff['per_replica_completed']}, hop pick->connect p50 "
        f"{aff['hop_pick_connect_p50_ms']}ms, connect->first-byte "
        f"p50 {aff['hop_connect_first_byte_p50_ms']}ms")
    sentinel = _router_sentinel_smoke(cfg, params, tok, max_seq,
                                      gen_tokens)
    log(f"sentinel smoke: clean anomalies "
        f"{sentinel['sentinel_clean_anomalies']}, storm fired "
        f"{sentinel['sentinel_storm_anomaly_kinds']} "
        f"(recompiles detected "
        f"{sentinel['sentinel_storm_recompile_anomalies']}, seeded "
        f"degradations {sentinel['sentinel_degradations_injected']})")
    closed = _router_closed_loop_smoke()
    log(f"closed-loop smoke: clean actions "
        f"{closed['router_anomaly_clean_actions']}, de-weights "
        f"{closed['router_anomaly_deweights']}, re-weights "
        f"{closed['router_anomaly_reweights']} (recovered in "
        f"{closed['router_anomaly_recovery_ticks']} tick(s))")
    disc = _router_discovery_smoke(cfg, params, tok, max_seq, slots,
                                   kv_pages, kv_page_size, gen_tokens)
    log(f"discovery smoke: hot-join -> first serve "
        f"{disc['router_disc_join_to_first_serve_ms']}ms, joiner "
        f"served {disc['router_disc_joiner_completed']} (placement "
        f"shift {disc['router_disc_placement_shift']}), hot-switch "
        f"admissions {disc['router_disc_switch_admissions_routed_around']}"
        f" (restored {disc['router_disc_switch_restored']}), "
        f"post-departure admissions "
        f"{disc['router_disc_post_departure_admissions']}")
    return {
        **closed,
        **disc,
        "metric": f"{name}_goodput_tok_s",
        "value": aff["goodput_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,
        "router_replicas": 2,
        "router_requests": aff["requests"],
        "router_goodput_tok_s_affinity": aff["goodput_tok_s"],
        "router_goodput_tok_s_round_robin": rr["goodput_tok_s"],
        "router_hit_rate_affinity": aff["hit_rate"],
        "router_hit_rate_round_robin": rr["hit_rate"],
        "router_new_regs_affinity": aff["new_regs"],
        "router_new_regs_round_robin": rr["new_regs"],
        "router_ttft_p50_ms_affinity": aff["ttft_p50_ms"],
        "router_ttft_p99_ms_affinity": aff["ttft_p99_ms"],
        "router_ttft_p50_ms_round_robin": rr["ttft_p50_ms"],
        "router_ttft_p99_ms_round_robin": rr["ttft_p99_ms"],
        "router_failovers": aff["failovers"] + rr["failovers"],
        "router_per_replica_affinity": aff["per_replica_completed"],
        "router_per_replica_round_robin": rr["per_replica_completed"],
        # per-replica trace-sampled hop latencies (router/tracing.py)
        "router_hop_pick_connect_p50_ms":
            aff["hop_pick_connect_p50_ms"],
        "router_hop_pick_connect_p99_ms":
            aff["hop_pick_connect_p99_ms"],
        "router_hop_connect_first_byte_p50_ms":
            aff["hop_connect_first_byte_p50_ms"],
        "router_hop_connect_first_byte_p99_ms":
            aff["hop_connect_first_byte_p99_ms"],
        **sentinel,
        "device_kind": dev.device_kind,
    }


def _router_closed_loop_smoke() -> dict:
    """The ISSUE 16 closed loop at the router tier, deterministic and
    engine-free: synthetic hop spans drive the REAL RouterServer +
    sentinel + RouterAnomalyActuator (--router-anomaly-weighting). A
    clean balanced fleet records ZERO actions; a 20x per-replica TTFT
    skew de-weights the offender (placement shifts toward the healthy
    replica — the goodput mechanism — while the offender stays
    eligible); balanced windows clear the detector and auto re-weight
    it. Both transitions land in the action history the router serves
    at GET /api/v1/anomalies."""
    from cake_tpu.router.server import RouterServer

    def fetch(addr, timeout=None):
        return {"status": "ok", "queue_depth": 0, "active_requests": 0}

    def drive(hops, tag, n, slow_ttft):
        for i in range(n):
            t = f"cl-{tag}-{i}"
            hops.begin(t)
            hops.attempt(t, "a:1", "hit")
            hops.span(t, "first_byte", replica="a:1", ttft_s=0.05)
            hops.attempt(t, "b:1", "hit")
            hops.span(t, "first_byte", replica="b:1", ttft_s=slow_ttft)

    r = RouterServer(["a:1", "b:1"], poll_interval_s=3600, fetch=fetch,
                     sentinel=True, sentinel_interval_s=3600,
                     anomaly_weighting=True)
    try:
        r.tracker.poll_once()
        # clean phase: balanced fleet, zero anomalies, zero actions
        drive(r.hops, "clean", 6, 0.05)
        assert r.sentinel.tick() == []
        clean_actions = r.actions.total
        assert clean_actions == 0, r.actions.history()
        # replica b degrades 20x for two windows (fire_after=2)
        for i in range(2):
            drive(r.hops, f"storm{i}", 6, 1.0)
            r.sentinel.tick()
        assert r.policy.weights().get("b:1") == 0.25, r.policy.weights()
        # recovery: balanced windows dilute the 30s TTFT window, then
        # clear_after consecutive clean ticks re-weight the replica
        ticks = 0
        while r.policy.weights() and ticks < 12:
            drive(r.hops, f"rec{ticks}", 6, 0.05)
            r.sentinel.tick()
            ticks += 1
        assert r.policy.weights() == {}, r.policy.weights()
        acts = r.anomalies()["actions"]
        applied = [a["action"] for a in acts
                   if a["outcome"] == "applied"]
        assert "deweight" in applied and "reweight" in applied, acts
        return {
            "router_anomaly_clean_actions": int(clean_actions),
            "router_anomaly_deweights": applied.count("deweight"),
            "router_anomaly_reweights": applied.count("reweight"),
            "router_anomaly_recovery_ticks": ticks,
        }
    finally:
        r.close()


def _router_discovery_smoke(cfg, params, tok, max_seq: int, slots: int,
                            kv_pages: int, kv_page_size: int,
                            gen_tokens: int) -> dict:
    """The ISSUE 18 discovery/placement smoke over the REAL announce
    wire: the router starts with an EMPTY static fleet; replica A
    self-registers and takes the whole offered load; replica B
    hot-joins mid-load (the tier reports the latency from B's
    announcer starting to B's first routed completion); a config
    hot-switch on B — ``switch_in_flight`` shipped over the announce
    channel by the replica itself — routes NEW admissions around B
    and restores it the moment the flag clears; B's explicit departure
    notice then drains-then-forgets with ZERO post-notice admissions."""
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from cake_tpu.api.server import ApiServer, make_handler
    from cake_tpu.args import Args
    from cake_tpu.master import Master
    from cake_tpu.models.chat import Message
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.router import start_router
    from cake_tpu.router.discovery import ReplicaAnnouncer
    from cake_tpu.serve.engine import InferenceEngine

    def replica(tag: str):
        eng = InferenceEngine(
            cfg, params, tok, max_slots=slots, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0,
                                    repeat_penalty=1.0),
            kv_pages=kv_pages, kv_page_size=kv_page_size,
            paged_attn="fold", auto_prefix_system=True)
        master = Master(Args(sample_len=gen_tokens),
                        text_generator=None)
        master.llm = object()
        api = ApiServer(master, engine=eng, replica_id=tag)
        httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                    make_handler(api))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        api.replica_id = f"127.0.0.1:{httpd.server_address[1]}"
        return eng, api, httpd, api.replica_id

    def msgs(tenant: str, i: int) -> list:
        return [{"role": "system",
                 "content": f"You are tenant {tenant}'s assistant. "
                            + "policy " * 8},
                {"role": "user", "content": f"q{i} wwww"}]

    engA, apiA, httpdA, addrA = replica("disc-a")
    engB, apiB, httpdB, addrB = replica("disc-b")
    # pay the jit compiles on BOTH engines before any clock starts, so
    # the join latency measures discovery + placement, not XLA
    for eng in (engA, engB):
        h = eng.chat([Message.from_json(m) for m in msgs("warm", 0)],
                     max_new_tokens=gen_tokens)
        assert h.wait(timeout=900), "discovery smoke warmup timed out"
    warm_b = engB.stats.requests_completed

    rhttpd, router = start_router(
        [], address="127.0.0.1:0", block=False, tokenizer=tok,
        poll_interval_s=0.05, stale_after_s=1.0,
        announce="127.0.0.1:0", announce_interval_s=0.1,
        forget_grace_s=0.5, policy_mode="affinity")
    raddr = f"127.0.0.1:{rhttpd.server_address[1]}"
    aport = router.discovery.port

    def ask(tenant: str, i: int) -> None:
        req = urllib.request.Request(
            f"http://{raddr}/api/v1/chat/completions",
            data=json.dumps({"messages": msgs(tenant, i),
                             "max_tokens": gen_tokens}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=900) as resp:
            json.loads(resp.read())

    def until(pred, timeout_s=60.0):
        deadline = time.monotonic() + timeout_s
        while not pred() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert pred(), "discovery smoke condition timed out"

    switch = {"flag": False}

    def b_health() -> dict:
        doc = apiB.health(lite=True)
        if switch["flag"]:
            doc["switch_in_flight"] = True
        return doc

    annA = annB = None
    try:
        annA = ReplicaAnnouncer(
            f"127.0.0.1:{aport}", addrA, interval_s=0.1,
            health=lambda: apiA.health(lite=True), engine=engA)
        until(lambda: (st := router.tracker.get(addrA)) is not None
              and st.admitting)
        for i in range(4):           # pre-join: A owns the fleet
            ask("solo", i)
        assert engA.stats.requests_completed >= 4

        # -- hot-join B mid-fleet; time announce -> first serve --
        t_join = time.perf_counter()
        annB = ReplicaAnnouncer(
            f"127.0.0.1:{aport}", addrB, interval_s=0.1,
            health=b_health, engine=engB)
        until(lambda: (st := router.tracker.get(addrB)) is not None
              and st.admitting)
        join_ms, sent = None, 0
        joiners = [f"j{i}" for i in range(24)]
        for tenant in joiners:       # fresh tenants hash across BOTH
            ask(tenant, 0)
            sent += 1
            if engB.stats.requests_completed > warm_b:
                join_ms = (time.perf_counter() - t_join) * 1e3
                if sent >= 8:        # enough samples for the shift
                    break
        b_served = engB.stats.requests_completed - warm_b
        placement_shift = b_served / sent if sent else 0.0

        # -- hot-switch: B flags switch_in_flight over the wire --
        switch["flag"] = True
        until(lambda: router.tracker.get(addrB).switch_in_flight)
        b0 = engB.stats.requests_completed
        for i in range(4):           # routed AROUND the switching box
            ask(f"s{i}", 0)
        routed_around = engB.stats.requests_completed - b0
        switch["flag"] = False       # epoch landed: restore
        until(lambda: not router.tracker.get(addrB).switch_in_flight)
        b1 = engB.stats.requests_completed
        for tenant in joiners[:sent]:
            ask(tenant, 1)           # B's tenants come HOME
            if engB.stats.requests_completed > b1:
                break
        restored = engB.stats.requests_completed > b1

        # -- explicit departure: drain-then-forget, 0 admissions --
        b2 = engB.stats.requests_completed
        assert annB.depart(timeout_s=5.0) is True
        until(lambda: (st := router.tracker.get(addrB)) is None
              or st.departing)
        for i in range(4):
            ask(f"d{i}", 0)
        post_departure = engB.stats.requests_completed - b2
        until(lambda: router.tracker.get(addrB) is None)
        return {
            "router_disc_join_to_first_serve_ms":
                round(join_ms, 1) if join_ms is not None else None,
            "router_disc_joiner_completed": int(b_served),
            "router_disc_placement_shift": round(placement_shift, 4),
            "router_disc_switch_admissions_routed_around":
                int(routed_around),
            "router_disc_switch_restored": bool(restored),
            "router_disc_post_departure_admissions":
                int(post_departure),
            "router_disc_forgotten_after_depart":
                router.tracker.get(addrB) is None,
        }
    finally:
        for a in (annA, annB):
            if a is not None:
                a.close(depart=True)
        rhttpd.shutdown()
        router.close()
        for h in (httpdA, httpdB):
            h.shutdown()
        for e in (engA, engB):
            e.stop(timeout=30)


def _router_sentinel_smoke(cfg, params, tok, max_seq: int,
                           gen_tokens: int) -> dict:
    """The ISSUE 15 sentinel smoke: a CLEAN engine under
    identical-shape load must fire ZERO anomalies; a degraded engine —
    a seeded --fault-plan wedge mid-decode plus prompts walking three
    FRESH prefill buckets in one window — must fire
    cake_anomaly_total{kind="recompile_storm"}. Dense engines (the
    paged mixed step compiles ONE program for every prompt length, so
    bucketed whole-prompt prefill is where a shape storm lives);
    detectors tick synchronously so the smoke is deterministic."""
    from cake_tpu.models.chat import History, Message
    from cake_tpu.models.llama.generator import (
        bucket_length, encode_text,
    )
    from cake_tpu.obs import metrics as obs_m
    from cake_tpu.obs.sentinel import attach_engine_sentinel
    from cake_tpu.ops.sampling import SamplingConfig
    from cake_tpu.serve.engine import InferenceEngine

    def msgs(n_user):
        return [Message.from_json({"role": "user",
                                   "content": "q" + "w" * n_user})]

    def render_len(n_user):
        hist = History(cfg.chat_template)
        for m in msgs(n_user):
            hist.add_message(m)
        return len(encode_text(tok, hist.render()))

    # one content length per DISTINCT prefill bucket, smallest first:
    # lengths[0] is the clean/warm shape, the rest are the storm
    base = render_len(0)
    lengths, seen = [], set()
    for n in range(1, max_seq - base - gen_tokens - 2):
        b = bucket_length(base + n, max_seq)
        if b not in seen:
            seen.add(b)
            lengths.append(n)
        if len(lengths) == 4:
            break
    assert len(lengths) >= 3, (lengths, base, max_seq)

    def build(fault_plan=None):
        return InferenceEngine(
            cfg, params, tok, max_slots=2, max_seq_len=max_seq,
            sampling=SamplingConfig(temperature=0.0,
                                    repeat_penalty=1.0),
            fault_plan=fault_plan).start()

    def drive(eng, ns):
        for n in ns:
            h = eng.chat(msgs(n), max_new_tokens=gen_tokens)
            assert h.wait(timeout=900), "sentinel smoke timed out"

    c = obs_m.REGISTRY.get("cake_anomaly_total")

    def fired(kind):
        return c.samples().get((kind,), 0) if c is not None else 0

    # clean phase: identical-shape load, zero anomalies
    clean = build()
    drive(clean, lengths[:1])          # warmup pays its compiles
    sen = attach_engine_sentinel(clean, fire_after=1,
                             attainment_floor=0.05)
    for _ in range(2):
        drive(clean, lengths[:1] * 2)
        sen.tick()
    clean_fired = sen.fired_total
    clean.stop(timeout=30)

    # degraded phase: the seeded wedge fires on the (gen+2)th decode
    # dispatch — i.e. mid-STORM, after the warmup's ~gen dispatches —
    # while the storm prompts compile three fresh prefill buckets
    storm = build(fault_plan=f"seed=7;engine.decode:"
                             f"nth={gen_tokens + 2}:wedge:secs=0.5")
    drive(storm, lengths[:1])          # aliased warm: no new shapes
    # >1.5/window: the tiny smoke's prompt walk reaches two fresh
    # buckets past the warm shape (the 8b tier reaches four) — both
    # are storms against a steady-state norm of zero
    sen2 = attach_engine_sentinel(storm, fire_after=1,
                                  recompile_threshold=1.5,
                                  attainment_floor=0.05)
    base_rc = fired("recompile_storm")
    drive(storm, lengths[1:])
    trs = sen2.tick()
    kinds = sorted({t["kind"] for t in trs if t["state"] == "fired"})
    degradations = len(storm.recovery_seconds)
    storm.stop(timeout=30)
    assert clean_fired == 0, sen.state()
    assert "recompile_storm" in kinds, (kinds, trs)
    assert fired("recompile_storm") > base_rc
    assert degradations >= 1, "the seeded fault plan never fired"
    return {
        "sentinel_clean_anomalies": int(clean_fired),
        "sentinel_storm_anomaly_kinds": kinds,
        "sentinel_storm_recompile_anomalies":
            int(fired("recompile_storm") - base_rc),
        "sentinel_degradations_injected": degradations,
    }


def tier_main():
    """Child-process entry: run one tier, print its JSON line."""
    name = os.environ[ORCH_ENV]
    if name in ROUTER_TIERS or name.startswith("router"):
        kwargs = {**ROUTER_TIERS, **SMOKE_TIERS}[name]
        result = run_router_tier(name, **kwargs)
    elif name in FLEET_TIERS or name.startswith("fleet"):
        kwargs = {**FLEET_TIERS, **SMOKE_TIERS}[name]
        result = run_fleet_tier(name, **kwargs)
    elif name in AUTOTUNE_TIERS or name.startswith("autotune"):
        kwargs = {**AUTOTUNE_TIERS, **SMOKE_TIERS}[name]
        result = run_autotune_tier(name, **kwargs)
    elif name in CHAOS_TIERS or name.startswith("chaos"):
        kwargs = {**CHAOS_TIERS, **SMOKE_TIERS}[name]
        result = run_chaos_tier(name, **kwargs)
    elif name in RESTART_TIERS or name.startswith("restart"):
        kwargs = {**RESTART_TIERS, **SMOKE_TIERS}[name]
        result = run_restart_tier(name, **kwargs)
    elif name in KV_TIER_TIERS or name.startswith("kvtier"):
        kwargs = {**KV_TIER_TIERS, **SMOKE_TIERS}[name]
        result = run_kv_tier(name, **kwargs)
    elif name in DISAGG_TIERS or name.startswith("disagg"):
        kwargs = {**DISAGG_TIERS, **SMOKE_TIERS}[name]
        result = run_disagg_tier(name, **kwargs)
    elif name in MIXED_TIERS or name.startswith("mixed_"):
        kwargs = {**MIXED_TIERS, **SMOKE_TIERS}[name]
        result = run_mixed_tier(name, **kwargs)
    elif name in SLO_TIERS or name.startswith("slo_"):
        kwargs = {**SLO_TIERS, **SMOKE_TIERS}[name]
        result = run_slo_tier(name, **kwargs)
    elif name in PAGED_PREFIX_TIERS or name.startswith("paged_prefix"):
        kwargs = {**PAGED_PREFIX_TIERS, **SMOKE_TIERS}[name]
        result = run_paged_prefix_tier(name, **kwargs)
    elif name in PAGED_TIERS or name.startswith("paged_tiny"):
        kwargs = {**PAGED_TIERS, **SMOKE_TIERS}[name]
        result = run_paged_tier(name, **kwargs)
    elif (name in dict(ENGINE_TIERS) or name in dict(ENGINE_PEAK_TIERS)
            or name in ("engine_tiny", "engine_spec_tiny")):
        kwargs = {**dict(ENGINE_TIERS), **dict(ENGINE_PEAK_TIERS),
                  **SMOKE_TIERS}[name]
        result = run_engine_tier(name, **kwargs)
    elif name in dict(SD_TIERS) or name == "sd_tiny":
        kwargs = {**dict(SD_TIERS), **SMOKE_TIERS}[name]
        result = run_sd_tier(name, **kwargs)
    elif name in SPEC_PAGED_TIERS or name == "spec_paged_tiny":
        kwargs = {**SPEC_PAGED_TIERS, **SMOKE_TIERS}[name]
        result = run_spec_paged_tier(name, **kwargs)
    elif name in dict(SPEC_TIERS) or name == "spec_tiny":
        kwargs = {**dict(SPEC_TIERS), **SMOKE_TIERS}[name]
        result = run_spec_tier(name, **kwargs)
    else:
        kwargs = {**dict(TIERS), **SMOKE_TIERS}[name]
        result = run_tier(name, **kwargs)
    print(json.dumps(result), flush=True)


def probe_main():
    """Child-process entry: init the backend, print one JSON line.

    Deliberately does nothing else — the point is to discover a dead or
    hung backend in seconds, in a process the orchestrator can kill."""
    import jax
    dev = jax.devices()[0]
    print(json.dumps({"platform": dev.platform,
                      "device_kind": dev.device_kind}), flush=True)


def _spawn_self(env_key: str, value: str, timeout: int, label: str,
                env_extra: dict | None = None):
    """Re-exec this file with env_key=value set; returns (proc, json_line)
    or (None, None) on timeout (partial stderr logged either way).
    json_line is None when the first '{'-line isn't parseable JSON, so no
    caller can crash out of the one-JSON-line output contract.
    env_extra: additional env overrides (the cpu-fallback path forces
    JAX_PLATFORMS=cpu into every child)."""
    env = dict(os.environ, **{env_key: value}, **(env_extra or {}))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        err = e.stderr or b""
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        log(f"{label}: timed out after {timeout}s; "
            f"partial stderr:\n{err[-2000:]}")
        return None, None
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")), None)
    if line is not None:
        try:
            json.loads(line)
        except json.JSONDecodeError:
            log(f"{label}: unparseable output line: {line[:200]}")
            line = None
    return proc, line


def _probe_backend(env_extra: dict | None = None) -> dict | None:
    """Fail-fast backend check. Returns device info, or None if the
    backend is unreachable/hung — in which case the caller must emit an
    error JSON line immediately instead of burning tier timeouts."""
    log(f"--- backend probe (timeout {PROBE_TIMEOUT_S}s"
        + (f", env {env_extra}" if env_extra else "") + ") ---")
    t0 = time.perf_counter()
    proc, line = _spawn_self(PROBE_ENV, "1", PROBE_TIMEOUT_S, "probe",
                             env_extra=env_extra)
    if proc is None:
        return None
    if proc.returncode == 0 and line:
        info = json.loads(line)
        log(f"probe: ok in {time.perf_counter() - t0:.1f}s -> "
            f"{info.get('platform')}/{info.get('device_kind')}")
        return info
    tail = (proc.stderr or "").strip().splitlines()
    log(f"probe: failed rc={proc.returncode}: "
        f"{tail[-1] if tail else 'no stderr'}")
    return None


CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def _probe_with_fallback() -> tuple[dict | None, dict | None]:
    """(device info, env_extra for every tier child). A dead/hung
    primary backend (the BENCH_r05 failure: every probe rc=1, value
    0.0, 'backend unreachable') falls back to JAX_PLATFORMS=cpu so the
    run still emits a real measurement tagged backend=cpu_fallback
    instead of exiting non-zero with an empty perf trajectory."""
    info = _probe_backend()
    if info is not None:
        return info, None
    log("primary backend unreachable; falling back to JAX_PLATFORMS=cpu")
    info = _probe_backend(env_extra=CPU_ENV)
    if info is not None:
        return info, CPU_ENV
    return None, CPU_ENV


def _run_tier_subprocess(name: str,
                         env_extra: dict | None = None) -> dict | None:
    log(f"--- tier {name} (fresh subprocess) ---")
    proc, line = _spawn_self(ORCH_ENV, name, 1800, name,
                             env_extra=env_extra)
    if proc is None:
        return None
    sys.stderr.write(proc.stderr)
    if proc.returncode == 0 and line:
        result = json.loads(line)
        if result.get("value", 0) > 0:
            return result
    log(f"{name}: failed (rc={proc.returncode})")
    return None


def _single_tier_main(metric: str, unit: str, cpu_tier: str,
                      tpu_tier: str, fail_error: str,
                      extra: dict | None = None) -> int:
    """THE probe → cpu-fallback → one-tier → one-JSON-line scaffold
    shared by every `bench.py --<mode>` entry (the BENCH_r05 contract:
    always emit one parseable line; rc 0 on an unreachable backend so a
    perf-trajectory parser never sees an empty run). `metric`/`unit`
    shape the error lines; `extra` rides every error line (e.g. the
    chosen paged_attn impl)."""
    info, env_extra = _probe_with_fallback()
    if info is None:
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "backend": "cpu_fallback",
            # top-level degraded marker: a driver round reading 0.0
            # here is the intermittent-TPU-tunnel condition (ROADMAP),
            # machine-distinguishable from a real perf regression
            "degraded": True,
            "error": "no backend reachable (TPU and CPU probes failed)",
            **(extra or {}),
        }), flush=True)
        return 0
    on_cpu = env_extra is not None or info.get("platform") != "tpu"
    name = cpu_tier if on_cpu else tpu_tier
    result = _run_tier_subprocess(name, env_extra=env_extra)
    if result is None:
        out = {
            "metric": f"{name}_{metric}", "value": 0.0, "unit": unit,
            "vs_baseline": 0.0, "error": fail_error, **(extra or {}),
        }
        if env_extra is not None:
            out["backend"] = "cpu_fallback"
            out["degraded"] = True
        print(json.dumps(out), flush=True)
        return 1
    if env_extra is not None:
        result["backend"] = "cpu_fallback"
        result["degraded"] = True
    print(json.dumps(result), flush=True)
    return 0


def _paged_main(impl: str) -> int:
    """`bench.py --paged-attn fold|pallas`: the paged-decode microbench
    — one tier, one JSON line, measuring the chosen attention impl
    through a --kv-pages engine. CPU-fallback rules match main()."""
    if impl not in ("fold", "pallas"):
        print(json.dumps({
            "metric": "paged_decode_tok_s", "value": 0.0,
            "unit": "tokens/s", "vs_baseline": 0.0,
            "error": f"--paged-attn takes fold or pallas, got {impl!r}",
        }), flush=True)
        return 2
    return _single_tier_main(
        "paged_decode_tok_s", "tokens/s",
        cpu_tier=f"paged_tiny_{impl}", tpu_tier=f"paged_8b_int8_{impl}",
        fail_error="paged microbench tier failed",
        extra={"paged_attn": impl})


def _mixed_main() -> int:
    """`bench.py --mixed`: the token-level continuous-batching tier —
    one JSON line with mixed-on vs mixed-off tok/s, step MFU, and
    arrival TTFT p50/p99 under the same interleaved-admission load,
    plus the both-kinds mixed-step count. CPU-fallback rules match
    main()."""
    return _single_tier_main(
        "mixed_ttft_p99_ms", "ms",
        cpu_tier="mixed_tiny", tpu_tier="mixed_8b_int8",
        fail_error="mixed continuous-batching tier failed")


def _kv_tier_main() -> int:
    """`bench.py --kv-tier`: the KV tiering A/B — one JSON line with
    resident streams, tok/s, and host-tier spill/restore counts at f32
    vs int8 KV under the same pool byte budget, headline value the
    int8/f32 resident-stream ratio. CPU-fallback rules match main()."""
    return _single_tier_main(
        "kv_resident_streams_ratio", "x",
        cpu_tier="kvtier_tiny", tpu_tier="kvtier_8b",
        fail_error="kv tiering tier failed")


def _disagg_main() -> int:
    """`bench.py --disagg`: the disaggregated prefill/decode A/B — one
    JSON line with colocated vs split-over-loopback decode tok/s and
    arrival TTFT p50/p99, pages/bytes shipped per KV dtype, and an
    f32 token-identity flag, headline value the int8/f32 ship-bytes
    ratio. CPU-fallback rules match main()."""
    return _single_tier_main(
        "disagg_ship_bytes_ratio_int8", "x",
        cpu_tier="disagg_tiny", tpu_tier="disagg_8b_int8",
        fail_error="disaggregated prefill/decode tier failed")


def _restart_main() -> int:
    """`bench.py --restart`: the durable-serving crash drill — one
    JSON line with RTO (recovery wall seconds after a staged kill -9),
    requests replayed vs lost (must be 0), and a token-identity flag
    vs an uninterrupted run of the same load through a --journal
    engine. CPU-fallback rules match main()."""
    return _single_tier_main(
        "rto_s", "s",
        cpu_tier="restart_tiny", tpu_tier="restart_8b_int8",
        fail_error="restart crash-drill tier failed")


def _chaos_main() -> int:
    """`bench.py --chaos`: the crash-resilience tier — one JSON line
    with recovered / failed / quarantined request counts, recovery
    latency p50/p99, and a clean-vs-chaos token-identity flag under
    the same offered load with a seeded --fault-plan injected.
    CPU-fallback rules match main()."""
    return _single_tier_main(
        "recovered_requests", "requests",
        cpu_tier="chaos_tiny", tpu_tier="chaos_8b_int8",
        fail_error="chaos crash-resilience tier failed")


def _autotune_main() -> int:
    """`bench.py --autotune`: the online-autotuner tier — one JSON
    line with per-phase tok/s + TTFT p99 for a pinned-config vs
    autotune-on run of the same mid-run load shift, plus the
    switch/rollback counts and the greedy token-identity flag.
    CPU-fallback rules match main()."""
    return _single_tier_main(
        "switches", "switches",
        cpu_tier="autotune_tiny", tpu_tier="autotune_8b_int8",
        fail_error="autotune hot-switch tier failed")


def _slo_main() -> int:
    """`bench.py --slo`: the mixed-priority SLO scheduling tier — one
    JSON line with per-class TTFT p50/p99 for a preemption-on vs
    preemption-off phase under the same offered load, plus the
    preemption count. CPU-fallback rules match main()."""
    return _single_tier_main(
        "interactive_ttft_p99_ms", "ms",
        cpu_tier="slo_tiny", tpu_tier="slo_8b_int8",
        fail_error="slo scheduling tier failed")


def _fleet_main() -> int:
    """`bench.py --fleet`: the telemetry-federation wire tier — one
    JSON line with export batches shipped, collector ingest lag
    p50/p99, control-channel bytes/op and the drained follower's
    applied-seq lag (must be 0). No model; CPU-fallback rules match
    main()."""
    return _single_tier_main(
        "export_batches", "frames",
        cpu_tier="fleet_tiny", tpu_tier="fleet_wire",
        fail_error="fleet telemetry federation tier failed")


def _router_main() -> int:
    """`bench.py --router`: the prefix-affinity router tier — one JSON
    line with aggregate goodput tok/s, fleet prefix-hit rate and TTFT
    p50/p99 for the SAME shared-prefix load routed prefix-affinity vs
    round-robin over 2 in-process engine replicas behind the real
    front door, plus the failover count (must be 0 on a healthy
    fleet). CPU-fallback rules match main()."""
    return _single_tier_main(
        "goodput_tok_s", "tokens/s",
        cpu_tier="router_tiny", tpu_tier="router_8b_int8",
        fail_error="router aggregate-goodput tier failed")


def _spec_paged_main() -> int:
    """`bench.py --spec-paged`: the paged speculative decoding smoke —
    one JSON line pinning greedy spec-paged output token-identical to
    plain greedy paged decode, acceptance > 0, tokens/round > 1, and
    full page-pool conservation. CPU-fallback rules match main()."""
    return _single_tier_main(
        "spec_paged_tok_per_round", "tokens/round",
        cpu_tier="spec_paged_tiny", tpu_tier="spec_paged_1b",
        fail_error="paged speculative smoke tier failed")


def _paged_prefix_main() -> int:
    """`bench.py --paged-prefix`: the paged prefix-sharing tier — one
    JSON line with suffix-only vs whole-prompt TTFT and pages_shared
    through a --kv-pages engine. CPU-fallback rules match main()."""
    return _single_tier_main(
        "prefix_ttft_p50_ms", "ms",
        cpu_tier="paged_prefix_tiny", tpu_tier="paged_prefix_8b_int8",
        fail_error="paged prefix tier failed")


def main():
    info, env_extra = _probe_with_fallback()
    if info is None:
        # One immediate, diagnosable line instead of rc=124 after hours
        # of per-tier timeouts against a backend that cannot answer
        # (the round-3 failure mode). Still exit 0 with parseable JSON:
        # a perf-trajectory parser must never see an empty run.
        print(json.dumps({
            "metric": "decode_tok_s_per_chip", "value": 0.0,
            "unit": "tokens/s", "vs_baseline": 0.0,
            "backend": "cpu_fallback", "degraded": True,
            "error": "backend unreachable: device init failed or hung "
                     f"within {PROBE_TIMEOUT_S}s (CPU fallback failed "
                     "too)",
        }), flush=True)
        sys.exit(0)
    if env_extra is not None:
        # CPU fallback: the real tiers would burn their 1800s timeouts
        # interpreting an 8B model — run the tiny tier for a valid,
        # honestly-labeled data point and exit 0.
        result = _run_tier_subprocess("tiny", env_extra=env_extra)
        if result is None:
            result = {"metric": "tiny_decode_tok_s_per_chip",
                      "value": 0.0, "unit": "tokens/s",
                      "vs_baseline": 0.0,
                      "error": "cpu fallback tier failed"}
        result["backend"] = "cpu_fallback"
        # top-level degraded marker (see _single_tier_main): driver
        # rounds that read this line know the probe fell back
        result["degraded"] = True
        print(json.dumps(result), flush=True)
        sys.exit(0)
    for name, _kwargs in TIERS:
        result = _run_tier_subprocess(name)
        if result is None:
            continue
        # headline secured; add engine-path TTFT + streaming throughput
        # (BASELINE config #5) as extra keys — a failure here must not
        # cost the headline number. Only try engine tiers no bigger than
        # the model that just fit (an 8B engine run after the 8B headline
        # OOMed would burn its whole timeout failing the same way).
        engine_tiers = [
            (ename, kw) for ename, kw in ENGINE_TIERS
            if not (kw["model"] == "8b" and not name.startswith("llama3_8b"))
        ]
        for ename, _kw in engine_tiers:
            eres = _run_tier_subprocess(ename)
            if eres is not None:
                result.update({k: v for k, v in eres.items()
                               if k.startswith(("ttft_", "engine_"))})
                break
        # peak-throughput engine configuration (32 slots) — extra keys
        if name.startswith("llama3_8b"):
            for ename, _kw in ENGINE_PEAK_TIERS:
                eres = _run_tier_subprocess(ename)
                if eres is not None:
                    result["engine_peak_tok_s"] = eres.get(
                        "engine_decode_tok_s")
                    result["engine_peak_streams"] = eres.get(
                        "engine_streams")
                    result["engine_peak_ttft_p50_ms"] = eres.get(
                        "ttft_p50_ms")
                    break
        # SD per-step latency (BASELINE config #4) — extra keys, same
        # failure isolation
        for sname, _kw in SD_TIERS:
            sres = _run_tier_subprocess(sname)
            if sres is not None:
                result.update({k: v for k, v in sres.items()
                               if k.startswith("sd_")})
                break
        # speculative acceptance + speedup (batch-1 latency axis) — only
        # when the 8B headline fit (the spec tier holds target AND draft)
        if name.startswith("llama3_8b"):
            for pname, _kw in SPEC_TIERS:
                pres = _run_tier_subprocess(pname)
                if pres is not None:
                    result.update({k: v for k, v in pres.items()
                                   if k.startswith("spec_")})
                    break
        print(json.dumps(result), flush=True)
        return
    print(json.dumps({
        "metric": "decode_tok_s_per_chip", "value": 0.0,
        "unit": "tokens/s", "vs_baseline": 0.0,
    }))
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get(PROBE_ENV):
        probe_main()
    elif os.environ.get(RESTART_CHILD_ENV):
        # BEFORE the ORCH_ENV check: the restart tier re-execs this
        # file from inside its own tier subprocess, so the child
        # inherits ORCH_ENV and would otherwise loop into tier_main
        restart_child_main()
    elif os.environ.get(ORCH_ENV):
        tier_main()
    elif "--kv-tier" in sys.argv:
        sys.exit(_kv_tier_main())
    elif "--disagg" in sys.argv:
        sys.exit(_disagg_main())
    elif "--mixed" in sys.argv:
        sys.exit(_mixed_main())
    elif "--autotune" in sys.argv:
        sys.exit(_autotune_main())
    elif "--slo" in sys.argv:
        sys.exit(_slo_main())
    elif "--chaos" in sys.argv:
        sys.exit(_chaos_main())
    elif "--restart" in sys.argv:
        sys.exit(_restart_main())
    elif "--fleet" in sys.argv:
        sys.exit(_fleet_main())
    elif "--router" in sys.argv:
        sys.exit(_router_main())
    elif "--paged-prefix" in sys.argv:
        sys.exit(_paged_prefix_main())
    elif "--spec-paged" in sys.argv:
        sys.exit(_spec_paged_main())
    elif "--paged-attn" in sys.argv:
        i = sys.argv.index("--paged-attn")
        arg = sys.argv[i + 1] if i + 1 < len(sys.argv) else ""
        sys.exit(_paged_main(arg))
    else:
        main()
