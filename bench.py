"""Benchmark: Llama-3-8B single-chip decode throughput (BASELINE.md config #1).

Prints ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Method mirrors the reference's instrumentation (master.rs:93-121): steady-
state decode tokens/s, excluding compile/warmup. The model is the real
Llama-3-8B architecture (random bf16 weights — no checkpoint egress in this
environment; throughput is weight-value independent). The whole
prefill+decode loop runs on-device (`lax.scan`), so the number is chip
throughput, not host dispatch.

vs_baseline: the reference publishes no numbers (BASELINE.md). We compare
against the chip's HBM-bandwidth roofline for bf16 8B decode (params bytes /
bandwidth), the fundamental limit for batch-1 decode: vs_baseline =
achieved / roofline. Falls back to smaller configs if the 8B doesn't fit.
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_params_on_device(cfg, dtype=jnp.bfloat16):
    """Random params initialised directly on-device (no 16GB host copy)."""
    from cake_tpu.models.llama.params import init_params
    return jax.jit(partial(init_params, cfg, dtype=dtype))(
        jax.random.PRNGKey(0)
    )


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def run_decode_bench(cfg, batch_size=1, prompt_len=128, gen_tokens=128,
                     max_seq=1024, quant=False):
    from cake_tpu.models.llama.cache import KVCache
    from cake_tpu.models.llama.generator import LlamaGenerator, ByteTokenizer
    from cake_tpu.ops.sampling import SamplingConfig

    import numpy as np

    params = build_params_on_device(cfg)
    n_params = count_params(params)
    log(f"params: {n_params/1e9:.2f}B ({n_params*2/2**30:.1f} GiB bf16)")
    if quant:
        from cake_tpu.ops.quant import quantize_params
        # donated: bf16 buffers free as int8 copies materialise
        params = jax.jit(quantize_params, donate_argnums=0)(params)
        jax.block_until_ready(params)
        log("weights quantized to int8 (weight-only, per-channel)")

    gen = LlamaGenerator(
        cfg, params, ByteTokenizer(cfg.vocab_size),
        max_seq_len=max_seq, batch_size=batch_size,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    prompt = np.ones((batch_size, prompt_len), np.int32)
    plen = np.full((batch_size,), prompt_len, np.int32)

    t0 = time.perf_counter()
    out = gen.generate_on_device(prompt, plen, gen_tokens)  # compile + run
    t_compile = time.perf_counter() - t0
    log(f"first call (compile+run): {t_compile:.1f}s")

    t0 = time.perf_counter()
    out = gen.generate_on_device(prompt, plen, gen_tokens)
    dt = time.perf_counter() - t0
    total = batch_size * gen_tokens
    tok_s = total / dt
    log(f"steady state: {total} tokens in {dt:.2f}s -> {tok_s:.2f} tok/s")
    assert out.shape == (batch_size, gen_tokens)
    return tok_s, n_params


def main():
    from cake_tpu.models.llama.config import LlamaConfig

    dev = jax.devices()[0]
    log(f"device: {dev.platform}/{dev.device_kind}")

    # HBM-bandwidth roofline for batch-1 bf16 decode (v5e ~819 GB/s)
    HBM_GBS = 819e9

    # (name, config, batch, max_seq, int8 weight-only). The headline is
    # int8 8B decode; vs_baseline stays the *bf16* HBM roofline, so a value
    # above 1.0 means beating the physical ceiling of the reference's best
    # dtype (f16) on this chip. bf16 tiers are the fallback.
    tiers = [
        ("llama3_8b_int8", LlamaConfig.llama3_8b(), 1, 1024, True),
        ("llama3_8b", LlamaConfig.llama3_8b(), 1, 1024, False),
        ("llama3_3b-ish", LlamaConfig(
            vocab_size=128256, hidden_size=3072, intermediate_size=8192,
            num_hidden_layers=28, num_attention_heads=24,
            num_key_value_heads=8, rope_theta=500000.0), 1, 1024, False),
        ("llama3_1b-ish", LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=8192,
            num_hidden_layers=16, num_attention_heads=32,
            num_key_value_heads=8, rope_theta=500000.0), 1, 1024, False),
    ]
    for name, cfg, bs, max_seq, quant in tiers:
        try:
            tok_s, n_params = run_decode_bench(cfg, batch_size=bs,
                                               max_seq=max_seq, quant=quant)
            roofline = HBM_GBS / (n_params * 2)  # bf16 tokens/s upper bound
            print(json.dumps({
                "metric": f"{name}_decode_tok_s_per_chip",
                "value": round(tok_s, 2),
                "unit": "tokens/s",
                "vs_baseline": round(tok_s / roofline, 3),
            }))
            return
        except Exception as e:  # noqa: BLE001 — fall to smaller tier on OOM
            log(f"{name} failed: {type(e).__name__}: {e}")
            continue
    print(json.dumps({
        "metric": "decode_tok_s_per_chip", "value": 0.0,
        "unit": "tokens/s", "vs_baseline": 0.0,
    }))
    sys.exit(1)


if __name__ == "__main__":
    main()
