"""Chat message types and the Llama-3 prompt template.

Reference: `MessageRole`/`Message` (cake-core/src/models/chat.rs:5-64) and
`History` (cake-core/src/models/llama3/history.rs:4-47), whose rendering
follows meta-llama's tokenizer.py ChatFormat:

  <|begin_of_text|>
  then per message:
    <|start_header_id|>{role}<|end_header_id|>\n\n{content}<|eot_id|>
  then an empty assistant header to cue the model's completion.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Iterator, List


class MessageRole(str, Enum):
    SYSTEM = "system"
    USER = "user"
    ASSISTANT = "assistant"


@dataclass
class Message:
    role: MessageRole
    content: str

    @classmethod
    def system(cls, content: str) -> "Message":
        return cls(MessageRole.SYSTEM, content)

    @classmethod
    def user(cls, content: str) -> "Message":
        return cls(MessageRole.USER, content)

    @classmethod
    def assistant(cls, content: str) -> "Message":
        return cls(MessageRole.ASSISTANT, content)

    @classmethod
    def from_json(cls, obj: dict) -> "Message":
        # serde aliases accepted by the reference REST body (chat.rs:5-38)
        role = obj.get("role") or obj.get("Role")
        content = obj.get("content") or obj.get("Content") or ""
        return cls(MessageRole(role.lower()), content)

    def to_json(self) -> dict:
        return {"role": self.role.value, "content": self.content}


BEGIN_OF_TEXT = "<|begin_of_text|>"
START_HEADER = "<|start_header_id|>"
END_HEADER = "<|end_header_id|>"
EOT = "<|eot_id|>"


TEMPLATES = ("llama3", "mistral", "chatml")


class History:
    """Chat history -> prompt string.

    template="llama3" (default): the reference's format (history.rs:8-33).
    template="mistral": the Mistral-instruct format — `<s>[INST] ...
    [/INST] answer</s>` turns, system prompt merged into the first user
    turn (the official template has no system role), ending after the
    last `[/INST]` to cue completion.
    template="chatml": the Qwen2 format — `<|im_start|>{role}\\n{content}
    <|im_end|>\\n` per message, ending with an open assistant header."""

    def __init__(self, template: str = "llama3") -> None:
        if template not in TEMPLATES:
            raise ValueError(
                f"unknown chat template '{template}' (have {TEMPLATES})")
        self.template = template
        self._messages: List[Message] = []

    def add_message(self, message: Message) -> None:
        self._messages.append(message)

    def clear(self) -> None:
        self._messages.clear()

    def __len__(self) -> int:
        return len(self._messages)

    def __iter__(self) -> Iterator[Message]:
        return iter(self._messages)

    @staticmethod
    def encode_header(role: str) -> str:
        return f"{START_HEADER}{role}{END_HEADER}\n\n"

    @staticmethod
    def encode_message(message: Message) -> str:
        return History.encode_header(message.role.value) + message.content.strip() + EOT

    def render(self) -> str:
        """Full dialog prompt, ending with the template's completion cue."""
        if self.template == "mistral":
            return self._render_mistral()
        if self.template == "chatml":
            out = []
            if not (self._messages
                    and self._messages[0].role == MessageRole.SYSTEM):
                # Qwen2's official template injects this default system
                # prompt when the dialog opens without one
                out.append("<|im_start|>system\n"
                           "You are a helpful assistant.<|im_end|>\n")
            out += [f"<|im_start|>{m.role.value}\n{m.content.strip()}"
                    f"<|im_end|>\n" for m in self._messages]
            out.append("<|im_start|>assistant\n")
            return "".join(out)
        out = [BEGIN_OF_TEXT]
        for m in self._messages:
            out.append(self.encode_message(m))
        out.append(self.encode_header(MessageRole.ASSISTANT.value))
        return "".join(out)

    def _render_mistral(self) -> str:
        out = ["<s>"]
        pending_system: List[str] = []
        for m in self._messages:
            if m.role == MessageRole.SYSTEM:
                # no system role in the template: accumulate (several
                # system messages concatenate) and merge into the next
                # user turn
                pending_system.append(m.content.strip())
            elif m.role == MessageRole.USER:
                text = m.content.strip()
                if pending_system:
                    text = "\n\n".join(pending_system + [text])
                    pending_system = []
                out.append(f"[INST] {text} [/INST]")
            else:
                out.append(f" {m.content.strip()}</s>")
        if pending_system:
            # trailing system with no user turn: render as its own
            # instruction block rather than dropping it silently
            out.append(f"[INST] {chr(10).join(pending_system)} [/INST]")
        return "".join(out)
