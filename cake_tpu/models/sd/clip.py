"""CLIP text transformer (functional JAX).

Capability parity with the reference's Clip wrapper over candle's
ClipTextTransformer (sd/clip.rs:13-66). Architecture matches
transformers' CLIPTextModel so HF checkpoints load directly: token +
learned-position embeddings, pre-LN causal transformer layers
(quick_gelu/gelu MLP), final LayerNorm; pooled output at each sequence's
EOT position, with an optional text projection (SDXL encoder 2).
Golden-tested against transformers.CLIPTextModel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from cake_tpu.models.sd.config import ClipConfig
from cake_tpu.models.sd.layers import layer_norm, linear, mha
from cake_tpu.ops.attention import causal_mask


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


_ACTS = {"quick_gelu": quick_gelu, "gelu": jax.nn.gelu}


def init_clip_params(cfg: ClipConfig, rng, dtype=jnp.float32):
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    ks = iter(jax.random.split(rng, 6 + L))

    def w(key, shape, scale=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    def layer(key):
        k = iter(jax.random.split(key, 6))
        return {
            "ln1": {"w": jnp.ones((D,), dtype), "b": jnp.zeros((D,), dtype)},
            "q": {"w": w(next(k), (D, D)), "b": jnp.zeros((D,), dtype)},
            "k": {"w": w(next(k), (D, D)), "b": jnp.zeros((D,), dtype)},
            "v": {"w": w(next(k), (D, D)), "b": jnp.zeros((D,), dtype)},
            "o": {"w": w(next(k), (D, D)), "b": jnp.zeros((D,), dtype)},
            "ln2": {"w": jnp.ones((D,), dtype), "b": jnp.zeros((D,), dtype)},
            "fc1": {"w": w(next(k), (D, F)), "b": jnp.zeros((F,), dtype)},
            "fc2": {"w": w(next(k), (F, D)), "b": jnp.zeros((D,), dtype)},
        }

    params = {
        "token_embed": w(next(ks), (cfg.vocab_size, D)),
        "pos_embed": w(next(ks), (cfg.max_position_embeddings, D)),
        "layers": [layer(next(ks)) for _ in range(L)],
        "final_ln": {"w": jnp.ones((D,), dtype), "b": jnp.zeros((D,), dtype)},
    }
    if cfg.projection_dim:
        params["text_projection"] = w(next(ks), (D, cfg.projection_dim))
    return params


def clip_encode(params, cfg: ClipConfig, input_ids,
                output_hidden_state: int = -1):
    """input_ids [B, S] -> (hidden [B, S, D], pooled [B, D or proj]).

    output_hidden_state: -1 = after final_ln (v1.5); -2 = penultimate
    layer's output (SD v2.x / XL "clip skip" behavior, no final_ln).
    """
    B, S = input_ids.shape
    x = jnp.take(params["token_embed"], input_ids, axis=0)
    x = x + params["pos_embed"][None, :S]
    mask = causal_mask(S)
    heads = cfg.num_attention_heads
    act = _ACTS[cfg.hidden_act]

    hidden_states = []
    for lp in params["layers"]:
        h = layer_norm(x, lp["ln1"]["w"], lp["ln1"]["b"])
        q = linear(h, lp["q"]["w"], lp["q"]["b"])
        k = linear(h, lp["k"]["w"], lp["k"]["b"])
        v = linear(h, lp["v"]["w"], lp["v"]["b"])
        attn = mha(q, k, v, heads, mask=mask)
        x = x + linear(attn, lp["o"]["w"], lp["o"]["b"])
        h = layer_norm(x, lp["ln2"]["w"], lp["ln2"]["b"])
        x = x + linear(act(linear(h, lp["fc1"]["w"], lp["fc1"]["b"])),
                       lp["fc2"]["w"], lp["fc2"]["b"])
        hidden_states.append(x)

    final = layer_norm(x, params["final_ln"]["w"], params["final_ln"]["b"])
    if output_hidden_state == -1:
        out = final
    else:
        out = hidden_states[output_hidden_state]

    # pooled: features at the EOT token (highest id position, like HF's
    # argmax(input_ids) for standard CLIP tokenizers)
    eot = jnp.argmax(input_ids, axis=-1)
    pooled = jnp.take_along_axis(
        final, eot[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    if "text_projection" in params:
        pooled = pooled @ params["text_projection"]
    return out, pooled
