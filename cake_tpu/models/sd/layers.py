"""Shared neural-net layers for the diffusion stack.

Convs run in NHWC (TPU-native layout: channels innermost feeds the MXU's
128-lane minor dimension); GroupNorm reduces in f32. Weight layouts follow
torch/diffusers conventions on disk (OIHW convs, [out,in] linears) and are
transposed at load time (params.py), the same policy as the Llama loader.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(x, w, b=None, stride: int = 1, padding: int = 1):
    """x: [B, H, W, C_in]; w: [kh, kw, C_in, C_out] (HWIO); b: [C_out]."""
    out = lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def group_norm(x, weight, bias, num_groups: int = 32, eps: float = 1e-6):
    """GroupNorm over channel groups; x: [B, H, W, C] (reduced in f32)."""
    B, H, W, C = x.shape
    xf = x.astype(jnp.float32).reshape(B, H * W, num_groups, C // num_groups)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = xf.var(axis=(1, 3), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    xf = xf.reshape(B, H, W, C)
    return (xf * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def linear(x, w, b=None):
    """x @ w (+ b); w stored [in, out]."""
    out = x @ w
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def mha(q, k, v, num_heads: int, mask=None):
    """Multi-head attention on [B, S, D] tensors (f32 accumulation).

    Used by CLIP (causal self-attn) and the UNet transformer blocks
    (self + cross attention). Reuses the GQA kernel with KV == H.
    """
    from cake_tpu.ops.attention import gqa_attention
    B, S, D = q.shape
    T = k.shape[1]
    hd = D // num_heads
    qh = q.reshape(B, S, num_heads, hd)
    kh = k.reshape(B, T, num_heads, hd)
    vh = v.reshape(B, T, num_heads, hd)
    out = gqa_attention(qh, kh, vh, mask=mask)
    return out.reshape(B, S, D)


def timestep_embedding(timesteps, dim: int, max_period: float = 10000.0,
                       flip_sin_to_cos: bool = True, shift: float = 0.0):
    """Sinusoidal timestep embedding [B] -> [B, dim] (diffusers semantics:
    half dim sin, half cos; flip order for SD)."""
    half = dim // 2
    freqs = jnp.exp(
        -jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None, :] + shift
    sin, cos = jnp.sin(args), jnp.cos(args)
    emb = jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos],
                          axis=-1)
    if dim % 2 == 1:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


def nearest_upsample_2x(x):
    """[B, H, W, C] -> [B, 2H, 2W, C] nearest-neighbour."""
    B, H, W, C = x.shape
    x = x[:, :, None, :, None, :]
    x = jnp.broadcast_to(x, (B, H, 2, W, 2, C))
    return x.reshape(B, 2 * H, 2 * W, C)
