"""Stable Diffusion architecture configs per version.

Capability parity with the reference's per-version StableDiffusionConfig
construction (sd/sd.rs:141-154) and version enum + HF repo mapping
(lib.rs:202-268). Defaults mirror the published v1-5 / v2-1 / SDXL / Turbo
architectures (diffusers configs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from cake_tpu.args import SDVersion


@dataclass(frozen=True)
class ClipConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    hidden_act: str = "quick_gelu"      # v2/XL encoders use "gelu"
    projection_dim: Optional[int] = None  # XL text_encoder_2 projects pooled

    @classmethod
    def vit_l_14(cls):  # SD v1.5 / SDXL encoder 1
        return cls()

    @classmethod
    def vit_h_14(cls):  # SD v2.1
        return cls(hidden_size=1024, intermediate_size=4096,
                   num_hidden_layers=23, num_attention_heads=16,
                   hidden_act="gelu")

    @classmethod
    def vit_bigg_14(cls):  # SDXL encoder 2
        return cls(hidden_size=1280, intermediate_size=5120,
                   num_hidden_layers=32, num_attention_heads=20,
                   hidden_act="gelu", projection_dim=1280)


@dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    sample_size: int = 64
    cross_attention_dim: int = 768
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    # per down-block: does it carry cross-attention transformer blocks?
    attn_blocks: Tuple[bool, ...] = (True, True, True, False)
    transformer_layers_per_block: Tuple[int, ...] = (1, 1, 1, 0)
    attention_head_dim: Tuple[int, ...] = (8, 8, 8, 8)   # heads per block
    time_embed_dim_mult: int = 4
    # SDXL extras
    addition_embed_dim: Optional[int] = None  # text_embeds+time_ids path
    num_groups: int = 32


@dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    scaling_factor: float = 0.18215     # 0.13025 for SDXL
    num_groups: int = 32

    @property
    def downscale_factor(self) -> int:
        """Spatial ratio pixels/latents: one stride-2 conv per non-final
        block (8 for the standard 4-block VAE)."""
        return 2 ** (len(self.block_out_channels) - 1)


@dataclass(frozen=True)
class SDConfig:
    version: SDVersion = SDVersion.V1_5
    clip: ClipConfig = field(default_factory=ClipConfig.vit_l_14)
    clip2: Optional[ClipConfig] = None
    unet: UNetConfig = field(default_factory=UNetConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    height: int = 512
    width: int = 512
    default_steps: int = 30
    default_guidance: float = 7.5
    prediction_type: str = "epsilon"    # "v_prediction" for v2.1-768


def get_sd_config(version: SDVersion, height: Optional[int] = None,
                  width: Optional[int] = None) -> SDConfig:
    """Per-version presets (reference sd.rs:141-154, lib.rs:202-268)."""
    if version == SDVersion.V1_5:
        cfg = SDConfig()
    elif version == SDVersion.V2_1:
        cfg = SDConfig(
            version=version,
            clip=ClipConfig.vit_h_14(),
            unet=UNetConfig(cross_attention_dim=1024,
                            attention_head_dim=(5, 10, 20, 20)),
            height=768, width=768,
            default_guidance=7.5,
        )
    elif version in (SDVersion.XL, SDVersion.TURBO):
        cfg = SDConfig(
            version=version,
            clip=ClipConfig.vit_l_14(),
            clip2=ClipConfig.vit_bigg_14(),
            unet=UNetConfig(
                cross_attention_dim=2048,
                block_out_channels=(320, 640, 1280),
                attn_blocks=(False, True, True),
                transformer_layers_per_block=(0, 2, 10),
                attention_head_dim=(5, 10, 20),
                addition_embed_dim=2816,
            ),
            vae=VAEConfig(scaling_factor=0.13025),
            height=1024, width=1024,
            default_steps=1 if version == SDVersion.TURBO else 30,
            default_guidance=0.0 if version == SDVersion.TURBO else 7.5,
        )
    else:
        raise ValueError(f"unknown SD version {version}")
    if height is not None or width is not None:
        h = height or cfg.height
        w = width or cfg.width
        if h % 8 or w % 8:
            raise ValueError("height/width must be multiples of 8")
        object.__setattr__(cfg, "height", h)
        object.__setattr__(cfg, "width", w)
    return cfg


def tiny_sd_config() -> SDConfig:
    """Miniature config for tests: full architecture, tiny dims."""
    return SDConfig(
        clip=ClipConfig(vocab_size=1000, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, max_position_embeddings=77),
        unet=UNetConfig(
            cross_attention_dim=64,
            block_out_channels=(32, 64),
            layers_per_block=1,
            attn_blocks=(True, False),
            transformer_layers_per_block=(1, 0),
            attention_head_dim=(4, 4),
            num_groups=8,
        ),
        vae=VAEConfig(block_out_channels=(32, 64), layers_per_block=1,
                      num_groups=8),
        height=64, width=64, default_steps=3,
    )
