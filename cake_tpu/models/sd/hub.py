"""HF-hub asset resolution for SD components.

Capability parity with the reference's `ModelFile::get`
(cake-core/src/models/sd/sd.rs:29-102) and the per-version repo/file
mapping (lib.rs:202-268): an explicit --sd-* path always wins; otherwise
the asset is resolved from the local HF cache, and — when the environment
permits network access — downloaded from the hub.

Resolution order:
  1. explicit file path (returned verbatim, like the reference's
     `Some(filename)` arm),
  2. local HF cache hit (huggingface_hub.try_to_load_from_cache),
  3. hub download (hf_hub_download), unless offline mode is requested via
     HF_HUB_OFFLINE/CAKE_HUB_OFFLINE or allow_download=False.
A miss raises FileNotFoundError with the (repo, file) it wanted, so
zero-egress environments get an actionable message instead of a stack of
network errors.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

# version -> base diffusers repo (reference lib.rs:212-219). The reference
# pins runwayml/stable-diffusion-v1-5, which was removed from the hub in
# 2024 — the maintained mirror is used for downloads, with the legacy name
# kept as a cache alias so pre-existing local caches still resolve.
_REPOS = {
    "v1-5": "stable-diffusion-v1-5/stable-diffusion-v1-5",
    "v2-1": "stabilityai/stable-diffusion-2-1",
    "xl": "stabilityai/stable-diffusion-xl-base-1.0",
    "turbo": "stabilityai/sdxl-turbo",
}
_REPO_CACHE_ALIASES = {
    "stable-diffusion-v1-5/stable-diffusion-v1-5": (
        "runwayml/stable-diffusion-v1-5",),
}

# tokenizer repos (reference sd.rs:41-54)
_TOKENIZER_REPOS = {
    "v1-5": "openai/clip-vit-base-patch32",
    "v2-1": "openai/clip-vit-base-patch32",
    "xl": "openai/clip-vit-large-patch14",
    "turbo": "openai/clip-vit-large-patch14",
}
_TOKENIZER2_REPO = "laion/CLIP-ViT-bigG-14-laion2B-39B-b160k"

# the fp16 SDXL VAE is numerically broken upstream; the reference (and
# diffusers) substitute the community fix (sd.rs:60-75)
_SDXL_VAE_FP16_FIX = ("madebyollin/sdxl-vae-fp16-fix",
                      "diffusion_pytorch_model.safetensors")


def _component_repo_file(component: str, version: str, use_f16: bool):
    v = getattr(version, "value", version)  # SDVersion enum or str
    if v not in _REPOS:
        raise ValueError(f"unknown SD version '{v}'")
    suffix = ".fp16.safetensors" if use_f16 else ".safetensors"
    if component == "tokenizer":
        return _TOKENIZER_REPOS[v], "tokenizer.json"
    if component == "tokenizer_2":
        return _TOKENIZER2_REPO, "tokenizer.json"
    if component == "clip":
        return _REPOS[v], f"text_encoder/model{suffix}"
    if component == "clip2":
        return _REPOS[v], f"text_encoder_2/model{suffix}"
    if component == "unet":
        return _REPOS[v], f"unet/diffusion_pytorch_model{suffix}"
    if component == "vae":
        if v in ("xl", "turbo") and use_f16:
            return _SDXL_VAE_FP16_FIX
        return _REPOS[v], f"vae/diffusion_pytorch_model{suffix}"
    raise ValueError(f"unknown SD component '{component}'")


def _offline() -> bool:
    return (os.environ.get("HF_HUB_OFFLINE", "") not in ("", "0")
            or os.environ.get("CAKE_HUB_OFFLINE", "") not in ("", "0"))


def resolve_sd_asset(component: str, version, *,
                     filename: Optional[str] = None, use_f16: bool = True,
                     cache_dir: Optional[str] = None,
                     allow_download: Optional[bool] = None) -> str:
    """Path to a component's weights/tokenizer file (see module docstring).

    component: tokenizer | tokenizer_2 | clip | clip2 | unet | vae
    """
    if filename:
        return filename
    repo, path = _component_repo_file(component, version, use_f16)
    if allow_download is None:
        allow_download = not _offline()

    try:
        from huggingface_hub import hf_hub_download, try_to_load_from_cache
    except ImportError as e:
        raise FileNotFoundError(
            f"SD {component} needs {repo}/{path}, but huggingface_hub is "
            f"unavailable ({e}); pass an explicit --sd-{component} path"
        ) from None

    for candidate in (repo, *_REPO_CACHE_ALIASES.get(repo, ())):
        cached = try_to_load_from_cache(candidate, path, cache_dir=cache_dir)
        if isinstance(cached, str) and os.path.exists(cached):
            log.info("sd: %s resolved from HF cache: %s", component, cached)
            return cached

    if allow_download:
        try:
            got = hf_hub_download(repo, path, cache_dir=cache_dir)
            log.info("sd: %s downloaded from hub: %s", component, got)
            return got
        except Exception as e:  # noqa: BLE001 — normalize network failures
            raise FileNotFoundError(
                f"SD {component}: {repo}/{path} not in the local HF cache "
                f"and the hub download failed ({type(e).__name__}: {e}); "
                f"pre-populate the cache or pass an explicit path"
            ) from None
    raise FileNotFoundError(
        f"SD {component}: {repo}/{path} not in the local HF cache and "
        "downloads are disabled (HF_HUB_OFFLINE/CAKE_HUB_OFFLINE); "
        "pre-populate the cache or pass an explicit path")
