"""AutoencoderKL (functional JAX, NHWC).

Capability parity with the reference's VAE wrapper over candle's
AutoEncoderKL (sd/vae.rs:13-108): `encode` samples the posterior (img2img
init latents), `decode` maps latents back to pixels. Architecture follows
diffusers AutoencoderKL (encoder: downsampling ResnetBlocks + mid with one
self-attention; decoder mirrors it), so SD checkpoints map on.

The reference multiplexes encode/decode through one packed-tensor RPC with
a direction flag (vae.rs:42-62); here they are simply two functions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cake_tpu.models.sd.config import VAEConfig
from cake_tpu.models.sd.layers import conv2d, group_norm, mha, nearest_upsample_2x
from cake_tpu.models.sd.unet import _KeyGen, _conv_p, _norm_p


def _res_p(kg, cin, cout, dtype):
    p = {
        "norm1": _norm_p(cin, dtype),
        "conv1": _conv_p(kg, 3, 3, cin, cout, dtype),
        "norm2": _norm_p(cout, dtype),
        "conv2": _conv_p(kg, 3, 3, cout, cout, dtype),
    }
    if cin != cout:
        p["shortcut"] = _conv_p(kg, 1, 1, cin, cout, dtype)
    return p


def _attn_p(kg, c, dtype):
    return {
        "norm": _norm_p(c, dtype),
        "q": _conv_p(kg, 1, 1, c, c, dtype),
        "k": _conv_p(kg, 1, 1, c, c, dtype),
        "v": _conv_p(kg, 1, 1, c, c, dtype),
        "o": _conv_p(kg, 1, 1, c, c, dtype),
    }


def init_vae_params(cfg: VAEConfig, rng, dtype=jnp.float32):
    kg = _KeyGen(rng)
    ch = cfg.block_out_channels
    n = len(ch)
    lat = cfg.latent_channels

    enc = {"conv_in": _conv_p(kg, 3, 3, cfg.in_channels, ch[0], dtype),
           "down": []}
    for i in range(n):
        cin = ch[i - 1] if i > 0 else ch[0]
        block = {"resnets": [
            _res_p(kg, cin if j == 0 else ch[i], ch[i], dtype)
            for j in range(cfg.layers_per_block)
        ]}
        if i < n - 1:
            block["downsample"] = _conv_p(kg, 3, 3, ch[i], ch[i], dtype)
        enc["down"].append(block)
    enc["mid"] = {
        "resnet1": _res_p(kg, ch[-1], ch[-1], dtype),
        "attn": _attn_p(kg, ch[-1], dtype),
        "resnet2": _res_p(kg, ch[-1], ch[-1], dtype),
    }
    enc["norm_out"] = _norm_p(ch[-1], dtype)
    enc["conv_out"] = _conv_p(kg, 3, 3, ch[-1], 2 * lat, dtype)
    enc["quant_conv"] = _conv_p(kg, 1, 1, 2 * lat, 2 * lat, dtype)

    dec = {"post_quant_conv": _conv_p(kg, 1, 1, lat, lat, dtype),
           "conv_in": _conv_p(kg, 3, 3, lat, ch[-1], dtype)}
    dec["mid"] = {
        "resnet1": _res_p(kg, ch[-1], ch[-1], dtype),
        "attn": _attn_p(kg, ch[-1], dtype),
        "resnet2": _res_p(kg, ch[-1], ch[-1], dtype),
    }
    dec["up"] = []
    rev = list(reversed(ch))
    for i in range(n):
        cin = rev[i - 1] if i > 0 else rev[0]
        block = {"resnets": [
            _res_p(kg, cin if j == 0 else rev[i], rev[i], dtype)
            for j in range(cfg.layers_per_block + 1)
        ]}
        if i < n - 1:
            block["upsample"] = _conv_p(kg, 3, 3, rev[i], rev[i], dtype)
        dec["up"].append(block)
    dec["norm_out"] = _norm_p(ch[0], dtype)
    dec["conv_out"] = _conv_p(kg, 3, 3, ch[0], cfg.in_channels, dtype)
    return {"encoder": enc, "decoder": dec}


def _res(p, x, groups):
    h = group_norm(x, p["norm1"]["w"], p["norm1"]["b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv1"]["w"], p["conv1"]["b"])
    h = group_norm(h, p["norm2"]["w"], p["norm2"]["b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv2"]["w"], p["conv2"]["b"])
    if "shortcut" in p:
        x = conv2d(x, p["shortcut"]["w"], p["shortcut"]["b"], padding=0)
    return x + h


def _self_attn(p, x, groups):
    B, H, W, C = x.shape
    h = group_norm(x, p["norm"]["w"], p["norm"]["b"], groups)
    q = conv2d(h, p["q"]["w"], p["q"]["b"], padding=0).reshape(B, H * W, C)
    k = conv2d(h, p["k"]["w"], p["k"]["b"], padding=0).reshape(B, H * W, C)
    v = conv2d(h, p["v"]["w"], p["v"]["b"], padding=0).reshape(B, H * W, C)
    attn = mha(q, k, v, num_heads=1).reshape(B, H, W, C)
    return x + conv2d(attn, p["o"]["w"], p["o"]["b"], padding=0)


def vae_encode(params, cfg: VAEConfig, images, rng=None,
               sample: bool = True):
    """images [B, H, W, 3] in [-1, 1] -> latents [B, H/8, W/8, C_lat],
    scaled by scaling_factor (reference vae.rs:87-96 sample semantics)."""
    p = params["encoder"]
    g = cfg.num_groups
    x = conv2d(images, p["conv_in"]["w"], p["conv_in"]["b"])
    for block in p["down"]:
        for rp in block["resnets"]:
            x = _res(rp, x, g)
        if "downsample" in block:
            # diffusers pads (0,1,0,1) before stride-2 conv
            x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
            x = conv2d(x, block["downsample"]["w"], block["downsample"]["b"],
                       stride=2, padding=0)
    x = _res(p["mid"]["resnet1"], x, g)
    x = _self_attn(p["mid"]["attn"], x, g)
    x = _res(p["mid"]["resnet2"], x, g)
    x = group_norm(x, p["norm_out"]["w"], p["norm_out"]["b"], g)
    x = conv2d(jax.nn.silu(x), p["conv_out"]["w"], p["conv_out"]["b"])
    moments = conv2d(x, p["quant_conv"]["w"], p["quant_conv"]["b"], padding=0)
    mean, logvar = jnp.split(moments, 2, axis=-1)
    if sample:
        if rng is None:
            raise ValueError("sampling the VAE posterior needs an rng")
        std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
        mean = mean + std * jax.random.normal(rng, mean.shape, mean.dtype)
    return mean * cfg.scaling_factor


def vae_decode(params, cfg: VAEConfig, latents):
    """latents (scaled) -> images [B, H, W, 3] in [-1, 1]
    (reference vae.rs:98-108)."""
    p = params["decoder"]
    g = cfg.num_groups
    x = latents / cfg.scaling_factor
    x = conv2d(x, p["post_quant_conv"]["w"], p["post_quant_conv"]["b"],
               padding=0)
    x = conv2d(x, p["conv_in"]["w"], p["conv_in"]["b"])
    x = _res(p["mid"]["resnet1"], x, g)
    x = _self_attn(p["mid"]["attn"], x, g)
    x = _res(p["mid"]["resnet2"], x, g)
    for block in p["up"]:
        for rp in block["resnets"]:
            x = _res(rp, x, g)
        if "upsample" in block:
            x = nearest_upsample_2x(x)
            x = conv2d(x, block["upsample"]["w"], block["upsample"]["b"])
    x = group_norm(x, p["norm_out"]["w"], p["norm_out"]["b"], g)
    x = conv2d(jax.nn.silu(x), p["conv_out"]["w"], p["conv_out"]["b"])
    return x
