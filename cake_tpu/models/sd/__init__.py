"""Stable Diffusion in functional JAX: CLIP text encoder, UNet2DCondition,
AutoencoderKL, schedulers, and the guidance/denoise driver.

Capability parity with the reference's SD path (cake-core/src/models/sd/),
which wraps candle-transformers' SD building blocks (sd.rs:141-154,
unet.rs:72, vae.rs:78, clip.rs:91). Here each component is a pure-JAX
module with diffusers-compatible weight naming, so the same safetensors
checkpoints load; components are placed on devices by sharding, not by the
reference's pack-tensors-over-TCP RPC workaround (unet.rs:81-100 — an
artifact of single-tensor message framing that SPMD makes unnecessary).
"""

from cake_tpu.models.sd.config import SDConfig, get_sd_config  # noqa: F401
