"""UNet2DCondition (functional JAX, NHWC).

Capability parity with the reference's UNet wrapper over candle's
UNet2DConditionModel (sd/unet.rs:13-79). Architecture follows the
diffusers UNet2DConditionModel graph exactly (conv_in -> time embedding ->
down blocks (ResnetBlock2D + Transformer2D cross-attn) -> mid -> up blocks
with skip connections -> GroupNorm/SiLU/conv_out) so SD v1.5/v2.1/SDXL
checkpoints map onto it; the SDXL added-condition path (text_embeds +
time_ids -> add_embedding) is included.

Unlike the reference, the UNet takes (latents, context, timestep) as three
real arguments — the reference packs them into one tensor to fit its
single-tensor RPC frame (unet.rs:81-100); SPMD needs no such workaround.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from cake_tpu.models.sd.config import UNetConfig
from cake_tpu.models.sd.layers import (
    conv2d, group_norm, layer_norm, linear, mha, nearest_upsample_2x,
    timestep_embedding,
)


# -- init --------------------------------------------------------------------

def _w(rng, shape, dtype, scale=0.02):
    return (jax.random.normal(rng, shape, jnp.float32) * scale).astype(dtype)


class _KeyGen:
    def __init__(self, rng):
        self.rng = rng

    def __call__(self):
        self.rng, sub = jax.random.split(self.rng)
        return sub


def _conv_p(kg, kh, kw, cin, cout, dtype):
    return {"w": _w(kg(), (kh, kw, cin, cout), dtype),
            "b": jnp.zeros((cout,), dtype)}


def _lin_p(kg, cin, cout, dtype):
    return {"w": _w(kg(), (cin, cout), dtype), "b": jnp.zeros((cout,), dtype)}


def _norm_p(c, dtype):
    return {"w": jnp.ones((c,), dtype), "b": jnp.zeros((c,), dtype)}


def _resnet_p(kg, cin, cout, temb_dim, dtype):
    p = {
        "norm1": _norm_p(cin, dtype),
        "conv1": _conv_p(kg, 3, 3, cin, cout, dtype),
        "time_emb": _lin_p(kg, temb_dim, cout, dtype),
        "norm2": _norm_p(cout, dtype),
        "conv2": _conv_p(kg, 3, 3, cout, cout, dtype),
    }
    if cin != cout:
        p["shortcut"] = _conv_p(kg, 1, 1, cin, cout, dtype)
    return p


def _xformer_p(kg, channels, n_layers, ctx_dim, dtype):
    inner = 4 * channels
    blocks = []
    for _ in range(n_layers):
        blocks.append({
            "ln1": _norm_p(channels, dtype),
            "attn1": {"q": _lin_p(kg, channels, channels, dtype),
                      "k": _lin_p(kg, channels, channels, dtype),
                      "v": _lin_p(kg, channels, channels, dtype),
                      "o": _lin_p(kg, channels, channels, dtype)},
            "ln2": _norm_p(channels, dtype),
            "attn2": {"q": _lin_p(kg, channels, channels, dtype),
                      "k": _lin_p(kg, ctx_dim, channels, dtype),
                      "v": _lin_p(kg, ctx_dim, channels, dtype),
                      "o": _lin_p(kg, channels, channels, dtype)},
            "ln3": _norm_p(channels, dtype),
            "geglu": _lin_p(kg, channels, 2 * inner, dtype),
            "ff_out": _lin_p(kg, inner, channels, dtype),
        })
    return {
        "norm": _norm_p(channels, dtype),
        "proj_in": _lin_p(kg, channels, channels, dtype),
        "blocks": blocks,
        "proj_out": _lin_p(kg, channels, channels, dtype),
    }


def init_unet_params(cfg: UNetConfig, rng, dtype=jnp.float32):
    kg = _KeyGen(rng)
    ch = cfg.block_out_channels
    temb_dim = ch[0] * cfg.time_embed_dim_mult
    n_blocks = len(ch)

    params = {
        "conv_in": _conv_p(kg, 3, 3, cfg.in_channels, ch[0], dtype),
        "time_mlp1": _lin_p(kg, ch[0], temb_dim, dtype),
        "time_mlp2": _lin_p(kg, temb_dim, temb_dim, dtype),
    }
    if cfg.addition_embed_dim:
        params["add_mlp1"] = _lin_p(kg, cfg.addition_embed_dim, temb_dim, dtype)
        params["add_mlp2"] = _lin_p(kg, temb_dim, temb_dim, dtype)

    skip_ch: List[int] = [ch[0]]
    down = []
    for i in range(n_blocks):
        cin = ch[i - 1] if i > 0 else ch[0]
        cout = ch[i]
        block = {"resnets": [], "attns": []}
        for j in range(cfg.layers_per_block):
            block["resnets"].append(
                _resnet_p(kg, cin if j == 0 else cout, cout, temb_dim, dtype))
            if cfg.attn_blocks[i]:
                block["attns"].append(_xformer_p(
                    kg, cout, cfg.transformer_layers_per_block[i],
                    cfg.cross_attention_dim, dtype))
            skip_ch.append(cout)
        if i < n_blocks - 1:
            block["downsample"] = _conv_p(kg, 3, 3, cout, cout, dtype)
            skip_ch.append(cout)
        down.append(block)
    params["down"] = down

    c_mid = ch[-1]
    # mid block always carries cross-attention (SD1.5's last *down* block
    # doesn't, but its mid does, with 1 transformer layer; SDXL's mid uses
    # its deepest transformer depth)
    mid_layers = (cfg.transformer_layers_per_block[-1]
                  if cfg.attn_blocks[-1] else 1)
    params["mid"] = {
        "resnet1": _resnet_p(kg, c_mid, c_mid, temb_dim, dtype),
        "attn": _xformer_p(kg, c_mid, mid_layers,
                           cfg.cross_attention_dim, dtype),
        "resnet2": _resnet_p(kg, c_mid, c_mid, temb_dim, dtype),
    }

    up = []
    rev = list(reversed(ch))
    prev = ch[-1]
    for i in range(n_blocks):
        cout = rev[i]
        block = {"resnets": [], "attns": []}
        src_block = n_blocks - 1 - i
        for j in range(cfg.layers_per_block + 1):
            skip = skip_ch.pop()
            block["resnets"].append(
                _resnet_p(kg, prev + skip, cout, temb_dim, dtype))
            prev = cout
            if cfg.attn_blocks[src_block]:
                block["attns"].append(_xformer_p(
                    kg, cout, cfg.transformer_layers_per_block[src_block],
                    cfg.cross_attention_dim, dtype))
        if i < n_blocks - 1:
            block["upsample"] = _conv_p(kg, 3, 3, cout, cout, dtype)
        up.append(block)
    params["up"] = up

    params["norm_out"] = _norm_p(ch[0], dtype)
    params["conv_out"] = _conv_p(kg, 3, 3, ch[0], cfg.out_channels, dtype)
    return params


# -- forward -----------------------------------------------------------------

def _resnet(p, x, temb, groups):
    h = group_norm(x, p["norm1"]["w"], p["norm1"]["b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv1"]["w"], p["conv1"]["b"])
    t = linear(jax.nn.silu(temb), p["time_emb"]["w"], p["time_emb"]["b"])
    h = h + t[:, None, None, :]
    h = group_norm(h, p["norm2"]["w"], p["norm2"]["b"], groups)
    h = conv2d(jax.nn.silu(h), p["conv2"]["w"], p["conv2"]["b"])
    if "shortcut" in p:
        x = conv2d(x, p["shortcut"]["w"], p["shortcut"]["b"], padding=0)
    return x + h


def _geglu(p, x):
    proj = linear(x, p["w"], p["b"])
    a, gate = jnp.split(proj, 2, axis=-1)
    return a * jax.nn.gelu(gate)


def _transformer(p, x, context, heads, groups):
    """Transformer2DModel: spatial tokens attend to themselves + context."""
    B, H, W, C = x.shape
    residual = x
    h = group_norm(x, p["norm"]["w"], p["norm"]["b"], groups)
    h = h.reshape(B, H * W, C)
    h = linear(h, p["proj_in"]["w"], p["proj_in"]["b"])
    for bp in p["blocks"]:
        n = layer_norm(h, bp["ln1"]["w"], bp["ln1"]["b"])
        h = h + linear(
            mha(linear(n, bp["attn1"]["q"]["w"]),
                linear(n, bp["attn1"]["k"]["w"]),
                linear(n, bp["attn1"]["v"]["w"]), heads),
            bp["attn1"]["o"]["w"], bp["attn1"]["o"]["b"])
        n = layer_norm(h, bp["ln2"]["w"], bp["ln2"]["b"])
        h = h + linear(
            mha(linear(n, bp["attn2"]["q"]["w"]),
                linear(context, bp["attn2"]["k"]["w"]),
                linear(context, bp["attn2"]["v"]["w"]), heads),
            bp["attn2"]["o"]["w"], bp["attn2"]["o"]["b"])
        n = layer_norm(h, bp["ln3"]["w"], bp["ln3"]["b"])
        h = h + linear(_geglu(bp["geglu"], n),
                       bp["ff_out"]["w"], bp["ff_out"]["b"])
    h = linear(h, p["proj_out"]["w"], p["proj_out"]["b"])
    return h.reshape(B, H, W, C) + residual


def unet_forward(params, cfg: UNetConfig, latents, timesteps, context,
                 added_cond: Optional[dict] = None):
    """latents [B, H, W, C_in] (NHWC), timesteps [B], context [B, S, ctx_dim]
    -> noise prediction [B, H, W, C_out]."""
    ch = cfg.block_out_channels
    groups = cfg.num_groups
    temb = timestep_embedding(timesteps, ch[0])
    temb = linear(jax.nn.silu(
        linear(temb.astype(latents.dtype), params["time_mlp1"]["w"],
               params["time_mlp1"]["b"])),
        params["time_mlp2"]["w"], params["time_mlp2"]["b"])
    if cfg.addition_embed_dim and added_cond is not None:
        # SDXL: concat(pooled text_embeds, fourier(time_ids)) -> MLP -> add
        te = added_cond["text_embeds"]
        tids = added_cond["time_ids"]  # [B, 6]
        tid_emb = timestep_embedding(
            tids.reshape(-1), 256).reshape(te.shape[0], -1)
        add = jnp.concatenate([te, tid_emb.astype(te.dtype)], axis=-1)
        add = linear(jax.nn.silu(
            linear(add, params["add_mlp1"]["w"], params["add_mlp1"]["b"])),
            params["add_mlp2"]["w"], params["add_mlp2"]["b"])
        temb = temb + add

    x = conv2d(latents, params["conv_in"]["w"], params["conv_in"]["b"])
    skips = [x]
    n_blocks = len(ch)

    for i, block in enumerate(params["down"]):
        heads = cfg.attention_head_dim[i]
        for j, rp in enumerate(block["resnets"]):
            x = _resnet(rp, x, temb, groups)
            if block["attns"]:
                x = _transformer(block["attns"][j], x, context, heads, groups)
            skips.append(x)
        if "downsample" in block:
            x = conv2d(x, block["downsample"]["w"], block["downsample"]["b"],
                       stride=2)
            skips.append(x)

    mid_heads = cfg.attention_head_dim[-1]
    x = _resnet(params["mid"]["resnet1"], x, temb, groups)
    x = _transformer(params["mid"]["attn"], x, context, mid_heads, groups)
    x = _resnet(params["mid"]["resnet2"], x, temb, groups)

    for i, block in enumerate(params["up"]):
        src_block = n_blocks - 1 - i
        heads = cfg.attention_head_dim[src_block]
        for j, rp in enumerate(block["resnets"]):
            skip = skips.pop()
            x = _resnet(rp, jnp.concatenate([x, skip], axis=-1), temb, groups)
            if block["attns"]:
                x = _transformer(block["attns"][j], x, context, heads, groups)
        if "upsample" in block:
            x = nearest_upsample_2x(x)
            x = conv2d(x, block["upsample"]["w"], block["upsample"]["b"])

    x = group_norm(x, params["norm_out"]["w"], params["norm_out"]["b"], groups)
    x = conv2d(jax.nn.silu(x), params["conv_out"]["w"], params["conv_out"]["b"])
    return x
