"""Diffusion noise schedulers: DDIM and Euler-discrete.

Capability parity with the reference's scheduler construction + step loop
(sd/sd.rs:429-431, 464-507; the reference borrows candle's schedulers and
wraps them in an unsafe-Send shim, safe_scheduler.rs:1-5 — no shim needed
here: schedulers are plain pytrees + pure functions, jit-compatible so the
whole denoise loop can run on-device under `lax.fori_loop`).

Beta schedule: scaled-linear (sqrt-space linear), the SD default.
Supports epsilon and v-prediction parameterisations (v2.1-768 uses v).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SchedulerConfig:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    prediction_type: str = "epsilon"   # or "v_prediction"
    kind: str = "ddim"                 # or "euler"


def _alphas_cumprod(cfg: SchedulerConfig) -> np.ndarray:
    betas = np.linspace(cfg.beta_start ** 0.5, cfg.beta_end ** 0.5,
                        cfg.num_train_timesteps, dtype=np.float64) ** 2
    return np.cumprod(1.0 - betas)


@dataclass(frozen=True)
class Schedule:
    """Precomputed per-inference-step state (host-side, static)."""

    config: SchedulerConfig
    timesteps: np.ndarray        # [steps] int32, descending
    alphas_cumprod: np.ndarray   # [train_timesteps] f64
    sigmas: np.ndarray           # [steps+1] (euler only; zeros for ddim)
    init_noise_sigma: float

    @classmethod
    def create(cls, cfg: SchedulerConfig, num_steps: int) -> "Schedule":
        ac = _alphas_cumprod(cfg)
        step = cfg.num_train_timesteps // num_steps
        ts = (np.arange(num_steps) * step).round()[::-1].astype(np.int32)
        if cfg.kind == "euler":
            sig = np.sqrt((1 - ac[ts]) / ac[ts])
            sigmas = np.concatenate([sig, [0.0]])
            init_sigma = float(sig.max())
        else:
            sigmas = np.zeros(num_steps + 1)
            init_sigma = 1.0
        return cls(cfg, ts, ac, sigmas, init_sigma)

    # -- common API (mirrors the reference's scheduler usage) ---------------

    def scale_model_input(self, latents, step_idx: int):
        """Euler scales by 1/sqrt(sigma^2+1); DDIM is identity
        (reference sd.rs:476-478 equivalent)."""
        if self.config.kind == "euler":
            sigma = self.sigmas[step_idx]
            return latents / float(np.sqrt(sigma ** 2 + 1.0))
        return latents

    def step(self, model_out, step_idx: int, latents):
        """One denoise update. All inputs jnp arrays; returns new latents."""
        t = int(self.timesteps[step_idx])
        if self.config.kind == "euler":
            return self._euler_step(model_out, step_idx, latents)
        return self._ddim_step(model_out, t, step_idx, latents)

    def _pred_x0_eps(self, model_out, latents, a_t):
        """(x0, eps) from the model output under the parameterisation."""
        sqrt_a = float(np.sqrt(a_t))
        sqrt_1ma = float(np.sqrt(1.0 - a_t))
        if self.config.prediction_type == "v_prediction":
            x0 = sqrt_a * latents - sqrt_1ma * model_out
            eps = sqrt_a * model_out + sqrt_1ma * latents
        else:
            x0 = (latents - sqrt_1ma * model_out) / sqrt_a
            eps = model_out
        return x0, eps

    def _ddim_step(self, model_out, t, step_idx, latents):
        a_t = self.alphas_cumprod[t]
        prev_i = step_idx + 1
        if prev_i < len(self.timesteps):
            a_prev = self.alphas_cumprod[int(self.timesteps[prev_i])]
        else:
            a_prev = 1.0
        x0, eps = self._pred_x0_eps(model_out, latents, a_t)
        dir_xt = float(np.sqrt(1.0 - a_prev)) * eps
        return float(np.sqrt(a_prev)) * x0 + dir_xt

    def _euler_step(self, model_out, step_idx, latents):
        sigma = float(self.sigmas[step_idx])
        sigma_next = float(self.sigmas[step_idx + 1])
        # latents here live in sigma-space (x = x0 + sigma*eps)
        if self.config.prediction_type == "v_prediction":
            denom = sigma ** 2 + 1.0
            x0 = latents / denom - model_out * sigma / float(np.sqrt(denom))
        else:
            x0 = latents - sigma * model_out
        d = (latents - x0) / sigma
        return latents + d * (sigma_next - sigma)

    def add_noise(self, x0, noise, step_idx: int):
        """Noise clean latents to the given step (img2img entry point,
        reference sd.rs:408-419). step_idx == num_steps means strength ~ 0:
        no denoising steps remain, so the latents stay clean."""
        if step_idx >= len(self.timesteps):
            return x0
        if self.config.kind == "euler":
            sigma = float(self.sigmas[step_idx])
            return x0 + noise * sigma
        t = int(self.timesteps[step_idx])
        a = self.alphas_cumprod[t]
        return float(np.sqrt(a)) * x0 + float(np.sqrt(1 - a)) * noise
