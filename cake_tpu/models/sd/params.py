"""SD component weight loading from HF checkpoints.

CLIP loads from transformers-format safetensors (text_model.* names).
UNet/VAE load from diffusers-format safetensors (the same per-component
files the reference resolves out of the HF hub cache and feeds to candle,
sd/sd.rs:141-302, unet.rs:66-79, vae.rs:78) via a declarative name table
(`_unet_entries` / `_vae_entries`) that mirrors the init functions'
structure exactly. The inverse direction (`save_sd_component`) writes the
same format, which gives round-trip tests and diffusers interoperability.

Layout conversions (torch -> our NHWC functional layout):
  conv    [out, in, kh, kw]  -> [kh, kw, in, out]
  linear  [out, in]          -> [in, out]
  norm    direct
  proj_in/proj_out: SD1.5 stores 1x1 convs, v2.1/XL store linears
  (use_linear_projection) — accepted by rank, exported as linear.
  VAE mid attention: new checkpoints use to_q/.../to_out.0 linears, old
  ones query/key/value/proj_attn 1x1 convs — both accepted.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

import jax.numpy as jnp
import numpy as np

from cake_tpu.models.sd.config import (
    ClipConfig, SDConfig, UNetConfig, VAEConfig,
)
from cake_tpu.utils.loading import load_weights


def load_clip_params(model_dir: str, cfg: ClipConfig, dtype=jnp.float32):
    """transformers CLIPTextModel safetensors -> clip param pytree."""
    host = load_weights(model_dir)

    def t(name):  # [out,in] -> [in,out]
        return jnp.asarray(np.asarray(host[name]).T, dtype=dtype)

    def v(name):
        return jnp.asarray(np.asarray(host[name]), dtype=dtype)

    pre = "text_model."
    layers = []
    for i in range(cfg.num_hidden_layers):
        lp = f"{pre}encoder.layers.{i}."
        layers.append({
            "ln1": {"w": v(lp + "layer_norm1.weight"),
                    "b": v(lp + "layer_norm1.bias")},
            "q": {"w": t(lp + "self_attn.q_proj.weight"),
                  "b": v(lp + "self_attn.q_proj.bias")},
            "k": {"w": t(lp + "self_attn.k_proj.weight"),
                  "b": v(lp + "self_attn.k_proj.bias")},
            "v": {"w": t(lp + "self_attn.v_proj.weight"),
                  "b": v(lp + "self_attn.v_proj.bias")},
            "o": {"w": t(lp + "self_attn.out_proj.weight"),
                  "b": v(lp + "self_attn.out_proj.bias")},
            "ln2": {"w": v(lp + "layer_norm2.weight"),
                    "b": v(lp + "layer_norm2.bias")},
            "fc1": {"w": t(lp + "mlp.fc1.weight"),
                    "b": v(lp + "mlp.fc1.bias")},
            "fc2": {"w": t(lp + "mlp.fc2.weight"),
                    "b": v(lp + "mlp.fc2.bias")},
        })
    params = {
        "token_embed": v(pre + "embeddings.token_embedding.weight"),
        "pos_embed": v(pre + "embeddings.position_embedding.weight"),
        "layers": layers,
        "final_ln": {"w": v(pre + "final_layer_norm.weight"),
                     "b": v(pre + "final_layer_norm.bias")},
    }
    if "text_projection.weight" in host:
        params["text_projection"] = t("text_projection.weight")
    return params


# -- diffusers name tables ----------------------------------------------------

Entry = Tuple[Tuple, str, str]  # (pytree path, hf name prefix, kind)


def _resnet_entries(path, pre, has_shortcut, with_time=True) -> Iterator[Entry]:
    yield (*path, "norm1"), f"{pre}.norm1", "norm"
    yield (*path, "conv1"), f"{pre}.conv1", "conv"
    if with_time:
        yield (*path, "time_emb"), f"{pre}.time_emb_proj", "linear"
    yield (*path, "norm2"), f"{pre}.norm2", "norm"
    yield (*path, "conv2"), f"{pre}.conv2", "conv"
    if has_shortcut:
        yield (*path, "shortcut"), f"{pre}.conv_shortcut", "conv"


def _xformer_entries(path, pre, n_layers) -> Iterator[Entry]:
    yield (*path, "norm"), f"{pre}.norm", "norm"
    yield (*path, "proj_in"), f"{pre}.proj_in", "proj"
    for k in range(n_layers):
        b, bp = f"{pre}.transformer_blocks.{k}", (*path, "blocks", k)
        yield (*bp, "ln1"), f"{b}.norm1", "norm"
        for qkv, hf in (("q", "to_q"), ("k", "to_k"), ("v", "to_v")):
            yield (*bp, "attn1", qkv), f"{b}.attn1.{hf}", "linear_nobias"
        yield (*bp, "attn1", "o"), f"{b}.attn1.to_out.0", "linear"
        yield (*bp, "ln2"), f"{b}.norm2", "norm"
        for qkv, hf in (("q", "to_q"), ("k", "to_k"), ("v", "to_v")):
            yield (*bp, "attn2", qkv), f"{b}.attn2.{hf}", "linear_nobias"
        yield (*bp, "attn2", "o"), f"{b}.attn2.to_out.0", "linear"
        yield (*bp, "ln3"), f"{b}.norm3", "norm"
        yield (*bp, "geglu"), f"{b}.ff.net.0.proj", "linear"
        yield (*bp, "ff_out"), f"{b}.ff.net.2", "linear"
    yield (*path, "proj_out"), f"{pre}.proj_out", "proj"


def _unet_entries(cfg: UNetConfig) -> List[Entry]:
    """Every UNet leaf's (pytree path, diffusers name, conversion kind);
    iteration order mirrors init_unet_params so presence of optional leaves
    (shortcut / downsample / attns) matches exactly."""
    ch = cfg.block_out_channels
    n_blocks = len(ch)
    out: List[Entry] = [
        (("conv_in",), "conv_in", "conv"),
        (("time_mlp1",), "time_embedding.linear_1", "linear"),
        (("time_mlp2",), "time_embedding.linear_2", "linear"),
    ]
    if cfg.addition_embed_dim:
        out += [(("add_mlp1",), "add_embedding.linear_1", "linear"),
                (("add_mlp2",), "add_embedding.linear_2", "linear")]

    skip_ch: List[int] = [ch[0]]
    for i in range(n_blocks):
        cin = ch[i - 1] if i > 0 else ch[0]
        cout = ch[i]
        for j in range(cfg.layers_per_block):
            rin = cin if j == 0 else cout
            out += _resnet_entries(("down", i, "resnets", j),
                                   f"down_blocks.{i}.resnets.{j}",
                                   rin != cout)
            if cfg.attn_blocks[i]:
                out += _xformer_entries(
                    ("down", i, "attns", j),
                    f"down_blocks.{i}.attentions.{j}",
                    cfg.transformer_layers_per_block[i])
            skip_ch.append(cout)
        if i < n_blocks - 1:
            out.append((("down", i, "downsample"),
                        f"down_blocks.{i}.downsamplers.0.conv", "conv"))
            skip_ch.append(cout)

    mid_layers = (cfg.transformer_layers_per_block[-1]
                  if cfg.attn_blocks[-1] else 1)
    out += _resnet_entries(("mid", "resnet1"), "mid_block.resnets.0", False)
    out += _xformer_entries(("mid", "attn"), "mid_block.attentions.0",
                            mid_layers)
    out += _resnet_entries(("mid", "resnet2"), "mid_block.resnets.1", False)

    rev = list(reversed(ch))
    prev = ch[-1]
    for i in range(n_blocks):
        cout = rev[i]
        src_block = n_blocks - 1 - i
        for j in range(cfg.layers_per_block + 1):
            skip = skip_ch.pop()
            out += _resnet_entries(("up", i, "resnets", j),
                                   f"up_blocks.{i}.resnets.{j}",
                                   prev + skip != cout)
            prev = cout
            if cfg.attn_blocks[src_block]:
                out += _xformer_entries(
                    ("up", i, "attns", j),
                    f"up_blocks.{i}.attentions.{j}",
                    cfg.transformer_layers_per_block[src_block])
        if i < n_blocks - 1:
            out.append((("up", i, "upsample"),
                        f"up_blocks.{i}.upsamplers.0.conv", "conv"))

    out += [(("norm_out",), "conv_norm_out", "norm"),
            (("conv_out",), "conv_out", "conv")]
    return out


def _vae_attn_entries(path, pre) -> Iterator[Entry]:
    yield (*path, "norm"), f"{pre}.group_norm", "norm"
    yield (*path, "q"), f"{pre}.to_q", "attn1x1"
    yield (*path, "k"), f"{pre}.to_k", "attn1x1"
    yield (*path, "v"), f"{pre}.to_v", "attn1x1"
    yield (*path, "o"), f"{pre}.to_out.0", "attn1x1"


def _vae_entries(cfg: VAEConfig) -> List[Entry]:
    ch = cfg.block_out_channels
    n = len(ch)
    out: List[Entry] = [(("encoder", "conv_in"), "encoder.conv_in", "conv")]
    for i in range(n):
        cin = ch[i - 1] if i > 0 else ch[0]
        for j in range(cfg.layers_per_block):
            rin = cin if j == 0 else ch[i]
            out += _resnet_entries(
                ("encoder", "down", i, "resnets", j),
                f"encoder.down_blocks.{i}.resnets.{j}",
                rin != ch[i], with_time=False)
        if i < n - 1:
            out.append((("encoder", "down", i, "downsample"),
                        f"encoder.down_blocks.{i}.downsamplers.0.conv",
                        "conv"))
    out += _resnet_entries(("encoder", "mid", "resnet1"),
                           "encoder.mid_block.resnets.0", False,
                           with_time=False)
    out += _vae_attn_entries(("encoder", "mid", "attn"),
                             "encoder.mid_block.attentions.0")
    out += _resnet_entries(("encoder", "mid", "resnet2"),
                           "encoder.mid_block.resnets.1", False,
                           with_time=False)
    out += [(("encoder", "norm_out"), "encoder.conv_norm_out", "norm"),
            (("encoder", "conv_out"), "encoder.conv_out", "conv"),
            (("encoder", "quant_conv"), "quant_conv", "conv"),
            (("decoder", "post_quant_conv"), "post_quant_conv", "conv"),
            (("decoder", "conv_in"), "decoder.conv_in", "conv")]
    out += _resnet_entries(("decoder", "mid", "resnet1"),
                           "decoder.mid_block.resnets.0", False,
                           with_time=False)
    out += _vae_attn_entries(("decoder", "mid", "attn"),
                             "decoder.mid_block.attentions.0")
    out += _resnet_entries(("decoder", "mid", "resnet2"),
                           "decoder.mid_block.resnets.1", False,
                           with_time=False)
    rev = list(reversed(ch))
    for i in range(n):
        cin = rev[i - 1] if i > 0 else rev[0]
        for j in range(cfg.layers_per_block + 1):
            rin = cin if j == 0 else rev[i]
            out += _resnet_entries(
                ("decoder", "up", i, "resnets", j),
                f"decoder.up_blocks.{i}.resnets.{j}",
                rin != rev[i], with_time=False)
        if i < n - 1:
            out.append((("decoder", "up", i, "upsample"),
                        f"decoder.up_blocks.{i}.upsamplers.0.conv", "conv"))
    out += [(("decoder", "norm_out"), "decoder.conv_norm_out", "norm"),
            (("decoder", "conv_out"), "decoder.conv_out", "conv")]
    return out


# -- conversions --------------------------------------------------------------

# old-format VAE attention names (pre-Attention refactor diffusers)
_VAE_ATTN_LEGACY = {"to_q": "query", "to_k": "key", "to_v": "value",
                    "to_out.0": "proj_attn"}


def _hf_get(host: Dict, name: str, suffix: str):
    """host[name.suffix], falling back to legacy VAE attention names."""
    full = f"{name}.{suffix}"
    if full in host:
        return np.asarray(host[full])
    leaf = name.rsplit(".", 2)
    for new, old in _VAE_ATTN_LEGACY.items():
        if name.endswith(new):
            legacy = name[: -len(new)] + old + "." + suffix
            if legacy in host:
                return np.asarray(host[legacy])
    raise KeyError(f"missing tensor '{full}' (legacy fallbacks exhausted; "
                   f"near {leaf})")


def _from_hf(host: Dict, name: str, kind: str, dtype) -> Dict:
    w = _hf_get(host, name, "weight")
    if kind == "conv":
        leaf = {"w": w.transpose(2, 3, 1, 0), "b": _hf_get(host, name, "bias")}
    elif kind == "linear":
        leaf = {"w": w.T, "b": _hf_get(host, name, "bias")}
    elif kind == "linear_nobias":
        leaf = {"w": w.T, "b": np.zeros((w.shape[0],), w.dtype)}
    elif kind == "norm":
        leaf = {"w": w, "b": _hf_get(host, name, "bias")}
    elif kind == "proj":  # 1x1 conv (SD1.5) or linear (use_linear_projection)
        w2 = w[:, :, 0, 0] if w.ndim == 4 else w
        leaf = {"w": w2.T, "b": _hf_get(host, name, "bias")}
    elif kind == "attn1x1":  # our 1x1-conv storage; hf linear or conv
        w2 = (w.transpose(2, 3, 1, 0) if w.ndim == 4
              else w.T[None, None])
        leaf = {"w": w2, "b": _hf_get(host, name, "bias")}
    else:
        raise ValueError(f"unknown conversion kind '{kind}'")
    return {k: jnp.asarray(v, dtype=dtype) for k, v in leaf.items()}


def _to_hf(leaf: Dict, name: str, kind: str, out: Dict) -> None:
    w = np.asarray(leaf["w"], np.float32)
    if kind == "conv":
        out[f"{name}.weight"] = w.transpose(3, 2, 0, 1)
        out[f"{name}.bias"] = np.asarray(leaf["b"], np.float32)
    elif kind in ("linear", "proj"):
        out[f"{name}.weight"] = w.T
        out[f"{name}.bias"] = np.asarray(leaf["b"], np.float32)
    elif kind == "linear_nobias":
        out[f"{name}.weight"] = w.T
    elif kind == "norm":
        out[f"{name}.weight"] = w
        out[f"{name}.bias"] = np.asarray(leaf["b"], np.float32)
    elif kind == "attn1x1":
        out[f"{name}.weight"] = w[0, 0].T
        out[f"{name}.bias"] = np.asarray(leaf["b"], np.float32)
    else:
        raise ValueError(f"unknown conversion kind '{kind}'")


def _walk(tree, path):
    node = tree
    for p in path:
        node = node[p]
    return node


def _assign(root, path, value) -> None:
    """Set tree[path] = value, growing dicts/lists along the way (int path
    entries create lists, str entries create dicts)."""
    node = root
    for p, nxt in zip(path, path[1:]):
        empty = [] if isinstance(nxt, int) else {}
        if isinstance(p, int):
            while len(node) <= p:
                node.append(None)
            if node[p] is None:
                node[p] = empty
        elif p not in node:
            node[p] = empty
        node = node[p]
    last = path[-1]
    if isinstance(last, int):
        while len(node) <= last:
            node.append(None)
        node[last] = value
    else:
        node[last] = value


def _component_entries(component: str, cfg: SDConfig) -> List[Entry]:
    if component == "unet":
        return _unet_entries(cfg.unet)
    if component == "vae":
        return _vae_entries(cfg.vae)
    raise ValueError(f"unknown SD component '{component}'")


def load_unet_params(model_dir: str, cfg: UNetConfig, dtype=jnp.float32):
    return _load_tabular("unet", model_dir,
                         SDConfig(unet=cfg), dtype)


def load_vae_params(model_dir: str, cfg: VAEConfig, dtype=jnp.float32):
    return _load_tabular("vae", model_dir, SDConfig(vae=cfg), dtype)


def _load_tabular(component: str, model_dir: str, cfg: SDConfig, dtype):
    """Build the param pytree straight from the entry table — no throwaway
    random init (a real SD1.5 UNet is ~860M params; generating then
    discarding that would double peak memory for nothing). Structure is
    validated against the init function under eval_shape (free: traced,
    never computed), so table drift fails loudly here instead of as a
    KeyError mid-forward."""
    from functools import partial

    import jax

    host = load_weights(model_dir)
    params: Dict = {}
    for path, name, kind in _component_entries(component, cfg):
        _assign(params, path, _from_hf(host, name, kind, dtype))
    if component == "unet":
        # attention-free blocks still carry an empty attns list in the
        # init structure (unet_forward branches on `block["attns"]`)
        for side in ("down", "up"):
            for block in params[side]:
                block.setdefault("attns", [])

    if component == "unet":
        from cake_tpu.models.sd.unet import init_unet_params
        init = partial(init_unet_params, cfg.unet, jax.random.PRNGKey(0),
                       dtype)
    else:
        from cake_tpu.models.sd.vae import init_vae_params
        init = partial(init_vae_params, cfg.vae, jax.random.PRNGKey(0),
                       dtype)
    expect = jax.eval_shape(init)
    if jax.tree.structure(params) != jax.tree.structure(expect):
        raise ValueError(
            f"{component} checkpoint mapping does not match the model "
            f"structure for this config (entry-table drift?)")
    return params


def export_sd_component(component: str, params, cfg: SDConfig
                        ) -> Dict[str, np.ndarray]:
    """params pytree -> {diffusers tensor name: np.ndarray} (f32).

    The exact inverse of the loader; used by round-trip tests and for
    writing checkpoints other SD stacks can read."""
    out: Dict[str, np.ndarray] = {}
    for path, name, kind in _component_entries(component, cfg):
        _to_hf(_walk(params, path), name, kind, out)
    return out


def save_sd_component(component: str, params, cfg: SDConfig,
                      path: str) -> None:
    from cake_tpu.utils.loading import save_safetensors
    save_safetensors(path, export_sd_component(component, params, cfg))


def load_sd_component(component: str, path: str, cfg: SDConfig, dtype):
    """Real-weight loading for every SD component the reference ships
    (sd/sd.rs:141-302: clip, clip2, vae, unet)."""
    if component in ("clip", "clip2"):
        ccfg = cfg.clip if component == "clip" else cfg.clip2
        return load_clip_params(path, ccfg, dtype)
    if component == "unet":
        return load_unet_params(path, cfg.unet, dtype)
    if component == "vae":
        return load_vae_params(path, cfg.vae, dtype)
    raise ValueError(f"unknown SD component '{component}'")
