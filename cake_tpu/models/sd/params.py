"""SD component weight loading from HF checkpoints.

CLIP loads from transformers-format safetensors (text_model.* names).
UNet/VAE diffusers-format mapping lands with the quantised-serving work;
until then missing weights fall back to random init in SDGenerator.load
(this environment is zero-egress, so benches run random-init regardless —
the mapping only matters for real deployments).
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from cake_tpu.models.sd.config import ClipConfig, SDConfig
from cake_tpu.utils.loading import load_weights


def load_clip_params(model_dir: str, cfg: ClipConfig, dtype=jnp.float32):
    """transformers CLIPTextModel safetensors -> clip param pytree."""
    host = load_weights(model_dir)

    def t(name):  # [out,in] -> [in,out]
        return jnp.asarray(np.asarray(host[name]).T, dtype=dtype)

    def v(name):
        return jnp.asarray(np.asarray(host[name]), dtype=dtype)

    pre = "text_model."
    layers = []
    for i in range(cfg.num_hidden_layers):
        lp = f"{pre}encoder.layers.{i}."
        layers.append({
            "ln1": {"w": v(lp + "layer_norm1.weight"),
                    "b": v(lp + "layer_norm1.bias")},
            "q": {"w": t(lp + "self_attn.q_proj.weight"),
                  "b": v(lp + "self_attn.q_proj.bias")},
            "k": {"w": t(lp + "self_attn.k_proj.weight"),
                  "b": v(lp + "self_attn.k_proj.bias")},
            "v": {"w": t(lp + "self_attn.v_proj.weight"),
                  "b": v(lp + "self_attn.v_proj.bias")},
            "o": {"w": t(lp + "self_attn.out_proj.weight"),
                  "b": v(lp + "self_attn.out_proj.bias")},
            "ln2": {"w": v(lp + "layer_norm2.weight"),
                    "b": v(lp + "layer_norm2.bias")},
            "fc1": {"w": t(lp + "mlp.fc1.weight"),
                    "b": v(lp + "mlp.fc1.bias")},
            "fc2": {"w": t(lp + "mlp.fc2.weight"),
                    "b": v(lp + "mlp.fc2.bias")},
        })
    params = {
        "token_embed": v(pre + "embeddings.token_embedding.weight"),
        "pos_embed": v(pre + "embeddings.position_embedding.weight"),
        "layers": layers,
        "final_ln": {"w": v(pre + "final_layer_norm.weight"),
                     "b": v(pre + "final_layer_norm.bias")},
    }
    if "text_projection.weight" in host:
        params["text_projection"] = t("text_projection.weight")
    return params


def load_sd_component(component: str, path: str, cfg: SDConfig, dtype):
    if component in ("clip", "clip2"):
        ccfg = cfg.clip if component == "clip" else cfg.clip2
        return load_clip_params(path, ccfg, dtype)
    raise NotImplementedError(
        f"checkpoint loading for '{component}' is not wired up yet; "
        "omit the weight path to run with random init"
    )
