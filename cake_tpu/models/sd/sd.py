"""Stable Diffusion generator: text embeddings, guidance, denoise, decode.

Capability parity with the reference's SD driver (sd/sd.rs:322-532):
  * prompt + negative-prompt CLIP embeddings, concatenated for
    classifier-free guidance (sd.rs:567-644: pad/truncate to 77, uncond
    concat),
  * txt2img: random init latents from the seed (sd.rs:377-379, 446-455),
  * img2img: VAE-encode the init image, noise to `strength` (sd.rs:408-419),
  * per-timestep loop: scale input, UNet eps prediction on the doubled
    batch, guidance mix, scheduler step (sd.rs:464-507),
  * intermediary decodes every `intermediary_images` steps and final VAE
    decode to u8 RGB PNGs via a callback (sd.rs:509-565),
  * SD v1.5 / v2.1 / XL / Turbo presets (lib.rs:202-268), with XL's dual
    text encoders and added-condition embeddings.

TPU-first differences: the denoise step (doubled-batch UNet + guidance +
scheduler update) is one jitted program; components are placed on mesh
devices by sharding/device_put driven by topology.yml names
("clip"/"clip2"/"vae"/"unet", reference sd.rs:198-302) rather than by TCP
proxies.
"""

from __future__ import annotations

import io
import logging
import time
from functools import partial
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.args import ImageGenerationArgs, SDArgs, SDVersion
from cake_tpu.models.sd.clip import clip_encode, init_clip_params
from cake_tpu.models.sd.config import SDConfig, get_sd_config
from cake_tpu.models.sd.scheduler import Schedule, SchedulerConfig
from cake_tpu.models.sd.unet import init_unet_params, unet_forward
from cake_tpu.models.sd.vae import init_vae_params, vae_decode, vae_encode

log = logging.getLogger(__name__)


class SimpleClipTokenizer:
    """Fallback tokenizer when no tokenizer.json is supplied: CRC32 word
    ids (deterministic across processes, unlike salted str hash). Real
    deployments pass --sd-tokenizer, matching the reference's required
    tokenizer files (sd.rs:29-102)."""

    def __init__(self, vocab_size: int = 49408):
        self.vocab_size = vocab_size
        self.bos = vocab_size - 2
        self.eos = vocab_size - 1

    def encode(self, text: str, max_len: int = 77) -> List[int]:
        import zlib
        ids = [self.bos]
        for word in text.lower().split():
            ids.append(zlib.crc32(word.encode()) % (self.vocab_size - 2))
        ids = ids[: max_len - 1] + [self.eos]
        ids += [self.eos] * (max_len - len(ids))
        return ids


class HFClipTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer
        self.tok = Tokenizer.from_file(path)

    def encode(self, text: str, max_len: int = 77) -> List[int]:
        ids = list(self.tok.encode(text).ids)
        eos = ids[-1] if ids else 0
        if len(ids) > max_len:
            # keep the EOS terminal so the EOT-position pooling stays valid
            ids = ids[: max_len - 1] + [eos]
        return ids + [eos] * (max_len - len(ids))


class SDGenerator:
    """ImageGenerator implementation (reference models/mod.rs:66-71)."""

    MODEL_NAME = "stable-diffusion"

    def __init__(self, config: SDConfig, params: dict, tokenizers: list,
                 dtype=jnp.float32):
        self.config = config
        self.params = params          # {"clip":…, "clip2":?, "unet":…, "vae":…}
        self.tokenizers = tokenizers  # [tok] or [tok, tok2] for XL
        self.dtype = dtype
        self._unet_step = None
        self._mesh = None             # set by shard_for_mesh

    # -- multi-device / multi-host sharding -----------------------------------

    def shard_for_mesh(self, mesh) -> None:
        """Run the whole pipeline as ONE SPMD program over `mesh` (axis
        "dp"): component params replicate across every device, and the
        jitted denoise step shards its batch axis — with guidance the
        cond/uncond pair runs on different devices concurrently, and
        multi-image batches split dp-ways. This is the TPU-native form
        of the reference's SD distribution (clip/vae/unet on different
        machines, sd.rs:198-302): instead of shipping activations over
        TCP between per-component hosts, every process dispatches the
        same program and XLA moves the (tiny, latent-sized) activations
        over ICI/DCN. On a process-spanning mesh the followers replay
        whole-generation ops (cli._serve_multihost image mode).

        Mutually exclusive with per-component placement
        (place_components) — one program cannot mix committed-to-device
        and mesh-sharded operands."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        rep = NamedSharding(mesh, P())
        self.params = jax.tree.map(
            lambda x: jax.device_put(x, rep), self.params)
        self._mesh = mesh
        self._unet_step = None   # recompile against the mesh
        log.info("sd: sharded for mesh %s (dp=%d)", mesh.axis_names,
                 mesh.shape.get("dp", 1))

    def _replicated(self, tree):
        """Host values -> mesh-replicated global arrays (identical on
        every process by construction: same seed / same request args)."""
        if self._mesh is None:
            return tree
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        rep = NamedSharding(self._mesh, P())
        return jax.tree.map(
            lambda x: (jax.device_put(jnp.asarray(x), rep)
                       if hasattr(x, "shape") or isinstance(x, (int, float))
                       else x), tree)

    def _host(self, x) -> np.ndarray:
        """Device -> host, including process-spanning arrays (replicated
        shardings are not fully addressable under multi-controller; the
        local shard of a replicated array IS the full value)."""
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return np.asarray(x.addressable_shards[0].data)
        return np.asarray(x)

    # -- loading -------------------------------------------------------------

    @classmethod
    def load(cls, ctx, rng_seed: int = 0) -> "SDGenerator":
        """Build from Context: version preset + optional weight overrides
        (reference sd.rs:141-302). Without weight files, random init (the
        zero-egress test/bench path)."""
        sd_args: SDArgs = ctx.sd_args or SDArgs()
        cfg = get_sd_config(sd_args.sd_version, sd_args.sd_height,
                            sd_args.sd_width)
        dtype = jnp.bfloat16 if sd_args.sd_use_f16 else jnp.float32
        rng = jax.random.PRNGKey(rng_seed)
        k1, k2, k3, k4 = jax.random.split(rng, 4)

        import os

        from cake_tpu.models.sd.hub import resolve_sd_asset

        def resolve(component, explicit):
            """explicit path > HF cache > hub download (sd.rs:29-102);
            None when nothing resolves (caller falls back to random init).
            An explicit path that does NOT exist is a hard error — a typo'd
            --sd-* flag must not silently produce a random-weight model."""
            if explicit:
                if os.path.exists(explicit):
                    return explicit
                raise FileNotFoundError(
                    f"--sd-{component.replace('_', '-')} path does not "
                    f"exist: {explicit}")
            try:
                return resolve_sd_asset(component, sd_args.sd_version,
                                        use_f16=sd_args.sd_use_f16)
            except FileNotFoundError as e:
                log.warning("sd: %s", e)
                return None

        def maybe_load(component, path, init_fn):
            path = resolve(component, path)
            if path:
                from cake_tpu.models.sd.params import load_sd_component
                return load_sd_component(component, path, cfg, dtype)
            log.warning("sd: no weights for %s; using random init", component)
            return init_fn()

        def tokenizer_for(component, explicit):
            path = resolve(component, explicit)
            return HFClipTokenizer(path) if path else SimpleClipTokenizer()

        params = {
            "clip": maybe_load("clip", sd_args.sd_clip,
                               lambda: init_clip_params(cfg.clip, k1, dtype)),
            "unet": maybe_load("unet", sd_args.sd_unet,
                               lambda: init_unet_params(cfg.unet, k2, dtype)),
            "vae": maybe_load("vae", sd_args.sd_vae,
                              lambda: init_vae_params(cfg.vae, k3, dtype)),
        }
        toks = [tokenizer_for("tokenizer", sd_args.sd_tokenizer)]
        if cfg.clip2 is not None:
            params["clip2"] = maybe_load(
                "clip2", sd_args.sd_clip2,
                lambda: init_clip_params(cfg.clip2, k4, dtype))
            toks.append(tokenizer_for("tokenizer_2", sd_args.sd_tokenizer_2))

        gen = cls(cfg, params, toks, dtype)
        if ctx.topology is not None:
            gen.place_components(ctx.topology)
        return gen

    def place_components(self, topology) -> None:
        """Map components onto devices via topology names (the reference's
        clip/vae/unet worker assignment, sd.rs:198-302, done as placement)."""
        devices = jax.devices()
        for name in ("clip", "clip2", "vae", "unet"):
            found = topology.get_node_for_layer(name)
            if found is None or name not in self.params:
                continue
            node_name, node = found
            idx = node.devices[0] if node.devices else 0
            dev = devices[idx % len(devices)]
            self.params[name] = jax.device_put(self.params[name], dev)
            log.info("sd: %s -> %s (node %s)", name, dev, node_name)

    def _component_device(self, name):
        if self._mesh is not None:
            # mesh mode: every component lives (replicated) on the mesh;
            # activations flow inside one SPMD program, no transfers
            return None
        params = self.params.get(name)
        if params is None:
            return None
        leaf = jax.tree.leaves(params)[0]
        devs = leaf.devices() if hasattr(leaf, "devices") else None
        if devs and len(devs) == 1:
            return next(iter(devs))
        if devs and len(devs) > 1:
            # a manually multi-device component outside mesh mode needs a
            # sharding-aware transfer of activations; silently skipping
            # would resurface jit's incompatible-devices error with no
            # hint why
            raise NotImplementedError(
                f"SD component '{name}' is sharded over {len(devs)} "
                "devices without mesh mode; use shard_for_mesh for a "
                "whole-pipeline mesh, or place_components for one device "
                "per component")
        return None

    def _to_component(self, name, tree):
        """Move activations to the device hosting component `name` — the
        explicit stage-boundary transfer that replaces the reference's
        TCP tensor send to each worker (sd.rs:198-302). Without it, jit
        rejects arguments committed to different devices (it will not
        guess which placement was intended)."""
        dev = self._component_device(name)
        if dev is None:
            return tree
        return jax.tree.map(
            lambda x: (jax.device_put(x, dev)
                       if hasattr(x, "shape") else x), tree)

    # -- text embeddings ------------------------------------------------------

    def text_embeddings(self, prompt: str, uncond_prompt: str,
                        use_guidance: bool):
        """[2B or B, 77, ctx] context (+ XL added-cond dict)
        (reference sd.rs:567-644)."""
        cfg = self.config
        added = None

        def encode_with(tok, clip_params, clip_cfg, text, skip):
            ids = self._replicated(
                jnp.asarray([tok.encode(text)], dtype=jnp.int32))
            out = clip_encode(clip_params, clip_cfg, ids,
                              output_hidden_state=skip)
            # hand the embeddings to the UNet's device right away: the two
            # encoders may live on different devices, and the concat below
            # (like every later consumer) needs co-located operands
            return self._to_component("unet", out)

        # Clip-skip (-2, no final_ln) applies to the XL encoders only.
        # v2.1's ViT-H config ships pre-truncated to 23 layers — diffusers
        # and candle both use its final hidden state + final_ln.
        skip = -2 if cfg.version in (SDVersion.XL, SDVersion.TURBO) else -1
        cond, pooled = encode_with(self.tokenizers[0], self.params["clip"],
                                   cfg.clip, prompt, skip)
        if cfg.clip2 is not None:
            cond2, pooled2 = encode_with(self.tokenizers[1],
                                         self.params["clip2"], cfg.clip2,
                                         prompt, -2)
            cond = jnp.concatenate([cond, cond2], axis=-1)
            pooled = pooled2
        if not use_guidance:
            if cfg.clip2 is not None:
                added = {"text_embeds": pooled,
                         "time_ids": self._time_ids(1)}
            return cond, added

        un, un_pooled = encode_with(self.tokenizers[0], self.params["clip"],
                                    cfg.clip, uncond_prompt, skip)
        if cfg.clip2 is not None:
            un2, un_pooled2 = encode_with(self.tokenizers[1],
                                          self.params["clip2"], cfg.clip2,
                                          uncond_prompt, -2)
            un = jnp.concatenate([un, un2], axis=-1)
            un_pooled = un_pooled2
            added = {
                "text_embeds": jnp.concatenate([un_pooled, pooled], axis=0),
                "time_ids": self._time_ids(2),
            }
        return jnp.concatenate([un, cond], axis=0), added

    def _time_ids(self, b: int):
        h, w = self.config.height, self.config.width
        return jnp.tile(jnp.asarray([[h, w, 0, 0, h, w]], jnp.float32),
                        (b, 1))

    # -- the jitted denoise step ---------------------------------------------

    def _make_unet_step(self, guidance_scale: float, use_guidance: bool):
        # memoized so repeated requests reuse the compiled program
        key = (guidance_scale, use_guidance, self._mesh)
        if self._unet_step is not None and self._unet_step[0] == key:
            return self._unet_step[1]
        ucfg = self.config.unet
        mesh = self._mesh
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            dp_s = NamedSharding(mesh, P("dp"))
            rep_s = NamedSharding(mesh, P())

        @jax.jit
        def step(unet_params, latents, t, context, added):
            inp = (jnp.concatenate([latents, latents], axis=0)
                   if use_guidance else latents)
            ts = jnp.full((inp.shape[0],), t, jnp.float32)
            if (mesh is not None
                    and inp.shape[0] % mesh.shape["dp"] == 0):
                # shard the UNet batch over dp: with guidance the
                # cond/uncond halves denoise on different devices (the
                # UNet math is per-sample, so the only cross-device
                # traffic is the eps-sized guidance combine below).
                # Non-divisible batches stay replicated (still correct,
                # just not parallel)
                inp = jax.lax.with_sharding_constraint(inp, dp_s)
                ts = jax.lax.with_sharding_constraint(ts, dp_s)
                context = jax.lax.with_sharding_constraint(context, dp_s)
                if added is not None:
                    added = jax.tree.map(
                        lambda x: jax.lax.with_sharding_constraint(
                            x, dp_s), added)
            eps = unet_forward(unet_params, ucfg, inp, ts, context,
                               added_cond=added)
            if use_guidance:
                eps_u, eps_c = jnp.split(eps, 2, axis=0)
                eps = eps_u + guidance_scale * (eps_c - eps_u)
            if mesh is not None:
                # the host-side scheduler reads eps; keep it replicated
                eps = jax.lax.with_sharding_constraint(eps, rep_s)
            return eps

        self._unet_step = (key, step)
        return step

    # -- generation -----------------------------------------------------------

    def generate_image(self, args: ImageGenerationArgs,
                       callback: Callable[[List[bytes]], None]) -> None:
        # --sd-tracing equivalent (reference sd.rs:350-356): profile the
        # whole generation to a Perfetto/TensorBoard trace directory.
        from cake_tpu.utils.profiling import trace
        with trace("sd-trace" if args.sd_tracing else None):
            self._generate_image(args, callback)

    def _generate_image(self, args: ImageGenerationArgs,
                        callback: Callable[[List[bytes]], None]) -> None:
        cfg = self.config
        steps = args.sd_n_steps or cfg.default_steps
        guidance = (args.sd_guidance_scale
                    if args.sd_guidance_scale is not None
                    else cfg.default_guidance)
        use_guidance = guidance > 1.0
        seed = args.sd_seed if args.sd_seed is not None else 299792458
        rng = jax.random.PRNGKey(seed)

        sched = Schedule.create(
            SchedulerConfig(
                prediction_type=cfg.prediction_type,
                kind="euler" if cfg.version in (SDVersion.XL, SDVersion.TURBO)
                else "ddim",
            ),
            steps,
        )
        context, added = self.text_embeddings(
            args.image_prompt, args.image_uncond_prompt, use_guidance)
        unet_step = self._make_unet_step(guidance, use_guidance)

        f = cfg.vae.downscale_factor
        lat_h, lat_w = cfg.height // f, cfg.width // f
        lat_c = cfg.vae.latent_channels
        bsize = args.sd_bsize

        # img2img init (reference sd.rs:408-419)
        init_latent, t_start = None, 0
        if args.sd_img2img:
            image = _image_preprocess(args.sd_img2img, cfg.height, cfg.width)
            rng, sub = jax.random.split(rng)
            init_latent = self._to_component("unet", vae_encode(
                self.params["vae"], cfg.vae,
                self._replicated(jnp.asarray(image, self.dtype)[None]),
                rng=self._replicated(sub)))
            t_start = max(steps - int(args.sd_img2img_strength * steps), 0)

        for sample_idx in range(args.sd_num_samples):
            rng, sub = jax.random.split(rng)
            noise = self._replicated(jax.random.normal(
                sub, (bsize, lat_h, lat_w, lat_c), self.dtype))
            if init_latent is not None:
                latents = sched.add_noise(
                    jnp.tile(init_latent, (bsize, 1, 1, 1)), noise, t_start)
            else:
                latents = noise * sched.init_noise_sigma

            ctx_b = self._replicated(
                jnp.repeat(context, bsize, axis=0)
                if bsize > 1 else context)
            added_b = added
            if added is not None and bsize > 1:
                added_b = {k: jnp.repeat(v, bsize, axis=0)
                           for k, v in added.items()}
            added_b = self._replicated(added_b)

            for i in range(t_start, steps):
                t0 = time.perf_counter()
                scaled = sched.scale_model_input(latents, i)
                eps = unet_step(self.params["unet"], scaled,
                                self._replicated(
                                    jnp.float32(sched.timesteps[i])),
                                ctx_b, added_b)
                latents = sched.step(eps, i, latents)
                log.info("sample %d step %d/%d (%.2fs)", sample_idx + 1,
                         i + 1, steps, time.perf_counter() - t0)
                if (args.sd_intermediary_images and i > t_start
                        and (i - t_start) % max(steps // 5, 1) == 0):
                    callback(self._decode_to_pngs(latents))
            callback(self._decode_to_pngs(latents))

    def _decode_to_pngs(self, latents) -> List[bytes]:
        """VAE decode -> u8 RGB -> PNG bytes (reference split_images,
        sd.rs:535-565)."""
        imgs = vae_decode(self.params["vae"], self.config.vae,
                          self._to_component("vae", latents))
        imgs = self._host(((jnp.clip(imgs, -1, 1) + 1.0) * 127.5)
                          .astype(jnp.uint8))
        out = []
        from PIL import Image
        for img in imgs:
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="PNG")
            out.append(buf.getvalue())
        return out


def _image_preprocess(path: str, height: int, width: int) -> np.ndarray:
    """Load + resize to multiples of 32, map to [-1, 1], NHWC
    (reference image_preprocess, sd.rs:647-665)."""
    from PIL import Image
    img = Image.open(path).convert("RGB")
    img = img.resize((width, height), Image.LANCZOS)
    arr = np.asarray(img, np.float32) / 127.5 - 1.0
    return arr
