"""Model-level abstractions: generator protocols, Token, chat types.

Reference: `Generator` / `TextGenerator` / `ImageGenerator` traits and
`Token` (cake-core/src/models/mod.rs:14-71).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Protocol, runtime_checkable


@dataclass
class Token:
    """One generated token (reference models/mod.rs:14-36)."""

    id: int
    text: str
    is_end_of_stream: bool = False

    def __str__(self) -> str:
        return "" if self.is_end_of_stream else self.text


@runtime_checkable
class TextGenerator(Protocol):
    """Reference models/mod.rs:52-64."""

    def add_message(self, message) -> None: ...
    def reset(self) -> None: ...
    def next_token(self, index: int) -> Token: ...
    def generated_tokens(self) -> int: ...


@runtime_checkable
class ImageGenerator(Protocol):
    """Reference models/mod.rs:66-71."""

    def generate_image(self, args, callback: Callable[[List[bytes]], None]) -> None: ...


from cake_tpu.models.chat import Message, MessageRole, History  # noqa: E402,F401
