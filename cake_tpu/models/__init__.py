"""Model-level abstractions: generator protocols, Token, chat types.

Reference: `Generator` / `TextGenerator` / `ImageGenerator` traits and
`Token` (cake-core/src/models/mod.rs:14-71).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Protocol, runtime_checkable


@dataclass
class Token:
    """One generated token (reference models/mod.rs:14-36)."""

    id: int
    text: str
    is_end_of_stream: bool = False

    def __str__(self) -> str:
        return "" if self.is_end_of_stream else self.text


@runtime_checkable
class TextGenerator(Protocol):
    """Reference models/mod.rs:52-64."""

    def add_message(self, message) -> None: ...
    def reset(self) -> None: ...
    def next_token(self, index: int) -> Token: ...
    def generated_tokens(self) -> int: ...


@runtime_checkable
class ImageGenerator(Protocol):
    """Reference models/mod.rs:66-71."""

    def generate_image(self, args, callback: Callable[[List[bytes]], None]) -> None: ...


from cake_tpu.models.chat import Message, MessageRole, History  # noqa: E402,F401


def load_text_params(config, model_dir: Optional[str], dtype, rng=None):
    """Parameter pytree for any text-model family, keyed by the config.

    HF safetensors when present under model_dir, else random init (with a
    warning). Family dispatch (dense Llama vs MoE) lives here, next to
    load_config's model_type dispatch, so app layers never branch on it.
    """
    import logging

    import jax

    from cake_tpu.utils.loading import has_weights

    is_moe = config.is_moe
    if is_moe:
        from cake_tpu.models.moe.params import (
            init_params, load_params_from_hf,
        )
    else:
        from cake_tpu.models.llama.params import (
            init_params, load_params_from_hf,
        )
    if has_weights(model_dir):
        return load_params_from_hf(model_dir, config, dtype=dtype)
    logging.getLogger(__name__).warning(
        "no weights at %r; using random init", model_dir)
    return init_params(config, rng if rng is not None
                       else jax.random.PRNGKey(0), dtype=dtype)
