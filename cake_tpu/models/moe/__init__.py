"""Sparse mixture-of-experts (Mixtral-style) model family.

Capability extension beyond the reference, which is dense-only
(`mlp.rs:7-11`; SURVEY.md §2.6 records expert parallelism as absent). The
family reuses the whole Llama stack — attention, KV cache, RoPE,
generator, serving engine, pipeline — because a block is just a pytree of
leaves: MoE blocks carry `router`/`we_*` leaves and `block_skeleton`
dispatches on their presence (models/llama/model.py). Expert math lives in
ops/moe.py; EP sharding specs in params.py here.
"""

from cake_tpu.models.moe.config import MoEConfig
from cake_tpu.models.moe.params import init_params, param_specs

__all__ = ["MoEConfig", "init_params", "param_specs"]
