"""MoE model hyperparameters (HF Mixtral `config.json` layout).

Extends LlamaConfig — everything but the FFN is identical Llama-3-family
architecture (GQA attention, RoPE, RMSNorm), which matches Mixtral's
design. `model_type: "mixtral"` in config.json selects this family
(context.py model dispatch).
"""

from __future__ import annotations

from dataclasses import dataclass

from cake_tpu.models.llama.config import LlamaConfig


@dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2

    @classmethod
    def from_hf_dict(cls, raw: dict) -> "MoEConfig":
        base = LlamaConfig.from_hf_dict(raw)
        return cls(
            **{f: getattr(base, f) for f in base.__dataclass_fields__},
            num_local_experts=raw.get("num_local_experts", 8),
            num_experts_per_tok=raw.get("num_experts_per_tok", 2),
        )

    @classmethod
    def tiny(cls, **overrides) -> "MoEConfig":
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, rms_norm_eps=1e-5, rope_theta=10000.0,
            max_position_embeddings=256, bos_token_id=1,
            eos_token_ids=(2,), tie_word_embeddings=False,
            num_local_experts=4, num_experts_per_tok=2,
        )
        base.update(overrides)
        return cls(**base)

    @classmethod
    def mixtral_8x7b(cls) -> "MoEConfig":
        return cls(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rms_norm_eps=1e-5, rope_theta=1e6,
            max_position_embeddings=32768, bos_token_id=1,
            eos_token_ids=(2,), num_local_experts=8, num_experts_per_tok=2,
            chat_template="mistral",
        )
