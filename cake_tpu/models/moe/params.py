"""MoE parameter pytree: init, HF (Mixtral) safetensors loading, EP specs.

Same stacked-[L, ...] layout as the Llama family (models/llama/params.py)
so the block walk is one `lax.scan`; expert weights add an E axis:
router [L, D, E], we_gate/we_up [L, E, D, F], we_down [L, E, F, D].
On-disk format is HF Mixtral safetensors
(model.layers.N.block_sparse_moe.gate.weight, .experts.K.{w1,w2,w3}.weight
— w1=gate, w2=down, w3=up), so public checkpoints load unchanged.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.params import _np_dtype
from cake_tpu.models.moe.config import MoEConfig


def init_params(config: MoEConfig, rng: jax.Array, dtype=jnp.bfloat16):
    """Random-init MoE parameter pytree (tests/benches)."""
    c = config
    L, D, F = c.num_hidden_layers, c.hidden_size, c.intermediate_size
    E = c.num_local_experts
    H, KV, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    keys = jax.random.split(rng, 12)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dtype)

    params = {
        "embed": w(keys[0], (c.vocab_size, D), D),
        "blocks": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": w(keys[1], (L, D, H * hd), D),
            "wk": w(keys[2], (L, D, KV * hd), D),
            "wv": w(keys[3], (L, D, KV * hd), D),
            "wo": w(keys[4], (L, H * hd, D), H * hd),
            "mlp_norm": jnp.ones((L, D), dtype),
            "router": w(keys[5], (L, D, E), D),
            "we_gate": w(keys[6], (L, E, D, F), D),
            "we_up": w(keys[7], (L, E, D, F), D),
            "we_down": w(keys[8], (L, E, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
        "lm_head": w(keys[9], (D, c.vocab_size), D),
    }
    if config.tie_word_embeddings:
        params["lm_head"] = params["embed"].T
    return params


MOE_PREFIX = "block_sparse_moe"
# our leaf -> (HF per-layer suffix, transpose); shared by the eager and
# streaming loaders so their trees cannot structurally diverge
MOE_ATTN_LAYOUT = {
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "router": (f"{MOE_PREFIX}.gate.weight", True),
}
# our expert leaf -> HF expert weight name (w1=gate, w3=up, w2=down)
MOE_EXPERT_LAYOUT = (("we_gate", "w1"), ("we_up", "w3"),
                     ("we_down", "w2"))


def load_params_from_hf(model_dir: str, config: MoEConfig,
                        dtype=jnp.bfloat16,
                        layer_range: Optional[range] = None):
    """Build the MoE pytree from HF Mixtral safetensors."""
    from cake_tpu.utils.loading import load_weights

    c = config
    L, E = c.num_hidden_layers, c.num_local_experts
    layers = list(layer_range) if layer_range is not None else list(range(L))
    nd = _np_dtype(dtype)

    moe = MOE_PREFIX
    needed = {"model.embed_tokens.weight", "model.norm.weight"}
    if not c.tie_word_embeddings:
        needed.add("lm_head.weight")
    attn = MOE_ATTN_LAYOUT
    for i in layers:
        for suffix, _t in attn.values():
            needed.add(f"model.layers.{i}.{suffix}")
        for e in range(E):
            for wn in ("w1", "w2", "w3"):
                needed.add(f"model.layers.{i}.{moe}.experts.{e}.{wn}.weight")

    host = load_weights(model_dir, filter_fn=lambda n: n in needed)

    def t(name, transpose):
        arr = np.asarray(host[name])
        return (arr.T if transpose else arr).astype(nd)

    blocks = {
        key: jnp.asarray(np.stack([
            t(f"model.layers.{i}.{suffix}", tr) for i in layers
        ]))
        for key, (suffix, tr) in attn.items()
    }
    # Experts: HF w1 [F, D] = gate, w3 [F, D] = up (both -> [D, F]);
    # w2 [D, F] = down (-> [F, D]).
    for key, wn in MOE_EXPERT_LAYOUT:
        blocks[key] = jnp.asarray(np.stack([
            np.stack([
                t(f"model.layers.{i}.{moe}.experts.{e}.{wn}.weight", True)
                for e in range(E)
            ]) for i in layers
        ]))

    params = {
        "blocks": blocks,
        "embed": jnp.asarray(t("model.embed_tokens.weight", False)),
        "final_norm": jnp.asarray(t("model.norm.weight", False)),
    }
    params["lm_head"] = (params["embed"].T if c.tie_word_embeddings
                         else jnp.asarray(t("lm_head.weight", True)))
    return params


def load_params_sharded(model_dir: str, config: MoEConfig, shardings,
                        dtype=jnp.bfloat16):
    """Stream HF Mixtral safetensors directly onto mesh shards — the MoE
    analog of models/llama/params.load_params_sharded: each leaf is a
    jax.make_array_from_callback over mmap views (prefetch disabled), so
    only locally addressable shard bytes are ever read. At Mixtral-8x22B
    scale the full tree (~280 GiB bf16) never fits one device; the
    sharded slices do. Reference behavior: worker-side subset
    materialisation (worker.rs:106-127), per shard.
    """
    from cake_tpu.models.llama.params import (
        make_stream_leaf_builders, stream_shard_of,
    )
    from cake_tpu.utils.loading import load_weights

    c = config
    L, E = c.num_hidden_layers, c.num_local_experts
    host = load_weights(model_dir, prefetch=False)
    nd = _np_dtype(dtype)
    simple_leaf, block_leaf = make_stream_leaf_builders(host, nd)
    shard_of = stream_shard_of(shardings)
    moe = MOE_PREFIX

    def expert_leaf(wn, sharding):
        # [L, E, in, out] stacked from per-expert [out, in] HF tensors
        views = [[host[f"model.layers.{i}.{moe}.experts.{e}.{wn}.weight"].T
                  for e in range(E)] for i in range(L)]
        shape = (L, E) + tuple(views[0][0].shape)

        def cb(index):
            sub = np.stack([
                np.stack([np.asarray(views[i][e][index[2:]])
                          for e in range(E)[index[1]]])
                for i in range(L)[index[0]]
            ])
            return sub.astype(nd, copy=False)

        return jax.make_array_from_callback(shape, sharding, cb)

    blocks = {
        key: block_leaf([f"model.layers.{i}.{suffix}" for i in range(L)],
                        tr, shard_of("blocks", key))
        for key, (suffix, tr) in MOE_ATTN_LAYOUT.items()}
    for key, wn in MOE_EXPERT_LAYOUT:
        blocks[key] = expert_leaf(wn, shard_of("blocks", key))

    params = {
        "blocks": blocks,
        "embed": simple_leaf("model.embed_tokens.weight", False,
                             shard_of("embed")),
        "final_norm": simple_leaf("model.norm.weight", False,
                                  shard_of("final_norm")),
    }
    params["lm_head"] = simple_leaf(
        "model.embed_tokens.weight" if c.tie_word_embeddings
        else "lm_head.weight", True, shard_of("lm_head"))
    return params


def param_specs(tp_axis: str = "tp", ep_axis: Optional[str] = "ep",
                stage_axis: Optional[str] = None):
    """PartitionSpec pytree: experts over ep, Megatron F-dim over tp.

    Under plain jit + NamedSharding, annotating the weights is all EP
    needs — XLA partitions the expert einsums in ops/moe.py and inserts
    the reduction. The router stays replicated (it is [D, E]-tiny).
    """
    from cake_tpu.models.llama.params import block_param_keys, block_specs
    return {
        "embed": P(tp_axis, None),
        "blocks": block_specs(block_param_keys(moe=True),
                              stage_axis=stage_axis, tp_axis=tp_axis,
                              ep_axis=ep_axis),
        "final_norm": P(None),
        "lm_head": P(None, tp_axis),
    }
