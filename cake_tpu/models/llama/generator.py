"""LlamaGenerator: stateful text generation over the functional model.

Capability parity with the reference's `LLama` driver (llama3/llama.rs):
  * first `next_token` call renders the chat history through the Llama-3
    template and tokenizes it (llama.rs:140-166, 281-283),
  * KV-cached decode feeds only the last token with its absolute position
    (llama.rs:285-298),
  * repeat-penalty over the last `repeat_last_n` tokens + sampling
    (llama.rs:311-326),
  * EOS detection (llama.rs:26-30, 339 — the reference checks a single id;
    we honor the config's full eos set, e.g. <|eot_id|> AND <|end_of_text|>),
  * `reset()` clears history/tokens/position (llama.rs:267-274). Unlike the
    reference — whose workers keep stale KV across REST requests
    (SURVEY.md §3.3) — reset here zeroes the entire cache explicitly.

TPU specifics: prompts are right-padded to bucket lengths so prefill
compiles once per bucket, not once per prompt length; decode is one cached
XLA program.  `generate_scan` runs the whole decode loop on-device via
`lax.scan` (zero host round-trips) for batch/throughput serving.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import Token
from cake_tpu.models.chat import History, Message
from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import (
    RopeTables, decode_step, forward, prefill,
)
from cake_tpu.ops.sampling import (
    SamplingConfig, sample_tokens, update_ring,
)

log = logging.getLogger(__name__)

PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


def bucket_length(n: int, max_seq_len: int) -> int:
    """Smallest bucket >= n (bounds the number of compiled prefill shapes)."""
    for b in PREFILL_BUCKETS:
        if b >= n and b <= max_seq_len:
            return b
    return max_seq_len


def chunk_windows(ids: List[int], C: int):
    """Yield (padded_window, n_real, start) fixed-C windows over a prompt —
    the ONE definition of the chunked-prefill windowing contract
    (right-padded final window, last real token at n_real - 1), shared by
    the sequential generator and the engine."""
    for start in range(0, len(ids), C):
        w = ids[start:start + C]
        n = len(w)
        yield w + [0] * (C - n), n, start


class ByteTokenizer:
    """Fallback tokenizer (tests / no tokenizer.json): UTF-8 bytes + offset."""

    OFFSET = 3  # leave room for pad/bos/eos

    def __init__(self, vocab_size: int = 259):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> List[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        data = bytes(max(0, i - self.OFFSET) for i in ids
                     if i >= self.OFFSET and i - self.OFFSET < 256)
        return data.decode("utf-8", errors="replace")


def load_tokenizer(model_dir: str):
    """HF tokenizer.json loader (same file the reference consumes)."""
    import os
    from tokenizers import Tokenizer
    path = os.path.join(model_dir, "tokenizer.json")
    return Tokenizer.from_file(path)


def encode_text(tokenizer, text: str) -> List[int]:
    """Tokenize, normalising HF `Encoding.ids` vs plain-list tokenizers."""
    enc = tokenizer.encode(text)
    return list(enc.ids if hasattr(enc, "ids") else enc)


def incremental_decode(tokenizer, ids: List[int],
                       pending: str, final: bool = False) -> Tuple[str, str]:
    """Streaming detokenization step: (new_text, updated_pending).

    Text is held back (empty delta) while the tail decodes to an incomplete
    UTF-8 sequence (the replacement char), so multi-token characters stream
    whole. final=True flushes a permanently-incomplete tail at end of
    stream — the streamed total must equal the buffered decode of the same
    ids."""
    full = tokenizer.decode(ids)
    new = full[len(pending):]
    if new.endswith("�") and not final:
        return "", pending
    return new, full


class LlamaGenerator:
    """TextGenerator implementation (reference models/mod.rs:52-64)."""

    MODEL_NAME = "llama3"

    def __init__(
        self,
        config: LlamaConfig,
        params,
        tokenizer,
        *,
        max_seq_len: int = 4096,
        batch_size: int = 1,
        sampling: Optional[SamplingConfig] = None,
        seed: int = 299792458,
        cache_dtype=jnp.bfloat16,
        forward_fn=None,
        cache: Optional[KVCache] = None,
        parallel=None,
        prefill_chunk: Optional[int] = None,
    ):
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.batch_size = batch_size
        self.sampling = sampling or SamplingConfig()
        self.rope = RopeTables.create(config, max_seq_len)
        # forward_fn: optional replacement for the single-device jitted
        # steps — e.g. parallel.pipeline.make_pipeline_forward's output when
        # a topology shards the model. Signature:
        #   forward_fn(params, tokens, cache, pos, rope,
        #              last_idx=None, is_prefill=False) -> (logits, cache)
        self._forward_fn = forward_fn
        # parallel: opaque (plan, mesh) context carried for consumers that
        # need to build matching-sharded state (Master.make_engine).
        self.parallel = parallel
        # prefill_chunk: process prompts in fixed windows of this many
        # tokens (one compiled program for ALL prompt lengths and chunk
        # positions, bounded activation memory); None = whole-prompt
        # prefill with bucketed shapes.
        if prefill_chunk is not None and (
                prefill_chunk < 1 or max_seq_len % prefill_chunk != 0):
            # a padded final window [start, start+C) must stay inside the
            # cache: dynamic_update_slice CLAMPS an out-of-range start and
            # would silently overwrite earlier live entries
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be >= 1 and divide "
                f"max_seq_len {max_seq_len}")
        self.prefill_chunk = prefill_chunk
        self.cache = cache if cache is not None else KVCache.create(
            config, batch_size, max_seq_len, dtype=cache_dtype)
        self.history = History(config.chat_template)
        self.rng = jax.random.PRNGKey(seed)
        self._reset_session()

    # -- TextGenerator protocol ---------------------------------------------

    def add_message(self, message: Message) -> None:
        self.history.add_message(message)

    def reset(self) -> None:
        """Clear chat + decode state (reference llama.rs:267-274), including
        the full KV cache (explicit pipeline-wide reset; see SURVEY.md §3.3
        for the reference wart this avoids)."""
        self.history.clear()
        self.cache = self.cache.fresh()
        self._reset_session()

    def _reset_session(self) -> None:
        self.tokens: List[int] = []      # all generated token ids
        self.index_pos = 0               # absolute position in the cache
        self._ring = jnp.full((self.batch_size, self.sampling.repeat_last_n),
                              -1, dtype=jnp.int32)
        self._pending_text = ""
        self._prompt_len = 0

    def generated_tokens(self) -> int:
        return len(self.tokens)

    def set_sampling(self, **overrides) -> None:
        """Apply per-request sampling overrides (None values ignored).

        SamplingConfig is a static jit arg, so a changed config costs one
        (cached thereafter) recompile of the tiny sample step only.
        """
        from dataclasses import replace
        kw = {k: v for k, v in overrides.items() if v is not None}
        if kw:
            self.sampling = replace(self.sampling, **kw)

    def next_token(self, index: int) -> Token:
        """Generate one token; index==0 triggers prompt prefill."""
        limit = getattr(self._forward_fn, "max_decode_tokens", None)
        if limit is not None and index >= limit:
            # e.g. the SP adapter's replicated decode tail is full; writing
            # past it would clamp over live cache entries
            raise ValueError(
                f"decode budget exhausted: this serving mode holds at most "
                f"{limit} generated tokens per session")
        if index == 0:
            logits = self._prefill_prompt()
        else:
            tok = jnp.full((self.batch_size, 1), self.tokens[-1], jnp.int32)
            if self._forward_fn is None:
                logits, self.cache = decode_step(
                    self.params, tok, jnp.int32(self.index_pos), self.cache,
                    self.rope, self.config,
                )
            else:
                logits, self.cache = self._forward_fn(
                    self.params, tok, self.cache, jnp.int32(self.index_pos),
                    self.rope,
                )
            self.index_pos += 1

        self.rng, sub = jax.random.split(self.rng)
        next_id = sample_tokens(sub, logits, self._ring, self.sampling)
        self._ring = update_ring(self._ring, next_id, len(self.tokens))
        tid = int(next_id[0])
        self.tokens.append(tid)

        if tid in self.config.eos_token_ids:
            # flush any held-back UTF-8 tail so the streamed total equals
            # the buffered decode of the same ids (engine parity)
            tail, self._pending_text = incremental_decode(
                self.tokenizer, self.tokens[:-1], self._pending_text,
                final=True)
            return Token(id=tid, text=tail, is_end_of_stream=True)
        return Token(id=tid, text=self._decode_incremental(), is_end_of_stream=False)

    # -- internals -----------------------------------------------------------

    def _encode_prompt(self) -> List[int]:
        ids = encode_text(self.tokenizer, self.history.render())
        # a custom forward may impose its own (inclusive) prompt bound —
        # e.g. the SP adapter's context window; dense decode needs one
        # free slot past the prompt
        limit = getattr(self._forward_fn, "max_prompt_len", None)
        if limit is None:
            limit = self.max_seq_len - 1
        if len(ids) > limit:
            raise ValueError(
                f"prompt length {len(ids)} exceeds limit {limit} "
                f"(max_seq_len {self.max_seq_len})"
            )
        return ids

    def _prefill_prompt(self):
        ids = self._encode_prompt()
        self._prompt_len = len(ids)
        C = self.prefill_chunk
        if C and len(ids) > C and self._forward_fn is None:
            logits = self._prefill_chunked(ids, C)
            self.index_pos = len(ids)
            return logits
        bucket = bucket_length(len(ids), self.max_seq_len)
        padded = ids + [0] * (bucket - len(ids))
        toks = jnp.asarray([padded] * self.batch_size, dtype=jnp.int32)
        plen = jnp.full((self.batch_size,), len(ids), dtype=jnp.int32)
        if self._forward_fn is None:
            logits, self.cache = prefill(
                self.params, toks, plen, self.cache, self.rope, self.config
            )
        else:
            logits, self.cache = self._forward_fn(
                self.params, toks, self.cache, jnp.int32(0), self.rope,
                last_idx=(plen - 1).astype(jnp.int32), is_prefill=True,
            )
        self.index_pos = len(ids)
        return logits

    def _prefill_chunked(self, ids: List[int], C: int):
        """Walk the prompt in fixed windows of C tokens: every chunk (and
        every future prompt) hits ONE compiled program, and attention per
        chunk runs against the growing cache (cache-aware flash kernel on
        TPU) instead of over a monolithic [S, S] window."""
        from cake_tpu.models.llama.model import prefill_chunk
        B = self.batch_size
        logits = None
        for window, n_real, start in chunk_windows(ids, C):
            toks = jnp.asarray([window] * B, dtype=jnp.int32)
            last_idx = jnp.full((B,), n_real - 1, dtype=jnp.int32)
            logits, self.cache = prefill_chunk(
                self.params, toks, jnp.int32(start), last_idx, self.cache,
                self.rope, self.config,
            )
        return logits

    def _decode_incremental(self) -> str:
        """Return newly-finalized text for the freshly appended token."""
        new, self._pending_text = incremental_decode(
            self.tokenizer, self.tokens, self._pending_text)
        return new

    # -- fully on-device generation (throughput path) ------------------------

    def generate_on_device(self, prompt_ids: np.ndarray, prompt_len: np.ndarray,
                           num_tokens: int) -> np.ndarray:
        """Generate num_tokens for a [B, S] batch with zero host round-trips.

        Returns [B, num_tokens] int32. EOS is not early-exited (static trip
        count keeps the program fixed-shape); callers trim at the first eos.
        Runs on a scratch cache — the interactive session cache/state is
        untouched. prompt_len must be uniform: decode positions are shared
        across the batch, and a shorter row would both attend pad-garbage KV
        and cache its tokens at the wrong RoPE positions. (Per-row positions
        arrive with the continuous-batching scheduler.)
        """
        plen_arr = np.asarray(prompt_len, dtype=np.int32)
        if not (plen_arr == plen_arr[0]).all():
            raise ValueError(
                "generate_on_device requires uniform prompt_len; "
                f"got {plen_arr.tolist()}"
            )
        plimit = getattr(self._forward_fn, "max_prompt_len", None)
        if plimit is not None and int(plen_arr[0]) > plimit:
            # e.g. the SP adapter's context window: a longer prompt would
            # silently truncate and zero the last-position hidden state
            raise ValueError(
                f"prompt length {int(plen_arr[0])} exceeds this serving "
                f"mode's prompt limit {plimit}")
        toks = jnp.asarray(prompt_ids, dtype=jnp.int32)
        plen = jnp.asarray(plen_arr)
        self.rng, sub = jax.random.split(self.rng)
        if self._forward_fn is not None:
            # a forward that allocates its own cache at prefill (SP) never
            # reads the one we pass — skip the full-size fresh() copy
            cache = (self.cache
                     if getattr(self._forward_fn, "allocates_cache", False)
                     else self.cache.fresh())
            return self._generate_hostloop(toks, plen, cache, sub,
                                           num_tokens)
        cache = self.cache.fresh()
        out, _ = _generate_scan(
            self.params, toks, plen, cache, self.rope, self.config,
            self.sampling, sub, num_tokens,
        )
        return np.asarray(out)

    def _generate_hostloop(self, toks, plen, cache, rng,
                           num_tokens: int) -> np.ndarray:
        """Host-stepped generation over a custom forward (pipeline path).

        The pipelined forward is already one compiled program per step with
        a donated cache; stepping it from the host matches the reference's
        master decode loop (master.rs:96-108) while every step stays a
        single XLA computation over the whole mesh.
        """
        B = toks.shape[0]
        fwd = self._forward_fn
        limit = getattr(fwd, "max_decode_tokens", None)
        if limit is not None and num_tokens > limit:
            raise ValueError(
                f"num_tokens {num_tokens} exceeds this serving mode's "
                f"decode budget of {limit} tokens per session")
        logits, cache = fwd(self.params, toks, cache, jnp.int32(0),
                            self.rope, last_idx=(plen - 1).astype(jnp.int32),
                            is_prefill=True)
        ring = jnp.full((B, self.sampling.repeat_last_n), -1, jnp.int32)
        rng, sub = jax.random.split(rng)
        first = sample_tokens(sub, logits, ring, self.sampling)
        ring = update_ring(ring, first, 0)
        if num_tokens > 1 and hasattr(fwd, "decode_scan"):
            # adapter provides an on-device multi-step decode (SP): the
            # remaining tokens cost ONE dispatch instead of one per token
            rest, cache, ring, rng = fwd.decode_scan(
                self.params, first[:, None], 0, cache, self.rope, rng,
                ring, num_steps=num_tokens - 1, sampling=self.sampling)
            out = jnp.concatenate([first[:, None], rest], axis=1)
            return np.asarray(out).astype(np.int32)
        outs = [np.asarray(first)]
        tok = first
        pos = int(np.max(np.asarray(plen)))
        for step in range(1, num_tokens):
            logits, cache = fwd(self.params, tok[:, None], cache,
                                jnp.int32(pos), self.rope)
            pos += 1
            rng, sub = jax.random.split(rng)
            tok = sample_tokens(sub, logits, ring, self.sampling)
            ring = update_ring(ring, tok, step)
            outs.append(np.asarray(tok))
        return np.stack(outs, axis=1).astype(np.int32)


@partial(jax.jit,
         static_argnames=("config", "sampling", "num_tokens"),
         donate_argnames=("cache",))
def _generate_scan(params, tokens, prompt_len, cache: KVCache,
                   rope: RopeTables, config: LlamaConfig,
                   sampling: SamplingConfig, rng, num_tokens: int):
    """prefill + num_tokens decode steps as one compiled program."""
    B = tokens.shape[0]
    last_idx = (prompt_len - 1).astype(jnp.int32)
    logits, cache = forward(params, tokens, cache, jnp.int32(0), rope,
                            config, last_idx=last_idx)
    ring0 = jnp.full((B, sampling.repeat_last_n), -1, dtype=jnp.int32)
    rng, sub = jax.random.split(rng)
    first = sample_tokens(sub, logits, ring0, sampling)
    ring0 = update_ring(ring0, first, 0)
    # decode positions are uniform only for uniform prompt_len; use max
    pos0 = jnp.max(prompt_len).astype(jnp.int32)

    def body(carry, step):
        cache, tok, ring, rng, pos = carry
        rng, sub = jax.random.split(rng)
        logits, cache = forward(params, tok[:, None], cache, pos, rope, config)
        nxt = sample_tokens(sub, logits, ring, sampling)
        ring = update_ring(ring, nxt, step)
        return (cache, nxt, ring, rng, pos + 1), nxt

    (cache, _, _, _, _), rest = jax.lax.scan(
        body, (cache, first, ring0, rng, pos0), jnp.arange(1, num_tokens)
    )
    out = jnp.concatenate([first[:, None], rest.T], axis=1)  # [B, num_tokens]
    return out, cache


def trim_at_eos(ids: np.ndarray, eos_ids: Tuple[int, ...]) -> List[List[int]]:
    """Cut each row at its first EOS token."""
    out = []
    for row in ids:
        cut = len(row)
        for j, t in enumerate(row):
            if int(t) in eos_ids:
                cut = j
                break
        out.append([int(t) for t in row[:cut]])
    return out
