"""Llama-3 model family: config, params, KV cache, forward fns, generator."""

from cake_tpu.models.llama.config import LlamaConfig  # noqa: F401
from cake_tpu.models.llama.cache import KVCache  # noqa: F401
from cake_tpu.models.llama import model  # noqa: F401
