"""Llama parameter pytree: init, HF-safetensors loading, sharding specs.

Layout decision (TPU-first): all decoder-block weights are **stacked along a
leading layer axis** `[L, ...]` so the block walk compiles as one
`lax.scan` — one XLA while-loop instead of L unrolled block programs
(faster compile, identical steady-state speed) — and a contiguous slice of
the stack *is* a pipeline stage's parameter shard.

Linear weights are stored `[in, out]` (x @ w), transposed from HF's
`[out, in]` at load. On-disk format stays HF safetensors with the exact
tensor names the reference consumes (model.layers.N.self_attn.q_proj.weight
etc. — transformer.rs:28-49), so any reference checkpoint loads unchanged.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.config import LlamaConfig


def init_params(config: LlamaConfig, rng: jax.Array, dtype=jnp.bfloat16):
    """Random-init parameter pytree (tests/benches; scale ~ 0.02)."""
    c = config
    L, D, F = c.num_hidden_layers, c.hidden_size, c.intermediate_size
    H, KV, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    keys = jax.random.split(rng, 12)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dtype)

    params = {
        "embed": w(keys[0], (c.vocab_size, D), D),
        "blocks": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": w(keys[1], (L, D, H * hd), D),
            "wk": w(keys[2], (L, D, KV * hd), D),
            "wv": w(keys[3], (L, D, KV * hd), D),
            "wo": w(keys[4], (L, H * hd, D), H * hd),
            "mlp_norm": jnp.ones((L, D), dtype),
            "w_gate": w(keys[5], (L, D, F), D),
            "w_up": w(keys[6], (L, D, F), D),
            "w_down": w(keys[7], (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
        "lm_head": w(keys[8], (D, c.vocab_size), D),
    }
    if config.attention_bias:
        # distinct keys: identical bk/bv would hide a k/v bias swap from
        # any value-sensitive test
        params["blocks"]["bq"] = w(keys[9], (L, H * hd), D)
        params["blocks"]["bk"] = w(keys[10], (L, KV * hd), D)
        params["blocks"]["bv"] = w(keys[11], (L, KV * hd), D)
    if config.tie_word_embeddings:
        params["lm_head"] = params["embed"].T
    return params


def init_params_quantized(config: LlamaConfig, rng: jax.Array,
                          dtype=jnp.bfloat16, bits: int = 8):
    """Random quantized params built directly on device (int8 per-channel
    or int4 group-wise, matching ``quantize_params(..., bits=bits)``).

    Produces the same pytree structure as ``quantize_params(init_params(...))``
    without ever materialising the full-precision tree — a bf16 8B tree is
    ~15 GiB, i.e. most of a v5e's HBM, so the quantize-after-init path is
    dead on arrival there. Benchmarks are weight-value independent
    (bench.py), so random weights + constant scales are as good as
    quantized real weights.
    """
    from cake_tpu.ops.quant import _BLOCK_CONTRACT, QTensor, pick_group

    c = config
    L, D, F = c.num_hidden_layers, c.hidden_size, c.intermediate_size
    H, KV, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    keys = jax.random.split(rng, 12)
    kit = iter(keys)

    def qleaf(shape, contract_dims, fan_in, leaf_bits=None):
        qmax = 127 if (leaf_bits or bits) == 8 else 7
        if (leaf_bits or bits) == 4:
            # random bytes ARE the packed group-halves stream — each
            # nibble is a uniform int4, which is all a weight-value-
            # independent benchmark needs
            cd = contract_dims[0]
            g = pick_group(shape[cd])
            q = jax.random.randint(
                next(kit), shape[:cd] + (shape[cd] // 2,) + shape[cd + 1:],
                0, 256, dtype=jnp.uint8)
            scale_shape = (shape[:cd] + (shape[cd] // g,) + shape[cd + 1:])
        else:
            q = jax.random.randint(next(kit), shape, -qmax, qmax + 1,
                                   dtype=jnp.int8)
            scale_shape = tuple(s for i, s in enumerate(shape)
                                if i not in contract_dims)
        # scale chosen so dequantized weights have the init std ~1/sqrt(fan_in)
        scale = jnp.full(scale_shape, 1.0 / (qmax * np.sqrt(fan_in)),
                         jnp.float32)
        return QTensor(q=q, scale=scale)

    def w(shape, fan_in):
        return (jax.random.normal(next(kit), shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(dtype)

    blocks = {
        "attn_norm": jnp.ones((L, D), dtype),
        "wq": qleaf((L, D, H * hd), _BLOCK_CONTRACT["wq"], D),
        "wk": qleaf((L, D, KV * hd), _BLOCK_CONTRACT["wk"], D),
        "wv": qleaf((L, D, KV * hd), _BLOCK_CONTRACT["wv"], D),
        "wo": qleaf((L, H * hd, D), _BLOCK_CONTRACT["wo"], H * hd),
        "mlp_norm": jnp.ones((L, D), dtype),
        "w_gate": qleaf((L, D, F), _BLOCK_CONTRACT["w_gate"], D),
        "w_up": qleaf((L, D, F), _BLOCK_CONTRACT["w_up"], D),
        "w_down": qleaf((L, F, D), _BLOCK_CONTRACT["w_down"], F),
    }
    if c.attention_bias:
        # full-precision, matching quantize_params (biases never quantize)
        blocks["bq"] = w((L, H * hd), D)
        blocks["bk"] = w((L, KV * hd), D)
        blocks["bv"] = w((L, KV * hd), D)
    return {
        "embed": w((c.vocab_size, D), D),
        "blocks": blocks,
        "final_norm": jnp.ones((D,), dtype),
        # lm_head stays int8 at bits=4 (quantize_params parity: the vocab
        # width fragments the int4 kernel's blocks; int8 is roofline there)
        "lm_head": qleaf((D, c.vocab_size), (0,), D, leaf_bits=8),
    }


# -- HF name mapping ---------------------------------------------------------

def hf_param_layout(config: LlamaConfig):
    """Map our pytree leaves -> (list of HF tensor names, assembler).

    Used both for loading (HF -> pytree) and by the split tool
    (pytree -> HF names).
    """
    L = config.num_hidden_layers
    layout = {
        ("embed",): ("model.embed_tokens.weight", False),
        ("final_norm",): ("model.norm.weight", False),
        ("lm_head",): ("lm_head.weight", True),
    }
    per_layer = {
        "attn_norm": ("input_layernorm.weight", False),
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "mlp_norm": ("post_attention_layernorm.weight", False),
        "w_gate": ("mlp.gate_proj.weight", True),
        "w_up": ("mlp.up_proj.weight", True),
        "w_down": ("mlp.down_proj.weight", True),
    }
    if config.attention_bias:
        per_layer.update({
            "bq": ("self_attn.q_proj.bias", False),
            "bk": ("self_attn.k_proj.bias", False),
            "bv": ("self_attn.v_proj.bias", False),
        })
    return layout, per_layer, L


def load_params_from_hf(
    model_dir: str,
    config: LlamaConfig,
    dtype=jnp.bfloat16,
    layer_range: Optional[range] = None,
    put: Optional[Callable[[np.ndarray, object], jax.Array]] = None,
    shardings: Optional[dict] = None,
):
    """Build the parameter pytree from HF safetensors.

    layer_range: only materialise these blocks (stage-local loading).
    put:         (host_array, sharding_or_None) -> device array; defaults to
                 jnp.asarray (single-device).
    shardings:   optional pytree of NamedShardings matching param_specs().
    """
    from cake_tpu.utils.loading import load_weights

    layout, per_layer, L = hf_param_layout(config)
    layers = list(layer_range) if layer_range is not None else list(range(L))

    needed = {name for (name, _t) in layout.values()}
    for i in layers:
        for hf_suffix, _t in per_layer.values():
            needed.add(f"model.layers.{i}.{hf_suffix}")
    if config.tie_word_embeddings:
        needed.discard("lm_head.weight")

    host = load_weights(model_dir, filter_fn=lambda n: n in needed)

    if put is None:
        def put(arr, sharding):
            x = jnp.asarray(np.asarray(arr), dtype=dtype)
            return jax.device_put(x, sharding) if sharding is not None else x

    def shard_of(*path):
        node = shardings
        for k in path:
            if node is None:
                return None
            node = node.get(k) if isinstance(node, dict) else None
        return node

    def leaf(name, transpose, sharding):
        arr = np.asarray(host[name])
        if transpose:
            arr = arr.T
        return put(arr.astype(_np_dtype(dtype)), sharding)

    params: Dict = {"blocks": {}}
    params["embed"] = leaf("model.embed_tokens.weight", False, shard_of("embed"))
    params["final_norm"] = leaf("model.norm.weight", False, shard_of("final_norm"))
    if config.tie_word_embeddings:
        params["lm_head"] = params["embed"].T
    else:
        params["lm_head"] = leaf("lm_head.weight", True, shard_of("lm_head"))

    for key, (hf_suffix, transpose) in per_layer.items():
        stack = np.stack([
            (np.asarray(host[f"model.layers.{i}.{hf_suffix}"]).T
             if transpose else np.asarray(host[f"model.layers.{i}.{hf_suffix}"]))
            for i in layers
        ])
        params["blocks"][key] = put(
            stack.astype(_np_dtype(dtype)), shard_of("blocks", key)
        )
    return params


def _np_dtype(jdtype):
    import ml_dtypes
    return {jnp.bfloat16: ml_dtypes.bfloat16,
            jnp.float16: np.float16,
            jnp.float32: np.float32}.get(jdtype, np.float32)


def make_stream_leaf_builders(host, nd):
    """(simple_leaf, block_leaf) closures for streaming sharded loads —
    shared by the dense and MoE loaders so the slice semantics cannot
    drift. host: name -> mmap view; nd: numpy target dtype."""

    def simple_leaf(name: str, transpose: bool, sharding):
        src = host[name].T if transpose else host[name]

        def cb(index):
            return np.ascontiguousarray(src[index]).astype(nd, copy=False)

        return jax.make_array_from_callback(tuple(src.shape), sharding, cb)

    def block_leaf(names, transpose: bool, sharding):
        views = [host[n] for n in names]
        views = [v.T if transpose else v for v in views]
        L = len(views)
        shape = (L,) + tuple(views[0].shape)

        def cb(index):
            sub = np.stack([np.asarray(views[i][index[1:]])
                            for i in range(L)[index[0]]])
            return sub.astype(nd, copy=False)

        return jax.make_array_from_callback(shape, sharding, cb)

    return simple_leaf, block_leaf


def stream_shard_of(shardings):
    def shard_of(*path):
        node = shardings
        for k in path:
            node = node[k]
        return node
    return shard_of


def load_params_sharded(model_dir: str, config: LlamaConfig, shardings,
                        dtype=jnp.bfloat16):
    """Stream HF safetensors directly onto mesh shards.

    The eager loader (load_params_from_hf) materialises the full tree on
    the default device — at 70B (~140 GiB bf16) that dies long before
    place_for_pipeline runs, even though the *sharded* model fits
    comfortably. This loader never builds a full host or device copy:
    each leaf is a `jax.make_array_from_callback` whose callback slices
    the mmap'd safetensors views, so only the bytes of locally
    addressable shards are ever read (mmap pages fault in per shard
    slice), matching the reference worker's materialise-only-your-layers
    behavior (worker.rs:106-127) per *shard* instead of per host.

    shardings: pytree of jax.sharding.Sharding matching the param tree
    ({"embed", "blocks": {leaf...}, "final_norm", "lm_head"}).
    """
    from cake_tpu.utils.loading import load_weights

    layout, per_layer, L = hf_param_layout(config)
    # host tensors stay zero-copy mmap views; nothing is read here —
    # prefetch=False keeps the native reader from madvise(WILLNEED)ing
    # the whole checkpoint (only shard slices will ever be touched)
    host = load_weights(model_dir, prefetch=False)
    simple_leaf, block_leaf = make_stream_leaf_builders(
        host, _np_dtype(dtype))
    shard_of = stream_shard_of(shardings)

    params: Dict = {
        "blocks": {
            key: block_leaf(
                [f"model.layers.{i}.{hf_suffix}" for i in range(L)],
                transpose, shard_of("blocks", key))
            for key, (hf_suffix, transpose) in per_layer.items()
        },
    }
    for (key,), (hf_name, transpose) in layout.items():
        if key == "lm_head" and config.tie_word_embeddings:
            # read the embed source again transposed instead of an eager
            # .T on the placed array (which would be a cross-process
            # eager op on a multi-host mesh)
            hf_name = "model.embed_tokens.weight"
        params[key] = simple_leaf(hf_name, transpose, shard_of(key))
    return params


# -- sharding ---------------------------------------------------------------

def block_param_keys(config=None, *, moe: Optional[bool] = None) -> tuple:
    """Stacked-block leaf names for a config's family (dense vs MoE)."""
    if moe is None:
        moe = bool(config is not None and config.is_moe)
    keys = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm"]
    if config is not None and getattr(config, "attention_bias", False):
        keys += ["bq", "bk", "bv"]
    keys += (["router", "we_gate", "we_up", "we_down"] if moe
             else ["w_gate", "w_up", "w_down"])
    return tuple(keys)


def block_specs(keys, stage_axis: Optional[str] = None,
                tp_axis: Optional[str] = None,
                ep_axis: Optional[str] = None):
    """PartitionSpecs for a set of stacked-block leaves, dense or MoE.

    Derives the spec dict from the actual pytree keys so every consumer
    (pipeline shard_map in_specs, placement, fits-in-HBM checks) handles
    both families without hardcoding a leaf list.
    """
    S, T, E = stage_axis, tp_axis, ep_axis
    table = {
        "attn_norm": P(S, None),
        "wq": P(S, None, T),
        "wk": P(S, None, T),
        "wv": P(S, None, T),
        # QKV bias (Qwen2): head dim sharded like the matching weight's
        # output dim
        "bq": P(S, T),
        "bk": P(S, T),
        "bv": P(S, T),
        "wo": P(S, T, None),
        "mlp_norm": P(S, None),
        "w_gate": P(S, None, T),
        "w_up": P(S, None, T),
        "w_down": P(S, T, None),
        # MoE leaves (models/moe): router replicated, experts over ep,
        # ffn dim over tp
        "router": P(S, None, None),
        "we_gate": P(S, E, None, T),
        "we_up": P(S, E, None, T),
        "we_down": P(S, E, T, None),
    }
    unknown = set(keys) - set(table)
    if unknown:
        raise KeyError(f"no PartitionSpec rule for block leaves {unknown}")
    return {k: table[k] for k in keys}


def param_specs(tp_axis: str = "tp", stage_axis: Optional[str] = None,
                config: Optional[LlamaConfig] = None):
    """PartitionSpec pytree for Megatron-style tensor parallelism.

    Column-parallel: q/k/v, gate/up (output dim over tp).
    Row-parallel:    o, down (input dim over tp).
    Embedding + lm_head sharded over vocab; norms replicated.
    stage_axis, if given, shards the stacked layer dim (pipeline via scan
    is NOT done this way — see parallel/pipeline.py — but a stage axis on
    the layer dim gives cheap weight-memory sharding for fits-in-HBM checks).
    config: pass the model config so family-dependent leaves (Qwen2's
    bq/bk/bv) get specs; without it the dense biasless set is assumed.
    """
    return {
        "embed": P(tp_axis, None),
        "blocks": block_specs(block_param_keys(config, moe=False),
                              stage_axis=stage_axis, tp_axis=tp_axis),
        "final_norm": P(None),
        "lm_head": P(None, tp_axis),
    }


def cache_specs(tp_axis: str = "tp", dp_axis: str = "dp",
                stage_axis: Optional[str] = None):
    """KVCache PartitionSpecs: [L, B, S, KV, hd] — batch over dp, kv-heads
    over tp."""
    from cake_tpu.models.llama.cache import KVCache
    return KVCache(
        k=P(stage_axis, dp_axis, None, tp_axis, None),
        v=P(stage_axis, dp_axis, None, tp_axis, None),
    )
