"""Paged KV cache: a shared page pool + per-slot page tables.

The capacity fix for many-slot serving (round-4 bench: 32 dense slots ×
max_seq_len slabs thrash HBM — 151 tok/s aggregate vs 408 at 16 slots):
instead of every slot owning a dense [max_seq_len] cache slab, KV lives
in a pool of fixed-size pages and each slot maps position ranges to pages
through a small table. Slot count then scales with USED context — a pool
budgeted at the expected aggregate tokens serves far more concurrent
short requests than the dense worst-case allocation, and the engine's
page allocator (host-side free list) gates admission instead of
over-allocating HBM.

Layout (all static shapes — XLA-friendly):
  pool_k/pool_v: [L, N_pages, page, KV, hd]  (page = tokens per page)
  table:         [slots, max_pages] int32    (page ids; -1 = unmapped)
Page j of a slot covers absolute positions [j*page, (j+1)*page): pages
are position-contiguous, so decode attention is an online-softmax
accumulation over the slot's pages — each page is gathered once, folded
into (m, l, o) running stats (context_parallel's merge machinery), and
never materialised as a dense copy. That is the paged-attention
algorithm expressed in pure XLA; a Pallas kernel with a scalar-prefetched
page table is a drop-in upgrade on the same layout.

Reference contrast: the reference has no paging (dense per-request state,
one request in flight — SURVEY §2.2 Cache); this is serving-scale
machinery the TPU design adds.
"""

from __future__ import annotations

from functools import partial as _partial
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from cake_tpu.kv.quantized_pool import (
    Int4PagedKVCache, Int4Pool, QuantPool, QuantizedPagedKVCache,
    dequantize_pages, qupdate_pool_per_row, qwrite_prompt_pages,
    qwrite_window_pages, qwrite_windows_pages,
)
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.parallel.context_parallel import (
    merge_attention_stats, partial_attention_stats,
)


class PagedKVCache(NamedTuple):
    """Device state of the paged cache. The page TABLE rides along as a
    device array (updated per admission/retire by the engine); the free
    list stays host-side in the allocator."""
    k: jnp.ndarray        # [L, N_pages, page, KV, hd]
    v: jnp.ndarray        # [L, N_pages, page, KV, hd]
    table: jnp.ndarray    # [slots, max_pages] int32, -1 = unmapped

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def max_pages(self) -> int:
        return self.table.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.table.shape[1] * self.k.shape[2]

    @classmethod
    def create(cls, config: LlamaConfig, slots: int, n_pages: int,
               page_size: int, max_seq_len: int,
               dtype=jnp.bfloat16) -> "PagedKVCache":
        if max_seq_len % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq_len "
                f"{max_seq_len}")
        L = config.num_hidden_layers
        KV = config.num_key_value_heads
        hd = config.head_dim
        shape = (L, n_pages, page_size, KV, hd)
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            table=jnp.full((slots, max_seq_len // page_size), -1,
                           jnp.int32),
        )

    def memory_bytes(self) -> int:
        """ACTUAL pool storage bytes, summed per leaf — matches the
        quantized cache's accounting (which adds f32 scale sidecars to
        the int8 pools) instead of assuming one dtype for the pool."""
        return sum(leaf.nbytes
                   for leaf in jax.tree_util.tree_leaves((self.k,
                                                          self.v)))


class PageAllocator:
    """Host-side free list with per-page refcounts. The ENGINE calls
    this at admission/retire — allocation never happens on the device
    path, so the jitted steps see only the (already-updated) table array.

    Refcounts are what make page-granular PREFIX SHARING safe: a shared
    prefix's pages appear in many slots' table rows, each mapping holds
    one reference (`retain`), and `release` returns a page to the free
    list only when its last holder lets go — a retiring request decrefs
    shared pages instead of freeing another slot's live context.

    The invariant `free_pages + live_pages == n_pages` holds after every
    operation; violations (double-free, foreign page ids) raise instead
    of silently corrupting the pool and masking leaks."""

    def __init__(self, n_pages: int, page_size: int):
        self.page_size = page_size
        self.n_pages = n_pages
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        # page id -> refcount, for every currently-allocated page
        self._refs: dict = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_pages(self) -> int:
        """Distinct pages currently allocated (each counted once however
        many holders share it): free_pages + live_pages == n_pages."""
        return len(self._refs)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def alloc(self, n_tokens: int) -> Optional[List[int]]:
        """Pages covering n_tokens (each at refcount 1), or None when
        the pool is exhausted (the caller keeps the request queued —
        admission control is the whole point of paging)."""
        need = self.pages_for(n_tokens)
        if need > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(need)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def retain(self, pages: List[int]) -> None:
        """Add one reference to each (already-live) page — a slot
        mapping a shared prefix's pages into its table row. Retaining a
        free or foreign page is a bookkeeping bug: raise before the
        table can alias dead storage."""
        for p in pages:
            if self._refs.get(p, 0) < 1:
                raise ValueError(
                    f"retain of page {p} which is not allocated "
                    f"(refcount 0) — the mapping would alias freed "
                    "storage")
        for p in pages:
            self._refs[p] += 1

    def release(self, pages: List[int]) -> None:
        """Drop one reference per page; a page returns to the free list
        only at refcount 0. Raises on foreign ids and double-frees —
        silently extending the free list would corrupt the pool (one
        page handed to two slots) and mask the leak that caused it."""
        for p in pages:
            if not 0 <= p < self.n_pages:
                raise ValueError(
                    f"release of foreign page id {p!r} (pool has pages "
                    f"0..{self.n_pages - 1})")
        for p in pages:
            n = self._refs.get(p, 0)
            if n <= 0:
                raise ValueError(
                    f"double-free of page {p} (refcount already 0)")
            if n == 1:
                del self._refs[p]
                self._free.append(p)
            else:
                self._refs[p] = n - 1

    def free(self, pages: List[int]) -> None:
        """Alias of release() — kept for call sites that predate
        refcounting; same validation applies."""
        self.release(pages)


def table_set_slot(table: jnp.ndarray, slot: int,
                   pages: List[int]) -> jnp.ndarray:
    """Map `slot` to `pages` (host-computed row; one tiny transfer)."""
    row = jnp.full((table.shape[1],), -1, jnp.int32)
    row = row.at[: len(pages)].set(jnp.asarray(pages, jnp.int32))
    return table.at[slot].set(row)


# -- device ops ---------------------------------------------------------------


def write_prompt_pages(pool_k, pool_v, k, v, table_row, n_real=None):
    """Scatter a prompt window's KV ([1, S, KV, hd]) into the pool pages
    of one slot (per layer — callers run this inside the block scan).

    S need not divide the page size: the final partial window is
    zero-padded to a whole page (a bucket smaller than one page is one
    padded window — with the default 128-token pages most prompts
    bucket below a single page, so S < P is the COMMON case, not an
    edge). Padding positions land in their mapped page as garbage and
    are overwritten by decode before they can be attended, exactly like
    dense padding. UNMAPPED pages (id -1) must not be written — page 0
    would alias another slot — so those windows write their page's
    current contents back (masked write).

    A QuantPool (int8 KV tiering, cake_tpu/kv) quantizes on scatter:
    page-aligned windows fully overwrite their pages, so each window
    sets its page's per-head scale fresh. n_real (traced scalar, the
    real token count) matters ONLY there: bucket-padding garbage is
    dead data in an f32 pool but would inflate the fresh page scales,
    so the quantized writer zeroes positions >= n_real first."""
    if isinstance(pool_k, (QuantPool, Int4Pool)):
        return (qwrite_prompt_pages(pool_k, k, table_row, n_real),
                qwrite_prompt_pages(pool_v, v, table_row, n_real))
    N, P = pool_k.shape[0], pool_k.shape[1]
    S = k.shape[1]
    KV, hd = k.shape[2], k.shape[3]
    n_win = -(-S // P)
    pad = n_win * P - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # one parallel scatter: unmapped windows route to the out-of-bounds
    # index N and mode="drop" skips them (no dummy-page read-back)
    pages = table_row[:n_win]
    idx = jnp.where(pages >= 0, pages, N)
    kw = k[0].reshape(n_win, P, KV, hd)
    vw = v[0].reshape(n_win, P, KV, hd)
    pk = pool_k.at[idx].set(kw.astype(pool_k.dtype), mode="drop")
    pv = pool_v.at[idx].set(vw.astype(pool_v.dtype), mode="drop")
    return pk, pv


def write_window_pages(pool_k, pool_v, k, v, table_row, pos0,
                       n_real=None):
    """Scatter one prefill window's KV ([1, C, KV, hd]) at absolute
    position `pos0` into one slot's pages (per layer).

    Unlike write_prompt_pages, pos0 need NOT be page-aligned: each of
    the C positions resolves its own (page, offset) pair through the
    table row, so chunked prefill windows may straddle page boundaries
    at any offset. Distinct positions map to distinct targets, so one
    vectorized scatter covers the window; positions past the slot's
    mapped pages (bucket padding beyond the allocation, or past the
    table entirely) route to the out-of-bounds index and mode="drop"
    skips them — the paged analog of dense padding semantics.

    A QuantPool quantizes on scatter via a touched-page read-modify-
    write (kv/quantized_pool.qwrite_window_pages); n_real (traced
    scalar) keeps the window's bucket-padding garbage out of the
    monotone page scales there (dead data for an f32 pool)."""
    if isinstance(pool_k, (QuantPool, Int4Pool)):
        return (qwrite_window_pages(pool_k, k, table_row, pos0, n_real),
                qwrite_window_pages(pool_v, v, table_row, pos0, n_real))
    N, P = pool_k.shape[0], pool_k.shape[1]
    C = k.shape[1]
    max_pages = table_row.shape[0]
    pos = pos0 + jnp.arange(C)
    pidx = pos // P
    pages = table_row[jnp.minimum(pidx, max_pages - 1)]
    valid = jnp.logical_and(pidx < max_pages, pages >= 0)
    idx = jnp.where(valid, pages, N)
    offs = pos % P
    pk = pool_k.at[idx, offs].set(k[0].astype(pool_k.dtype), mode="drop")
    pv = pool_v.at[idx, offs].set(v[0].astype(pool_v.dtype), mode="drop")
    return pk, pv


def write_windows_pages(pool_k, pool_v, k, v, pos, q_len, active, table):
    """Batched write_window_pages: every row scatters its q_len-token
    window at absolute position pos[b] into its own pages (per layer).

    pool_k/v: [N_pages, page, KV, hd]; k/v: [B, C, KV, hd]; pos/q_len:
    [B]; active: [B] bool; table: [slots(=B), max_pages]. One
    vectorized scatter covers the whole mixed batch: decode rows write
    their single token (q_len=1), prefill-chunk rows their window, and
    padding columns (i >= q_len), inactive rows, and positions landing
    on unmapped pages all route to the out-of-bounds index N where
    mode="drop" skips them. Distinct rows own distinct pages and a
    row's positions are distinct, so the targets never collide.

    A QuantPool quantizes on scatter via per-row touched-page
    read-modify-writes (kv/quantized_pool.qwrite_windows_pages)."""
    if isinstance(pool_k, (QuantPool, Int4Pool)):
        return (qwrite_windows_pages(pool_k, k, pos, q_len, active,
                                     table),
                qwrite_windows_pages(pool_v, v, pos, q_len, active,
                                     table))
    N, P = pool_k.shape[0], pool_k.shape[1]
    B, C = k.shape[0], k.shape[1]
    max_pages = table.shape[1]
    positions = pos[:, None] + jnp.arange(C)[None, :]         # [B, C]
    pidx = positions // P
    pages = jnp.take_along_axis(
        table, jnp.minimum(pidx, max_pages - 1), axis=1)
    valid = ((jnp.arange(C)[None, :] < q_len[:, None])
             & active[:, None] & (pidx < max_pages) & (pages >= 0))
    idx = jnp.where(valid, pages, N)
    offs = positions % P
    pk = pool_k.at[idx, offs].set(k.astype(pool_k.dtype), mode="drop")
    pv = pool_v.at[idx, offs].set(v.astype(pool_v.dtype), mode="drop")
    return pk, pv


def update_pool_per_row(pool_k, pool_v, k, v, pos, active, table):
    """Write one decode token per row into its page (per layer).

    pool_k/v: [N_pages, page, KV, hd]; k/v: [B, 1, KV, hd]; pos: [B];
    active: [B] bool; table: [slots(=B), max_pages]. One vectorized
    scatter (distinct slots own distinct pages, so the B targets are
    disjoint); inactive rows — and rows whose position lands on an
    unmapped page — route to the out-of-bounds index and mode="drop"
    skips them.

    A QuantPool quantizes on scatter: each row's page is gathered,
    its scale grown to cover the new token, residents re-quantized,
    and the page scattered back (kv/quantized_pool)."""
    if isinstance(pool_k, (QuantPool, Int4Pool)):
        return (qupdate_pool_per_row(pool_k, k, pos, active, table),
                qupdate_pool_per_row(pool_v, v, pos, active, table))
    N, P = pool_k.shape[0], pool_k.shape[1]
    B = k.shape[0]
    rows = jnp.arange(B)
    pages = table[rows, pos // P]
    offs = pos % P
    valid = jnp.logical_and(active, pages >= 0)
    idx = jnp.where(valid, pages, N)
    pk = pool_k.at[idx, offs].set(k[:, 0].astype(pool_k.dtype),
                                  mode="drop")
    pv = pool_v.at[idx, offs].set(v[:, 0].astype(pool_v.dtype),
                                  mode="drop")
    return pk, pv


def paged_attention(q, pool_k, pool_v, table, pos, *, impl: str = "fold"):
    """Ragged decode attention over paged KV.

    impl="fold" (the documented REFERENCE semantics): an XLA fori_loop
    over all max_pages — online-softmax accumulation where every page is
    read once and folded into running (m, l, o) stats; no dense per-slot
    copy ever exists. impl="pallas": the TPU-native single kernel
    (ops/ragged_paged_attention.py) — same math, but each row streams
    only its LIVE pages through VMEM and exits at ceil((pos+1)/page)
    instead of folding the whole pool; falls back to the fold on
    hardware-untileable shapes (tiny test configs).

    q: [B, 1, H, hd] (rope already applied; the current token's KV must
    already be written to its page); pool_k/v: [N_pages, page, KV, hd];
    table: [B, max_pages]; pos: [B] (position of the CURRENT token).
    Returns [B, 1, H, hd].
    """
    B, _, H, hd = q.shape
    quant = isinstance(pool_k, (QuantPool, Int4Pool))
    packed4 = isinstance(pool_k, Int4Pool)
    pk_arr = pool_k.q if quant else pool_k
    N, P, KV = pk_arr.shape[0], pk_arr.shape[1], pk_arr.shape[2]
    if packed4:
        P *= 2      # the packed axis stores two tokens per byte
    max_pages = table.shape[1]

    if impl == "pallas":
        from cake_tpu.ops.ragged_paged_attention import (
            ragged_paged_attention, ragged_paged_supported,
        )
        if ragged_paged_supported(P, H, KV, hd, quantized=quant,
                                  n_pages=N, packed4=packed4):
            if quant:
                return ragged_paged_attention(
                    q, pool_k.q, pool_v.q, table, pos,
                    scale_k=pool_k.scale, scale_v=pool_v.scale,
                    packed4=packed4)
            return ragged_paged_attention(q, pool_k, pool_v, table, pos)
    elif impl != "fold":
        raise ValueError(f"unknown paged_attn impl {impl!r}")

    m0 = jnp.full((B, KV, H // KV, 1, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, KV, H // KV, 1, 1), jnp.float32)
    o0 = jnp.zeros((B, KV, H // KV, 1, hd), jnp.float32)

    def fold(j, carry):
        m, l, o = carry
        pages = table[:, j]                          # [B]
        # unmapped slots route to the out-of-bounds index N with a zero
        # fill instead of gathering page 0 (which aliases another
        # slot's live data into the masked lanes). Whether the OOB row
        # read is actually elided is up to the XLA gather lowering —
        # the guarantee that dead pages cost NO bandwidth lives in the
        # pallas kernel's index-map clamp, not here; the fold's masking
        # (below) keeps the fill value out of the output either way.
        idx = jnp.where(pages >= 0, pages, N)
        if quant:
            # dequantize in the loop: int8 page * its per-head scale,
            # in f32 — the bit-exact reference the int8 pallas kernel
            # is pinned against
            kj = dequantize_pages(pool_k, idx,
                                  fill_zero=True).astype(q.dtype)
            vj = dequantize_pages(pool_v, idx,
                                  fill_zero=True).astype(q.dtype)
        else:
            kj = jnp.take(pool_k, idx, axis=0, mode="fill",
                          fill_value=0)              # [B,P,KV,hd]
            vj = jnp.take(pool_v, idx, axis=0, mode="fill",
                          fill_value=0)
        # validity: absolute slots j*P + t attend when <= pos (causal,
        # current token included) AND the page is mapped
        slots_abs = j * P + jnp.arange(P)            # [P]
        valid = (slots_abs[None] <= pos[:, None]) & (pages >= 0)[:, None]
        valid = valid[:, None, None, None, :]        # [B,1,1,1,P]
        mj, lj, oj = partial_attention_stats(q, kj, vj, valid)
        m_new = jnp.maximum(m, mj)
        a_old = jnp.exp(m - m_new)
        a_new = jnp.exp(mj - m_new)
        return (m_new, a_old * l + a_new * lj,
                a_old * o + a_new * oj)

    m, l, o = lax.fori_loop(0, max_pages, fold, (m0, l0, o0))
    out = merge_attention_stats([(m, l, o)])
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(
        B, 1, H, hd).astype(q.dtype)


def paged_attention_mixed(q, pool_k, pool_v, table, pos, q_len, *,
                          impl: str = "fold"):
    """Mixed ragged attention over paged KV: decode rows (q_len=1) and
    prefill-chunk rows (q_len=C at arbitrary page offset) in ONE batch.

    impl="fold" (the bit-exact REFERENCE semantics, exactly as the fold
    is for decode): an XLA fori_loop over all max_pages — per-query
    online-softmax accumulation where every page is read once; no dense
    per-slot copy ever exists. impl="pallas": the mixed TPU kernel
    (ops/ragged_paged_attention.ragged_paged_attention_mixed) — same
    math, but each row streams only the pages up to
    ceil((pos + q_len)/page); falls back to the fold on
    hardware-untileable shapes (tiny test configs) and on chunk widths
    whose C-scaled scratch would overflow VMEM (large --prefill-chunk).

    q: [B, C, H, hd] (rope applied; every real query token's KV already
    written to its page); pos: [B] position of each row's FIRST query;
    q_len: [B] real query tokens (0 = idle row). Columns past q_len are
    padding whose output the caller never reads. Returns [B, C, H, hd].
    """
    B, C, H, hd = q.shape
    quant = isinstance(pool_k, (QuantPool, Int4Pool))
    packed4 = isinstance(pool_k, Int4Pool)
    pk_arr = pool_k.q if quant else pool_k
    N, P, KV = pk_arr.shape[0], pk_arr.shape[1], pk_arr.shape[2]
    if packed4:
        P *= 2      # the packed axis stores two tokens per byte
    max_pages = table.shape[1]

    if impl == "pallas":
        from cake_tpu.ops.ragged_paged_attention import (
            ragged_paged_attention_mixed, ragged_paged_mixed_supported,
        )
        if ragged_paged_mixed_supported(P, H, KV, hd, C,
                                        quantized=quant, n_pages=N,
                                        packed4=packed4):
            if quant:
                return ragged_paged_attention_mixed(
                    q, pool_k.q, pool_v.q, table, pos, q_len,
                    scale_k=pool_k.scale, scale_v=pool_v.scale,
                    packed4=packed4)
            return ragged_paged_attention_mixed(q, pool_k, pool_v,
                                                table, pos, q_len)
    elif impl != "fold":
        raise ValueError(f"unknown paged_attn impl {impl!r}")

    G = H // KV
    m0 = jnp.full((B, KV, G, C, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, KV, G, C, 1), jnp.float32)
    o0 = jnp.zeros((B, KV, G, C, hd), jnp.float32)
    qi = jnp.arange(C)

    def fold(j, carry):
        m, l, o = carry
        pages = table[:, j]                          # [B]
        idx = jnp.where(pages >= 0, pages, N)
        if quant:
            kj = dequantize_pages(pool_k, idx,
                                  fill_zero=True).astype(q.dtype)
            vj = dequantize_pages(pool_v, idx,
                                  fill_zero=True).astype(q.dtype)
        else:
            kj = jnp.take(pool_k, idx, axis=0, mode="fill",
                          fill_value=0)              # [B,P,KV,hd]
            vj = jnp.take(pool_v, idx, axis=0, mode="fill",
                          fill_value=0)
        # per-query causality: absolute slot j*P + t attends for query
        # i iff <= pos + i (current token included) AND the page is
        # mapped — the decode fold's mask with a query axis
        slots_abs = j * P + jnp.arange(P)            # [P]
        valid = (slots_abs[None, None, :]
                 <= (pos[:, None] + qi[None, :])[:, :, None])
        valid &= (pages >= 0)[:, None, None]
        valid = valid[:, None, None, :, :]           # [B,1,1,C,P]
        mj, lj, oj = partial_attention_stats(q, kj, vj, valid)
        m_new = jnp.maximum(m, mj)
        a_old = jnp.exp(m - m_new)
        a_new = jnp.exp(mj - m_new)
        return (m_new, a_old * l + a_new * lj,
                a_old * o + a_new * oj)

    m, l, o = lax.fori_loop(0, max_pages, fold, (m0, l0, o0))
    out = merge_attention_stats([(m, l, o)])
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(
        B, C, H, hd).astype(q.dtype)


# -- model-level steps (engine step-fn signatures) ----------------------------


def run_blocks_ragged_paged(blocks, x, cache: PagedKVCache, pos, active,
                            rope_c, rope_s, config: LlamaConfig,
                            attn: str = "fold"):
    """run_blocks_ragged over the page pool: write the token, attend the
    pages. x: [B, 1, D]; pos/active: [B]; attn: paged_attention impl
    ({fold,pallas} — static under jit)."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.rope import apply_rope

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = update_pool_per_row(pk, pv, k, v, pos, active,
                                           cache.table)
            return (paged_attention(q, pk2, pv2, cache.table, pos,
                                    impl=attn), (pk2, pv2))

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    x, (k_new, v_new) = lax.scan(body, x, (blocks, cache.k, cache.v))
    return x, cache._replace(k=k_new, v=v_new)


def forward_ragged_paged(params, tokens, cache: PagedKVCache, pos,
                         active, rope, config: LlamaConfig,
                         attn: str = "fold"):
    """model.forward_ragged's signature over a paged cache — un-jitted,
    so serve.engine.make_decode_scan can build the K-step paged decode
    scan from it (dispatch amortization works for paged serving exactly
    like dense)."""
    from cake_tpu.models.llama.model import rope_rows_per_row
    from cake_tpu.ops.norms import rms_norm
    from cake_tpu.ops.quant import qmatmul

    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows_per_row(rope.cos, rope.sin, pos)
    x, cache = run_blocks_ragged_paged(params["blocks"], x, cache, pos,
                                       active, rope_c, rope_s, config,
                                       attn=attn)
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    logits = qmatmul(x[:, -1], params["lm_head"]).astype(jnp.float32)
    return logits, cache


@_partial(jax.jit, static_argnames=("config", "attn"),
          donate_argnames=("cache",))
def decode_step_ragged_paged(params, tokens, pos, active,
                             cache: PagedKVCache, rope,
                             config: LlamaConfig, attn: str = "fold"):
    """decode_step_ragged signature over a paged cache — the engine's
    drop-in decode step fn for --kv-pages serving. attn selects the
    paged_attention impl ({fold,pallas}); static, so both variants are
    separately compiled programs with the same traced signature."""
    return forward_ragged_paged(params, tokens, cache, pos, active,
                                rope, config, attn=attn)


@_partial(jax.jit, static_argnames=("config", "attn"),
          donate_argnames=("cache",))
def prefill_slot_paged(params, tokens, prompt_len, slot,
                       cache: PagedKVCache, rope, config: LlamaConfig,
                       attn: str = "fold"):
    """prefill_slot signature over a paged cache: ordinary causal
    prefill math on the fresh window (the window starts at position 0
    and covers the whole prompt, so no cache reads are needed), with
    each layer's KV scattered into the slot's pages. Padding positions
    land in their mapped page as garbage and are overwritten by decode
    before they can be attended — the dense path's exact semantics.
    Windows beyond the slot's mapped pages (bucket padding past the
    allocation) are dropped by the -1 guard in write_prompt_pages.

    attn="pallas" routes the fresh-window attention through the Pallas
    flash kernel (the prompt window starts at position 0, so causal
    flash over the in-window k/v is exact — no page reads are needed at
    prefill); untileable shapes fall back to the einsum path like the
    dense prefill."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.attention import causal_mask, gqa_attention
    from cake_tpu.ops.flash_attention import (
        flash_attention, flash_supported,
    )
    from cake_tpu.ops.norms import rms_norm
    from cake_tpu.ops.quant import qmatmul
    from cake_tpu.ops.rope import apply_rope, rope_rows

    B, S = tokens.shape
    H = config.num_attention_heads
    KV = config.num_key_value_heads
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows(rope.cos, rope.sin, jnp.int32(0), S)
    table_row = jnp.take(cache.table, slot, axis=0)
    use_flash = (attn == "pallas"
                 and flash_supported(S, S, H, KV, hd=config.head_dim))
    mask = None if use_flash else causal_mask(S)

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = write_prompt_pages(pk, pv, k, v, table_row,
                                          prompt_len[0])
            if use_flash:
                return flash_attention(q, k, v, causal=True), (pk2, pv2)
            return gqa_attention(q, k, v, mask=mask), (pk2, pv2)

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    x, (k_new, v_new) = lax.scan(body, x,
                                 (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    last = jnp.take_along_axis(
        x, (prompt_len - 1).reshape(B, 1, 1).astype(jnp.int32), axis=1
    )[:, 0]
    logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
    return logits, cache._replace(k=k_new, v=v_new)


# -- prefix sharing + chunked prefill (page-granular) --------------------------


@_partial(jax.jit, static_argnames=("config", "attn"),
          donate_argnames=("cache",))
def prefill_prefix_pages(params, tokens, table_row,
                         cache: PagedKVCache, rope, config: LlamaConfig,
                         attn: str = "fold"):
    """Prefill a registered prefix ONCE into dedicated pool pages.

    tokens: [1, S] with S the page-ALIGNED prefix length (the engine
    rounds registrations down to a page boundary; remainder ids join
    each request's suffix); table_row: [max_pages] int32 mapping the
    prefix's dedicated pages (no engine slot involved — the row is a
    standalone mapping, later copied into every matching slot's table
    row head). Ordinary causal prefill at position 0 with each layer's
    KV scattered into the mapped pages; logits are discarded (a
    registered prefix is always a proper head, so the next token comes
    from the suffix prefill). attn="pallas" routes the fresh-window
    attention through the Pallas flash kernel like prefill_slot_paged.
    Returns the updated cache."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.attention import causal_mask, gqa_attention
    from cake_tpu.ops.flash_attention import (
        flash_attention, flash_supported,
    )
    from cake_tpu.ops.rope import apply_rope, rope_rows

    B, S = tokens.shape
    H = config.num_attention_heads
    KV = config.num_key_value_heads
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows(rope.cos, rope.sin, jnp.int32(0), S)
    use_flash = (attn == "pallas"
                 and flash_supported(S, S, H, KV, hd=config.head_dim))
    mask = None if use_flash else causal_mask(S)

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = write_prompt_pages(pk, pv, k, v, table_row)
            if use_flash:
                return flash_attention(q, k, v, causal=True), (pk2, pv2)
            return gqa_attention(q, k, v, mask=mask), (pk2, pv2)

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    _, (k_new, v_new) = lax.scan(body, x,
                                 (params["blocks"], cache.k, cache.v))
    # final norm / lm_head skipped on purpose: only the KV matters here
    return cache._replace(k=k_new, v=v_new)


@_partial(jax.jit, static_argnames=("config", "n_prefix", "attn"),
          donate_argnames=("cache",))
def prefill_slot_paged_prefixed(params, tokens, suffix_len, slot,
                                cache: PagedKVCache, rope,
                                config: LlamaConfig, n_prefix: int,
                                attn: str = "fold"):
    """Slot prefill continuing a POOL-RESIDENT shared prefix: prefill
    only the suffix window, attending the fresh window causally PLUS the
    prefix pages already mapped into the slot's table row head.

    tokens: [1, S] right-padded suffix; suffix_len: [1] real length;
    n_prefix: static page-aligned prefix token count — the slot's first
    n_prefix // page_size table entries are the SHARED prefix pages
    (read-only here: suffix KV scatters into the row's remaining pages
    only, so one prefix page can back many slots). The prefix K/V are
    gathered from their pages once per layer and concatenated with the
    fresh window, giving dense-prefixed-prefill semantics without any
    per-slot prefix copy. Compiles once per (suffix bucket, n_prefix)
    pair — n_prefix is a registered-prefix property, so the set stays
    small. attn="pallas" routes through the cache-aware flash kernel
    (queries at pos n_prefix+i attend keys <= n_prefix+i); decode needs
    no changes at all — the ragged kernel reads through the table."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.attention import gqa_attention
    from cake_tpu.ops.flash_attention import (
        flash_attention_cached, flash_supported,
    )
    from cake_tpu.ops.norms import rms_norm
    from cake_tpu.ops.quant import qmatmul
    from cake_tpu.ops.rope import apply_rope, rope_rows

    B, S = tokens.shape
    H = config.num_attention_heads
    KV = config.num_key_value_heads
    hd = config.head_dim
    P = cache.page_size
    n_pp = n_prefix // P          # static: whole pages by contract
    T = n_prefix + S
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows(rope.cos, rope.sin, jnp.int32(n_prefix), S)
    table_row = jnp.take(cache.table, slot, axis=0)
    prefix_pages = jnp.maximum(table_row[:n_pp], 0)
    suffix_row = table_row[n_pp:]
    use_flash = (attn == "pallas"
                 and flash_supported(S, T, H, KV, hd=hd))
    mask = (None if use_flash else
            (jnp.arange(T)[None, :] <= n_prefix + jnp.arange(S)[:, None]))

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = write_prompt_pages(pk, pv, k, v, suffix_row,
                                          suffix_len[0])
            # gather the shared prefix pages (position-ordered by the
            # row) into a dense [1, n_prefix, KV, hd] view — read-only,
            # pre-write pool (prefix and suffix pages are disjoint);
            # a quantized pool dequantizes page-by-page on the gather
            if isinstance(pk, (QuantPool, Int4Pool)):
                kp = dequantize_pages(pk, prefix_pages).reshape(
                    1, n_prefix, KV, hd).astype(q.dtype)
                vp = dequantize_pages(pv, prefix_pages).reshape(
                    1, n_prefix, KV, hd).astype(q.dtype)
            else:
                kp = jnp.take(pk, prefix_pages, axis=0).reshape(
                    1, n_prefix, KV, hd).astype(q.dtype)
                vp = jnp.take(pv, prefix_pages, axis=0).reshape(
                    1, n_prefix, KV, hd).astype(q.dtype)
            k_full = jnp.concatenate([kp, k.astype(q.dtype)], axis=1)
            v_full = jnp.concatenate([vp, v.astype(q.dtype)], axis=1)
            if use_flash:
                return (flash_attention_cached(q, k_full, v_full,
                                               jnp.int32(n_prefix)),
                        (pk2, pv2))
            return gqa_attention(q, k_full, v_full, mask=mask), (pk2, pv2)

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    x, (k_new, v_new) = lax.scan(body, x,
                                 (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    last = jnp.take_along_axis(
        x, (suffix_len - 1).reshape(B, 1, 1).astype(jnp.int32), axis=1
    )[:, 0]
    logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
    return logits, cache._replace(k=k_new, v=v_new)


@_partial(jax.jit, static_argnames=("config", "attn"),
          donate_argnames=("cache",))
def prefill_slot_paged_chunk(params, tokens, n_real, slot, pos0,
                             cache: PagedKVCache, rope,
                             config: LlamaConfig, attn: str = "fold"):
    """One fixed-size prefill window into a PAGED slot at absolute
    position `pos0` — the paged analog of model.prefill_slot_chunk,
    lifting the old "paged prompts prefill whole-window" restriction:
    long prompts admit in C-token windows with bounded activation
    memory, one compiled program per window shape (pos0 is traced).

    tokens: [1, C]; n_real: [1] real tokens in the window. The window's
    KV scatters through write_window_pages (pos0 may sit anywhere
    inside a page); attention gathers the slot's mapped pages into a
    position-ordered dense [1, max_seq, KV, hd] view and masks
    kj <= pos0 + qi — every already-written position (earlier windows
    AND a shared prefix mapped at the row head) is attended through the
    same gather, so prefix + chunked-suffix composes with no separate
    install step. attn="pallas" routes through the cache-aware flash
    kernel; unmapped pages gather as zeros, which only garbage
    (padding) queries can see under the causal bound."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.attention import gqa_attention
    from cake_tpu.ops.flash_attention import (
        flash_attention_cached, flash_supported,
    )
    from cake_tpu.ops.norms import rms_norm
    from cake_tpu.ops.quant import qmatmul
    from cake_tpu.ops.rope import apply_rope, rope_rows

    B, C = tokens.shape
    H = config.num_attention_heads
    KV = config.num_key_value_heads
    hd = config.head_dim
    N, P = cache.n_pages, cache.page_size
    T = cache.max_seq_len
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows(rope.cos, rope.sin, pos0, C)
    table_row = jnp.take(cache.table, slot, axis=0)
    gather_idx = jnp.where(table_row >= 0, table_row, N)
    use_flash = (attn == "pallas"
                 and flash_supported(C, T, H, KV, hd=hd))
    mask = (None if use_flash else
            (jnp.arange(T)[None, :] <= pos0 + jnp.arange(C)[:, None]))

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = write_window_pages(pk, pv, k, v, table_row, pos0,
                                          n_real[0])
            # post-write gather: the dense view holds every written
            # position (prefix head, earlier windows, this window);
            # a quantized pool dequantizes page-by-page on the gather
            if isinstance(pk2, (QuantPool, Int4Pool)):
                k_full = dequantize_pages(
                    pk2, gather_idx, fill_zero=True).reshape(
                    1, T, KV, hd).astype(q.dtype)
                v_full = dequantize_pages(
                    pv2, gather_idx, fill_zero=True).reshape(
                    1, T, KV, hd).astype(q.dtype)
            else:
                k_full = jnp.take(pk2, gather_idx, axis=0, mode="fill",
                                  fill_value=0).reshape(
                    1, T, KV, hd).astype(q.dtype)
                v_full = jnp.take(pv2, gather_idx, axis=0, mode="fill",
                                  fill_value=0).reshape(
                    1, T, KV, hd).astype(q.dtype)
            if use_flash:
                return (flash_attention_cached(q, k_full, v_full, pos0),
                        (pk2, pv2))
            return gqa_attention(q, k_full, v_full, mask=mask), (pk2, pv2)

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    x, (k_new, v_new) = lax.scan(body, x,
                                 (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    last = jnp.take_along_axis(
        x, (n_real - 1).reshape(B, 1, 1).astype(jnp.int32), axis=1
    )[:, 0]
    logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
    return logits, cache._replace(k=k_new, v=v_new)


# -- token-level continuous batching: the mixed ragged step -------------------


def run_blocks_mixed_paged(blocks, x, cache: PagedKVCache, pos, q_len,
                           active, rope_c, rope_s, config: LlamaConfig,
                           attn: str = "fold"):
    """run_blocks over a MIXED batch of per-row windows: write each
    row's window into its pages, attend everything written through the
    table. x: [B, C, D]; pos/q_len/active: [B]; rope_c/rope_s:
    [B, C, hd//2] per-row per-column tables; attn: paged_attention_mixed
    impl ({fold,pallas} — static under jit)."""
    from cake_tpu.models.llama.model import block_skeleton
    from cake_tpu.ops.rope import apply_rope

    def body(h, xs):
        lp, pk, pv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            pk2, pv2 = write_windows_pages(pk, pv, k, v, pos, q_len,
                                           active, cache.table)
            return (paged_attention_mixed(q, pk2, pv2, cache.table,
                                          pos, q_len, impl=attn),
                    (pk2, pv2))

        h, (pk2, pv2) = block_skeleton(lp, h, config, attn_fn)
        return h, (pk2, pv2)

    x, (k_new, v_new) = lax.scan(body, x, (blocks, cache.k, cache.v))
    return x, cache._replace(k=k_new, v=v_new)


def _mixed_windows_trunk(params, tokens, pos, q_len, active,
                         cache: PagedKVCache, rope,
                         config: LlamaConfig, attn: str):
    """Shared body of the mixed ragged step: embed, per-row per-column
    rope, run_blocks_mixed_paged, final norm. mixed_step_paged reads
    one position from the normed hidden states, the speculative verify
    (verify_window_paged) reads all of them — the window math exists
    once so the two callers cannot drift."""
    from cake_tpu.ops.norms import rms_norm

    C = tokens.shape[1]
    x = jnp.take(params["embed"], tokens, axis=0)
    # per-row per-column rope rows: query i of row b sits at absolute
    # position pos[b] + i (clamped into the table for padding columns
    # past the window — their values are garbage nothing reads)
    T = rope.cos.shape[0]
    pos_grid = jnp.minimum(pos[:, None] + jnp.arange(C)[None, :], T - 1)
    rope_c = jnp.take(rope.cos, pos_grid, axis=0)     # [B, C, hd//2]
    rope_s = jnp.take(rope.sin, pos_grid, axis=0)
    x, cache = run_blocks_mixed_paged(params["blocks"], x, cache, pos,
                                      q_len, active, rope_c, rope_s,
                                      config, attn=attn)
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    return x, cache


@_partial(jax.jit, static_argnames=("config", "attn"),
          donate_argnames=("cache",))
def mixed_step_paged(params, tokens, pos, q_len, active,
                     cache: PagedKVCache, rope, config: LlamaConfig,
                     attn: str = "fold"):
    """ONE jitted step over a mixed batch of row descriptors — the
    token-level continuous-batching step that collapses the
    prefill_slot_paged / prefill_slot_paged_chunk /
    decode_step_ragged_paged zoo behind a single dispatch seam:

      * a DECODE row carries (pos = current token position, q_len = 1,
        tokens[:, 0] = last sampled token) — exactly the ragged decode
        semantics (write the token, attend the pages);
      * a PREFILL-CHUNK row carries (pos = window start, q_len = real
        window tokens, tokens[:, :q_len] = the window) — exactly the
        prefill_slot_paged_chunk semantics at any page offset, a
        shared-prefix head included (the window attends every position
        written through the table);
      * an IDLE row carries (q_len = 0, active = False) and touches
        neither its pages nor the output the caller reads.

    tokens: [B, C] int32 right-padded windows; pos/q_len: [B] int32;
    active: [B] bool. Returns ([B, vocab] logits of each row's LAST
    real token, cache) — decode rows sample their next token from it,
    a prefill row whose window ends its prompt samples its FIRST token,
    and mid-prompt rows' logits are simply not consumed. attn selects
    the paged_attention_mixed impl ({fold,pallas}); fold is the
    bit-exact reference for the mixed step exactly as it is for decode.
    """
    from cake_tpu.ops.quant import qmatmul

    B = tokens.shape[0]
    x, cache = _mixed_windows_trunk(params, tokens, pos, q_len, active,
                                    cache, rope, config, attn)
    last = jnp.take_along_axis(
        x, (jnp.maximum(q_len, 1) - 1).reshape(B, 1, 1).astype(jnp.int32),
        axis=1)[:, 0]
    logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def verify_window_paged(params, tokens, pos, q_len, active,
                        cache: PagedKVCache, rope,
                        config: LlamaConfig, attn: str = "fold"):
    """The speculative VERIFY pass over paged KV: the mixed ragged
    step's exact window math (same trunk — write each row's window
    into its pages, attend everything mapped through the table) but
    with logits at EVERY window position [B, C, V], so the target
    scores a row's whole [last_tok, d_0..d_{gamma-1}] burst in one
    launch. A spec row carries (pos = round frontier, q_len = gamma+1);
    an inactive row carries q_len = 0 and touches nothing. Un-jitted:
    the paged spec round (cake_tpu/spec/round.py) calls it inside its
    own jit."""
    from cake_tpu.ops.quant import qmatmul

    x, cache = _mixed_windows_trunk(params, tokens, pos, q_len, active,
                                    cache, rope, config, attn)
    logits = qmatmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, cache
