"""Fixed-shape KV cache for TPU decode.

The reference grows the cache by concatenation each step and trims past
MAX_SEQ_LEN (llama3/cache.rs:93-122 — with a latent axis bug SURVEY.md §2.2
tells us not to replicate). Growing shapes force recompilation under XLA, so
the TPU design preallocates `[num_layers, batch, max_seq, kv_heads, head_dim]`
buffers and writes each step's k/v with `dynamic_update_slice`; the absolute
write position is a traced scalar, so prefill and every decode step reuse one
compiled program.

Per-session isolation (reference `Cache::as_new`, cache.rs:125-129) is
`KVCache.fresh()` — a zeroed cache of the same spec; `clear()` semantics
(cache.rs:132-135) are the same operation since the buffers are dense arrays.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from cake_tpu.models.llama.config import LlamaConfig


class KVCache(NamedTuple):
    """Stacked per-layer KV buffers. k/v: [L, B, S_max, KV, hd]."""

    k: jnp.ndarray
    v: jnp.ndarray

    @classmethod
    def create(cls, config: LlamaConfig, batch_size: int, max_seq_len: int,
               dtype=jnp.bfloat16, num_layers: int | None = None) -> "KVCache":
        L = num_layers if num_layers is not None else config.num_hidden_layers
        shape = (
            L, batch_size, max_seq_len,
            config.num_key_value_heads, config.head_dim,
        )
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    def fresh(self) -> "KVCache":
        """Zeroed cache with identical spec (reference cache.rs:125-135)."""
        return KVCache(k=jnp.zeros_like(self.k), v=jnp.zeros_like(self.v))

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]

    @property
    def batch_size(self) -> int:
        return self.k.shape[1]


def update_layer_cache_per_row(k_cache, v_cache, new_k, new_v, pos, active):
    """Write one new k/v per row at that row's own position (ragged decode).

    k_cache/v_cache: [B, S_max, KV, hd]
    new_k/new_v:     [B, 1, KV, hd] (single decode token per row)
    pos:             [B] absolute positions (one per row)
    active:          [B] bool; inactive rows keep their existing cache line
                     (their pos may be stale — a retired slot must not
                     corrupt state a future prefill won't overwrite).
    """
    b = jnp.arange(k_cache.shape[0])
    sel = active[:, None, None]
    old_k = k_cache[b, pos]
    old_v = v_cache[b, pos]
    k_cache = k_cache.at[b, pos].set(
        jnp.where(sel, new_k[:, 0].astype(k_cache.dtype), old_k))
    v_cache = v_cache.at[b, pos].set(
        jnp.where(sel, new_v[:, 0].astype(v_cache.dtype), old_v))
    return k_cache, v_cache


def update_layer_cache(k_cache, v_cache, new_k, new_v, pos):
    """Write one layer's new k/v at absolute position `pos`.

    k_cache/v_cache: [B, S_max, KV, hd]
    new_k/new_v:     [B, S, KV, hd]
    pos:             traced scalar start index
    Returns the updated buffers (same shapes — jit-donatable).
    """
    zeros = (0, pos, 0, 0)
    k_cache = lax.dynamic_update_slice(k_cache, new_k.astype(k_cache.dtype), zeros)
    v_cache = lax.dynamic_update_slice(v_cache, new_v.astype(v_cache.dtype), zeros)
    return k_cache, v_cache


# -- ring-buffer (sliding-window) writes --------------------------------------

def update_layer_cache_ring(k_cache, v_cache, new_k, new_v, pos, n_real=None):
    """Write S <= W new k/v at ring slots (pos+i) % W.

    k_cache/v_cache: [B, W, KV, hd] ring buffers (W = window capacity)
    new_k/new_v:     [B, S, KV, hd]
    pos:             traced scalar absolute start position
    n_real:          traced count of REAL tokens in the window; entries
                     i >= n_real keep the slot's previous content — a
                     padded chunk's junk would otherwise alias ring slots
                     of positions still inside upcoming queries' windows
                     (the dense cache never had this hazard: junk landed
                     at untouched higher positions).
    """
    B, W = k_cache.shape[0], k_cache.shape[1]
    S = new_k.shape[1]
    assert S <= W, f"ring write of {S} tokens exceeds ring capacity {W}"
    slots = jnp.mod(pos + jnp.arange(S), W)                  # [S] unique
    keep = (jnp.arange(S) >= (S if n_real is None else n_real))
    old_k = k_cache[:, slots]
    old_v = v_cache[:, slots]
    sel = keep[None, :, None, None]
    k_cache = k_cache.at[:, slots].set(
        jnp.where(sel, old_k, new_k.astype(k_cache.dtype)))
    v_cache = v_cache.at[:, slots].set(
        jnp.where(sel, old_v, new_v.astype(v_cache.dtype)))
    return k_cache, v_cache


def update_layer_cache_per_row_ring(k_cache, v_cache, new_k, new_v, pos,
                                    active):
    """Ragged single-token ring write: row b writes at slot pos[b] % W."""
    W = k_cache.shape[1]
    return update_layer_cache_per_row(k_cache, v_cache, new_k, new_v,
                                      jnp.mod(pos, W), active)


def update_layer_cache_window_per_row(k_cache, v_cache, new_k, new_v,
                                      pos0, active):
    """Write a W-token window per row at that row's own start position
    (the batched speculative verify: row b's tokens j land at absolute
    positions pos0[b]+j).

    k_cache/v_cache: [B, S_max, KV, hd]
    new_k/new_v:     [B, W, KV, hd]
    pos0:            [B] absolute start positions
    active:          [B] bool; inactive rows keep their cache lines.
    Indices clamp at S_max-1 (callers bound pos0+W <= S_max; the clamp
    only protects inactive rows' stale pos0)."""
    B, W = new_k.shape[:2]
    b = jnp.arange(B)[:, None]
    idx = jnp.clip(pos0[:, None] + jnp.arange(W)[None],
                   0, k_cache.shape[1] - 1)
    sel = active[:, None, None, None]
    old_k = k_cache[b, idx]
    old_v = v_cache[b, idx]
    k_cache = k_cache.at[b, idx].set(
        jnp.where(sel, new_k.astype(k_cache.dtype), old_k))
    v_cache = v_cache.at[b, idx].set(
        jnp.where(sel, new_v.astype(v_cache.dtype), old_v))
    return k_cache, v_cache
