"""Speculative decoding: draft-model propose, one target verify pass.

A capability past the reference's surface (it decodes strictly one token
per model pass, llama.rs:285-298): a small draft model proposes gamma
tokens autoregressively, the target scores all of them in ONE forward
(logits at every position, model.forward_logits_all), and the standard
accept/resample rule keeps the leading agreeing prefix plus one
correction token — so each target pass yields 1..gamma+1 tokens. With
greedy sampling the output is the target's own greedy stream
(tests/test_speculative.py asserts token-for-token equality against
LlamaGenerator), up to one caveat shared by every speculative
implementation: the verify pass scores gamma+1 positions in one batched
forward, whose bf16 accumulation order differs from stepwise decode by
~1e-2 logits — when the target's top-2 logits tie within that noise,
the two evaluation shapes may break the tie differently. Both streams
are valid greedy outputs of the same model. With temperature sampling
the accept/resample rule preserves the target distribution (Leviathan
et al., 2023 — public algorithm).

TPU shape: one jitted program per spec step — the draft loop is a
lax.scan of gamma+1 decode steps (the +1 writes the last draft's KV so an
all-accept step needs no patch-up pass), the verify is one forward over
the gamma+1-token window (masked-einsum attention against the cache —
the window is a handful of tokens, so the flash kernel would gain
nothing), and accept/resample is branch-free arithmetic on the stacked
logits. Nothing rolls back: both
caches index KV by absolute position, and positions past the accepted
frontier are masked (decode_mask) until overwritten, exactly like padded
prefill garbage.

Scope (v1): batch 1 (speculation is a latency feature), single device,
repeat_penalty == 1.0 (the verify pass scores gamma+1 positions in
parallel, so a within-burst penalty ring cannot be replayed exactly).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import Token
from cake_tpu.models.chat import History, Message
from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    bucket_length, encode_text, incremental_decode,
)
from cake_tpu.models.llama.model import (
    RopeTables, forward, forward_logits_all, prefill,
)
from cake_tpu.ops.sampling import SamplingConfig

log = logging.getLogger(__name__)


@partial(jax.jit,
         static_argnames=("t_cfg", "d_cfg", "gamma", "greedy"),
         donate_argnames=("t_cache", "d_cache"))
def spec_step(t_params, d_params, t_cache: KVCache, d_cache: KVCache,
              last_tok, pos, t_rope: RopeTables, d_rope: RopeTables,
              rng, temperature,
              t_cfg: LlamaConfig, d_cfg: LlamaConfig,
              gamma: int, greedy: bool):
    """One propose-verify-accept round.

    last_tok [1, 1] at absolute `pos` (its KV not yet written).
    Returns (tokens [1, gamma+1] — first n_emit valid, rest -1,
    n_emit scalar, t_cache, d_cache, rng).
    """
    return _spec_round(t_params, d_params, t_cache, d_cache, last_tok,
                       pos, t_rope, d_rope, rng, temperature,
                       t_cfg, d_cfg, gamma, greedy)


# The accept/resample arithmetic moved to cake_tpu/spec/accept.py so
# the PAGED round (cake_tpu/spec/round.py) shares it verbatim with the
# dense rounds below; the historical underscore names stay importable
# here for the dense path's callers and tests.
from cake_tpu.spec.accept import (  # noqa: E402
    advance_row_keys as _advance_row_keys,
    assemble_sampled as _assemble_sampled,
    greedy_accept as _greedy_accept,
    rejection_accept as _rejection_accept,
)


@partial(jax.jit,
         static_argnames=("t_cfg", "d_cfg", "gamma"),
         donate_argnames=("t_cache", "d_cache"))
def spec_round_batched(t_params, d_params, t_cache: KVCache,
                       d_cache: KVCache, last_tok, pos, active, keys,
                       temp, t_rope: RopeTables, d_rope: RopeTables,
                       t_cfg: LlamaConfig, d_cfg: LlamaConfig,
                       gamma: int):
    """One propose-verify-accept round for EVERY active slot in one
    compiled program: gamma+1 batched ragged draft steps + one batched
    windowed verify. The per-slot engine path (spec_step_slot) ran B
    separate batch-1 rounds, streaming the weights B times per round —
    this streams them once, which is the whole cost model of batched
    decode.

    last_tok [B, 1] at per-row absolute `pos` (KV not yet written);
    active [B]; keys [B, 2] per-slot PRNG keys (advanced only for
    active sampled rows); temp [B] (<= 0 -> greedy row: argmax drafts,
    exact-match acceptance; > 0 -> leftover-residual rejection
    sampling, per row).
    Returns (out [B, gamma+1] — first n_emit[b] valid, rest -1;
    n_emit [B] (0 for inactive rows); t_cache; d_cache; keys;
    state = (last_tok [B, 1], pos [B]) — each active row's final
    emitted token at its advanced frontier, fed straight back as the
    next round's (last_tok, pos) by the engine's double-buffered spec
    burst without a host round-trip)."""
    from cake_tpu.models.llama.model import (
        forward_ragged, forward_window_ragged,
    )

    B = last_tok.shape[0]
    greedy = temp <= 0.0
    temp_eff = jnp.where(greedy, 1.0, temp)[:, None]

    def draft_body(carry, _):
        cache, tok, p, keys = carry
        logits, cache = forward_ragged(d_params, tok, cache, p, active,
                                       d_rope, d_cfg)
        probs = jax.nn.softmax(logits / temp_eff, axis=-1)
        nxt_g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        keys, subs = _advance_row_keys(keys, active & ~greedy)
        nxt_s = jax.vmap(jax.random.categorical)(
            subs, logits / temp_eff).astype(jnp.int32)
        nxt = jnp.where(greedy, nxt_g, nxt_s)
        return ((cache, nxt[:, None], p + active, keys),
                (nxt, probs))

    (d_cache, _, _, keys), (drafts_all, d_probs_all) = jax.lax.scan(
        draft_body, (d_cache, last_tok, pos, keys), None,
        length=gamma + 1)
    drafts = drafts_all[:gamma].T                      # [B, gamma]
    d_probs = jnp.swapaxes(d_probs_all[:gamma], 0, 1)  # [B, gamma, V]

    tokens_v = jnp.concatenate([last_tok, drafts], axis=1)
    t_logits, t_cache = forward_window_ragged(
        t_params, tokens_v, t_cache, pos, active, t_rope, t_cfg)

    # greedy rows: exact-match acceptance against the target argmax
    targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    n_acc_g = _greedy_accept(drafts, targets)

    # sampled rows: leftover-residual rejection sampling (per row),
    # the same _rejection_accept/_assemble_sampled math as _spec_round.
    # Greedy rows' residual/correction are computed but unused (their
    # out comes from `targets`) and their keys never advance.
    t_probs = jax.nn.softmax(t_logits / temp_eff[..., None], axis=-1)
    keys, subs = _advance_row_keys(keys, active & ~greedy)
    u = jax.vmap(lambda k: jax.random.uniform(k, (gamma,)))(subs)
    n_acc_s, resid = _rejection_accept(drafts, d_probs, t_probs, u,
                                       gamma)
    keys, subs = _advance_row_keys(keys, active & ~greedy)
    correction = jax.vmap(jax.random.categorical)(
        subs, jnp.log(jnp.maximum(resid, 1e-20))).astype(jnp.int32)
    out_s = _assemble_sampled(drafts, correction, n_acc_s, gamma)

    n_acc = jnp.where(greedy, n_acc_g, n_acc_s)
    out = jnp.where(greedy[:, None], targets, out_s)
    n_emit = jnp.where(active, n_acc + 1, 0)
    mask = jnp.arange(gamma + 1)[None] < n_emit[:, None]
    out = jnp.where(mask, out, -1)
    # chained-round state (the engine's double-buffered spec burst
    # feeds this straight back as (last_tok, pos) without a host
    # round-trip): each active row continues from its final emitted
    # token at its advanced frontier
    last = jnp.take_along_axis(
        out, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
    last_out = jnp.where(active, last, last_tok[:, 0])[:, None]
    pos_out = pos + n_emit
    state = (last_out, pos_out)
    return out, n_emit, t_cache, d_cache, keys, state


def _spec_round(t_params, d_params, t_cache: KVCache, d_cache: KVCache,
                last_tok, pos, t_rope: RopeTables, d_rope: RopeTables,
                rng, temperature,
                t_cfg: LlamaConfig, d_cfg: LlamaConfig,
                gamma: int, greedy: bool):
    B = last_tok.shape[0]

    def draft_body(carry, i):
        cache, tok, p, rng = carry
        logits, cache = forward(d_params, tok, cache, p, d_rope, d_cfg)
        if greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            probs = jax.nn.softmax(logits, axis=-1)
        else:
            rng, sub = jax.random.split(rng)
            probs = jax.nn.softmax(logits / temperature, axis=-1)
            nxt = jax.random.categorical(sub, logits / temperature
                                         ).astype(jnp.int32)
        return (cache, nxt[:, None], p + 1, rng), (nxt, probs)

    # gamma+1 iterations: iteration gamma writes the gamma-th draft's KV
    # (needed when every draft is accepted) and its proposal is discarded
    (d_cache, _, _, rng), (drafts_all, d_probs_all) = jax.lax.scan(
        draft_body, (d_cache, last_tok, pos, rng),
        jnp.arange(gamma + 1))
    drafts = drafts_all[:gamma].T                      # [B, gamma]
    d_probs = jnp.swapaxes(d_probs_all[:gamma], 0, 1)  # [B, gamma, V]

    # verify: target scores [last_tok, d_0..d_{gamma-1}] in one pass,
    # writing target KV for positions pos..pos+gamma
    tokens_v = jnp.concatenate([last_tok, drafts], axis=1)  # [B, gamma+1]
    t_logits, t_cache = forward_logits_all(
        t_params, tokens_v, t_cache, pos, t_rope, t_cfg)   # [B, g+1, V]

    if greedy:
        targets = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        # emitted = targets[:, :n_acc+1] (accepted drafts equal targets;
        # position n_acc is the correction / bonus token)
        n_acc = _greedy_accept(drafts, targets)
        out = targets
        n_emit = n_acc + 1
    else:
        t_probs = jax.nn.softmax(t_logits / temperature, axis=-1)
        rng, sub = jax.random.split(rng)
        u = jax.random.uniform(sub, drafts.shape)
        n_acc, resid = _rejection_accept(drafts, d_probs, t_probs, u,
                                         gamma)
        rng, sub = jax.random.split(rng)
        correction = jax.random.categorical(
            sub, jnp.log(jnp.maximum(resid, 1e-20))).astype(jnp.int32)
        out = _assemble_sampled(drafts, correction, n_acc, gamma)
        n_emit = n_acc + 1

    mask = jnp.arange(gamma + 1)[None] < n_emit[:, None]
    out = jnp.where(mask, out, -1)
    return out, n_emit, t_cache, d_cache, rng


@partial(jax.jit,
         static_argnames=("t_cfg", "d_cfg", "gamma", "greedy",
                          "num_rounds"),
         donate_argnames=("t_cache", "d_cache"))
def spec_scan(t_params, d_params, t_cache: KVCache, d_cache: KVCache,
              last_tok, pos, t_rope: RopeTables, d_rope: RopeTables,
              rng, temperature,
              t_cfg: LlamaConfig, d_cfg: LlamaConfig,
              gamma: int, greedy: bool, num_rounds: int):
    """num_rounds propose-verify-accept rounds chained on device
    (lax.scan over _spec_round), so the host pays ONE dispatch + fetch
    per num_rounds rounds instead of per round — the host-stepped loop
    is fetch-bound (~100ms/round over a remote-dispatch tunnel), which
    caps batch-1 speculation at ~10 tok/s regardless of acceptance.

    Caller must guarantee pos + num_rounds*(gamma+1) <= max_seq_len
    (every round writes up to gamma+1 cache positions at its dynamic
    offset). Returns (outs [num_rounds, gamma+1] — per round the first
    n valid, rest -1; ns [num_rounds]; t_cache; d_cache; rng). Tokens
    after an EOS inside the window are overshoot for the caller to
    discard (same contract as the engine's budget-frozen scans)."""

    def body(carry, _):
        t_cache, d_cache, tok, p, rng = carry
        out, n, t_cache, d_cache, rng = _spec_round(
            t_params, d_params, t_cache, d_cache, tok, p,
            t_rope, d_rope, rng, temperature, t_cfg, d_cfg, gamma,
            greedy)
        last = out[:, n[0] - 1][:, None]    # [1, 1] for the next round
        return (t_cache, d_cache, last, p + n[0], rng), (out[0], n[0])

    (t_cache, d_cache, _tok, _pos, rng), (outs, ns) = jax.lax.scan(
        body, (t_cache, d_cache, last_tok, pos, rng), None,
        length=num_rounds)
    return outs, ns, t_cache, d_cache, rng


class SpeculativeGenerator:
    """TextGenerator with draft-model speculation (batch 1).

    Exposes the same protocol as LlamaGenerator plus acceptance stats;
    next_token streams from an internal burst buffer so the CLI/API token
    loop is unchanged.
    """

    MODEL_NAME = "llama3-spec"

    def __init__(self, config: LlamaConfig, params,
                 draft_config: LlamaConfig, draft_params,
                 tokenizer, *, gamma: int = 4, max_seq_len: int = 4096,
                 sampling: Optional[SamplingConfig] = None,
                 seed: int = 299792458, cache_dtype=jnp.bfloat16,
                 spec_rounds: int = 4):
        if gamma < 1:
            raise ValueError("gamma must be >= 1")
        if spec_rounds < 1:
            raise ValueError("spec_rounds must be >= 1")
        sampling = sampling or SamplingConfig()
        if sampling.repeat_penalty != 1.0:
            raise ValueError(
                "speculative decoding supports repeat_penalty=1.0 only "
                "(the verify pass scores the burst in parallel)")
        if sampling.top_k is not None or (sampling.top_p or 1.0) < 1.0:
            raise ValueError(
                "speculative decoding samples from the full temperature "
                "softmax; top_k/top_p are not supported (the accept/"
                "resample identity assumes the unfiltered distributions)")
        self.config = config
        self.params = params
        self.draft_config = draft_config
        self.draft_params = draft_params
        self.tokenizer = tokenizer
        self.gamma = gamma
        self.max_seq_len = max_seq_len
        self.sampling = sampling
        self.rope = RopeTables.create(config, max_seq_len)
        self.d_rope = RopeTables.create(draft_config, max_seq_len)
        self.cache = KVCache.create(config, 1, max_seq_len,
                                    dtype=cache_dtype)
        self.d_cache = KVCache.create(draft_config, 1, max_seq_len,
                                      dtype=cache_dtype)
        self.history = History(config.chat_template)
        self.rng = jax.random.PRNGKey(seed)
        self.spec_rounds = spec_rounds
        self.proposed = 0        # drafts offered to the verifier
        self.accepted = 0        # drafts kept
        self._reset_session()

    # -- TextGenerator protocol ----------------------------------------------

    def add_message(self, message: Message) -> None:
        self.history.add_message(message)

    def reset(self) -> None:
        self.history.clear()
        self.cache = self.cache.fresh()
        self.d_cache = self.d_cache.fresh()
        self._reset_session()

    def _reset_session(self) -> None:
        self.tokens: List[int] = []
        self.index_pos = 0
        self._buffer: List[int] = []
        self._pending_text = ""

    def generated_tokens(self) -> int:
        return len(self.tokens)

    def set_sampling(self, temperature=None, top_p=None, **overrides):
        """Per-request sampling overrides (the locked API path's
        contract). Speculation supports temperature only — the verify
        pass scores raw model probabilities, so top-p/top-k filtering
        would break the accept/resample correctness proof; a request
        asking for them gets a clean error instead of silently different
        sampling."""
        from dataclasses import replace
        if top_p is not None and top_p < 1.0:
            raise ValueError(
                "--draft-model serving supports temperature only "
                "(top_p/top_k would break speculative accept/resample)")
        if overrides.get("top_k") is not None:
            raise ValueError(
                "--draft-model serving supports temperature only")
        if temperature is not None:
            self.sampling = replace(self.sampling,
                                    temperature=temperature)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    def next_token(self, index: int) -> Token:
        if index == 0:
            self._prefill_prompt()
        if not self._buffer:
            self._fill_buffer()
        tid = self._buffer.pop(0)
        self.tokens.append(tid)
        if tid in self.config.eos_token_ids:
            tail, self._pending_text = incremental_decode(
                self.tokenizer, self.tokens[:-1], self._pending_text,
                final=True)
            return Token(id=tid, text=tail, is_end_of_stream=True)
        new, self._pending_text = incremental_decode(
            self.tokenizer, self.tokens, self._pending_text)
        return Token(id=tid, text=new, is_end_of_stream=False)

    # -- internals ------------------------------------------------------------

    def _prefill_prompt(self) -> None:
        ids = encode_text(self.tokenizer, self.history.render())
        if len(ids) > self.max_seq_len - self.gamma - 2:
            raise ValueError(
                f"prompt length {len(ids)} leaves no speculation window "
                f"(max_seq_len {self.max_seq_len}, gamma {self.gamma})")
        bucket = bucket_length(len(ids), self.max_seq_len)
        padded = ids + [0] * (bucket - len(ids))
        toks = jnp.asarray([padded], jnp.int32)
        plen = jnp.asarray([len(ids)], jnp.int32)
        logits, self.cache = prefill(
            self.params, toks, plen, self.cache, self.rope, self.config)
        _, self.d_cache = prefill(
            self.draft_params, toks, plen, self.d_cache, self.d_rope,
            self.draft_config)
        first = self._sample_first(logits)
        self._buffer = [int(first)]
        self.index_pos = len(ids)

    def _sample_first(self, logits):
        if self._greedy:
            return jnp.argmax(logits, axis=-1)[0]
        self.rng, sub = jax.random.split(self.rng)
        return jax.random.categorical(
            sub, logits / self.sampling.temperature)[0]

    @property
    def _greedy(self) -> bool:
        t = self.sampling.temperature
        return t is None or t <= 0.0

    def _fill_buffer(self) -> None:
        if self.index_pos + self.gamma + 1 >= self.max_seq_len:
            raise ValueError(
                f"speculation window exceeds max_seq_len {self.max_seq_len}"
                f" at position {self.index_pos}")
        if not self.tokens:
            raise RuntimeError(
                "next_token(index>0) called before the index==0 prefill")
        last = jnp.asarray([[self.tokens[-1]]], jnp.int32)
        R = self.spec_rounds
        if (R > 1 and self.index_pos + R * (self.gamma + 1)
                <= self.max_seq_len):
            # R rounds per dispatch+fetch (spec_scan): the host-stepped
            # loop is fetch-bound over a remote-dispatch tunnel, so
            # chaining rounds on device multiplies batch-1 throughput
            # by ~R. Near the window end fall back to single rounds
            # (two compiled programs total: R-round and 1-round).
            outs, ns, self.cache, self.d_cache, self.rng = spec_scan(
                self.params, self.draft_params, self.cache, self.d_cache,
                last, jnp.int32(self.index_pos), self.rope, self.d_rope,
                self.rng,
                jnp.float32(self.sampling.temperature or 1.0),
                self.config, self.draft_config, self.gamma,
                self._greedy, R)
            ns_h, outs_h = jax.device_get((ns, outs))
            eos = set(self.config.eos_token_ids)
            for k in range(R):
                n = int(ns_h[k])
                toks = [int(t) for t in outs_h[k, :n]]
                self._buffer.extend(toks)
                self.proposed += self.gamma
                self.accepted += n - 1
                self.index_pos += n
                if any(t in eos for t in toks):
                    # rounds past EOS ran on device (overshoot by
                    # design) but condition on post-EOS garbage — they
                    # must pollute neither the stream nor the
                    # acceptance stats
                    break
            return
        out, n_emit, self.cache, self.d_cache, self.rng = spec_step(
            self.params, self.draft_params, self.cache, self.d_cache,
            last, jnp.int32(self.index_pos), self.rope, self.d_rope,
            self.rng,
            jnp.float32(self.sampling.temperature or 1.0),
            self.config, self.draft_config, self.gamma, self._greedy)
        # one batched fetch (a remote-dispatch tunnel charges ~100ms per
        # round-trip; int(n_emit) then asarray(out) would pay it twice)
        n_emit_h, out_h = jax.device_get((n_emit, out))
        n = int(n_emit_h[0])
        self._buffer.extend(int(t) for t in out_h[0, :n])
        self.proposed += self.gamma
        self.accepted += n - 1
        self.index_pos += n

    # -- batch generation (bench/tests parity with LlamaGenerator) -----------

    def generate_on_device(self, prompt_ids: np.ndarray,
                           prompt_len: np.ndarray,
                           num_tokens: int) -> np.ndarray:
        """Greedy/spec generation for a [1, S] prompt; returns
        [1, num_tokens]. Host-stepped (one device call per burst)."""
        if prompt_ids.shape[0] != 1:
            raise ValueError("speculative decoding is batch-1")
        toks = jnp.asarray(prompt_ids, jnp.int32)
        plen = jnp.asarray(prompt_len, jnp.int32)
        cache = self.cache.fresh()
        d_cache = self.d_cache.fresh()
        logits, cache = prefill(self.params, toks, plen, cache, self.rope,
                                self.config)
        _, d_cache = prefill(self.draft_params, toks, plen, d_cache,
                             self.d_rope, self.draft_config)
        rng = self.rng
        if self._greedy:
            first = int(jnp.argmax(logits, axis=-1)[0])
        else:
            rng, sub = jax.random.split(rng)
            first = int(jax.random.categorical(
                sub, logits / self.sampling.temperature)[0])
        out = [first]
        pos = int(np.asarray(plen)[0])
        R = self.spec_rounds
        while len(out) < num_tokens:
            if pos + self.gamma + 1 >= self.max_seq_len:
                raise ValueError("speculation window exceeds max_seq_len")
            last = jnp.asarray([[out[-1]]], jnp.int32)
            if R > 1 and pos + R * (self.gamma + 1) <= self.max_seq_len:
                outs_d, ns_d, cache, d_cache, rng = spec_scan(
                    self.params, self.draft_params, cache, d_cache, last,
                    jnp.int32(pos), self.rope, self.d_rope, rng,
                    jnp.float32(self.sampling.temperature or 1.0),
                    self.config, self.draft_config, self.gamma,
                    self._greedy, R)
                ns_h, outs_h = jax.device_get((ns_d, outs_d))
                for k in range(R):
                    n = int(ns_h[k])
                    self.proposed += self.gamma
                    self.accepted += n - 1
                    out.extend(int(t) for t in outs_h[k, :n])
                    pos += n
                continue
            burst, n_emit, cache, d_cache, rng = spec_step(
                self.params, self.draft_params, cache, d_cache, last,
                jnp.int32(pos), self.rope, self.d_rope, rng,
                jnp.float32(self.sampling.temperature or 1.0),
                self.config, self.draft_config, self.gamma, self._greedy)
            n_emit_h, burst_h = jax.device_get((n_emit, burst))
            n = int(n_emit_h[0])
            self.proposed += self.gamma
            self.accepted += n - 1
            out.extend(int(t) for t in burst_h[0, :n])
            pos += n
        # persist the advanced PRNG stream: repeated sampled calls must
        # differ, matching LlamaGenerator.generate_on_device
        self.rng = rng
        return np.asarray([out[:num_tokens]], np.int32)
