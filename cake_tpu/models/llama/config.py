"""Llama model hyperparameters, deserialised from HF `config.json`.

Reference: `LlamaConfig`/`Config` (cake-core/src/models/llama3/config.rs):
rope_theta defaults to 10k (config.rs:8-10), GQA kv-head fallback to the
full head count (config.rs:40-42). The reference hardcodes
MAX_SEQ_LEN = 4096 (config.rs:6); here the runtime context window is a
separate knob (`Args.max_seq_len`) so long-context serving isn't capped by
a constant.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional, Tuple


def _read_config(model_dir: str) -> dict:
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)


def load_config_dict(raw: dict) -> "LlamaConfig":
    """Dispatch a parsed config.json on `model_type`: "mixtral" ->
    MoEConfig (sparse experts), anything else -> LlamaConfig."""
    if raw.get("model_type") == "mixtral":
        from cake_tpu.models.moe import MoEConfig
        return MoEConfig.from_hf_dict(raw)
    return LlamaConfig.from_hf_dict(raw)


def load_config(model_dir: str) -> "LlamaConfig":
    """Load `<model_dir>/config.json` with model_type dispatch — the single
    entry point every config.json consumer should use."""
    return load_config_dict(_read_config(model_dir))


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 8192
    bos_token_id: int = 128000
    eos_token_ids: Tuple[int, ...] = (128001, 128009)
    tie_word_embeddings: bool = False
    # Sliding-window attention (Mistral-family, HF "sliding_window"):
    # each query attends at most this many most-recent positions. None =
    # full causal attention (Llama). The KV cache stays full-length
    # (correct; a ring buffer is a memory optimization, not semantics).
    sliding_window: Optional[int] = None
    # prompt template for the chat paths (models/chat.TEMPLATES);
    # from_hf_dict sets "mistral" for model_type mistral/mixtral and
    # "chatml" for qwen2
    chat_template: str = "llama3"
    # QKV projection bias (Qwen2-family; HF "attention_bias" / implied by
    # model_type qwen2) — adds bq/bk/bv leaves to every block
    attention_bias: bool = False
    # Use the Pallas flash-attention kernel for prefill windows whose shapes
    # tile (ops/flash_attention.py). Off by default so CPU test runs don't
    # pay interpret-mode cost; the TPU Context enables it.
    use_flash_attention: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def is_moe(self) -> bool:
        """Single source of truth for family dispatch (MoEConfig carries
        num_local_experts; dense configs don't)."""
        return bool(getattr(self, "num_local_experts", 0))

    @classmethod
    def from_path(cls, model_dir: str) -> "LlamaConfig":
        """Load from `<model_dir>/config.json` (reference config.rs:30-37),
        dispatching on model_type — a Mixtral checkpoint yields MoEConfig.
        Called on a subclass, that subclass is guaranteed (so e.g.
        MoEConfig.from_path on a checkpoint without model_type still reads
        the expert fields)."""
        raw = _read_config(model_dir)
        cfg = load_config_dict(raw)
        return cfg if isinstance(cfg, cls) else cls.from_hf_dict(raw)

    @classmethod
    def from_hf_dict(cls, raw: dict) -> "LlamaConfig":
        eos = raw.get("eos_token_id", 128001)
        if isinstance(eos, int):
            eos = (eos,)
        else:
            eos = tuple(eos)
        return cls(
            vocab_size=raw["vocab_size"],
            hidden_size=raw["hidden_size"],
            intermediate_size=raw["intermediate_size"],
            num_hidden_layers=raw["num_hidden_layers"],
            num_attention_heads=raw["num_attention_heads"],
            num_key_value_heads=raw.get(
                "num_key_value_heads", raw["num_attention_heads"]
            ),
            rms_norm_eps=raw.get("rms_norm_eps", 1e-5),
            rope_theta=raw.get("rope_theta", 10000.0),
            max_position_embeddings=raw.get("max_position_embeddings", 8192),
            bos_token_id=raw.get("bos_token_id", 128000),
            eos_token_ids=eos,
            tie_word_embeddings=raw.get("tie_word_embeddings", False),
            # Qwen2/2.5 checkpoints ship sliding_window alongside
            # use_sliding_window: false (full attention) — honor the gate
            sliding_window=(raw.get("sliding_window")
                            if raw.get("use_sliding_window", True)
                            else None),
            # Mixtral shares Mistral's [INST] instruct format and
            # SentencePiece vocab — Llama-3 header tokens don't exist
            # there; Qwen2 uses ChatML
            chat_template={"mistral": "mistral", "mixtral": "mistral",
                           "qwen2": "chatml"}.get(
                               raw.get("model_type", ""), "llama3"),
            attention_bias=raw.get("attention_bias",
                                   raw.get("model_type") == "qwen2"),
        )

    @classmethod
    def tiny(cls, **overrides) -> "LlamaConfig":
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=2, rms_norm_eps=1e-5, rope_theta=10000.0,
            max_position_embeddings=256, bos_token_id=1,
            eos_token_ids=(2,), tie_word_embeddings=False,
        )
        base.update(overrides)
        return cls(**base)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rms_norm_eps=1e-5, rope_theta=500000.0,
            max_position_embeddings=8192,
        )

    @classmethod
    def mistral_7b(cls) -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama architecture + 4096-token sliding
        window (HF mistralai/Mistral-7B-v0.1 config.json; weight names
        are identical, so loading/sharding/quantization all apply)."""
        return cls(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rms_norm_eps=1e-5, rope_theta=10000.0,
            max_position_embeddings=32768, bos_token_id=1,
            eos_token_ids=(2,), sliding_window=4096,
            chat_template="mistral",
        )

    @classmethod
    def qwen2_7b(cls) -> "LlamaConfig":
        """Qwen2-7B-Instruct: Llama architecture + QKV bias + ChatML
        (HF Qwen/Qwen2-7B-Instruct config.json)."""
        return cls(
            vocab_size=152064, hidden_size=3584, intermediate_size=18944,
            num_hidden_layers=28, num_attention_heads=28,
            num_key_value_heads=4, rms_norm_eps=1e-6, rope_theta=1e6,
            max_position_embeddings=32768, bos_token_id=151643,
            eos_token_ids=(151645, 151643), attention_bias=True,
            chat_template="chatml",
        )

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_hidden_layers=80, num_attention_heads=64,
            num_key_value_heads=8, rms_norm_eps=1e-5, rope_theta=500000.0,
            max_position_embeddings=8192,
        )
