"""Llama-3 forward functions: pure, jit-friendly, static shapes.

Block semantics match the reference decoder block (transformer.rs:51-73):
  x = x + attn(rms_norm(x))        # input_layernorm -> GQA+RoPE -> o_proj
  x = x + mlp(rms_norm(x))         # post_attention_layernorm -> SwiGLU
with attention accumulated in f32 (attention.rs:96-118) and RoPE from
precomputed tables (cache.rs:23-61).

The whole-model forward (reference llama.rs:72-137: embedding -> block walk
-> final norm -> last-position slice -> lm_head -> f32 logits) is expressed
as one `lax.scan` over the stacked block params; a contiguous sub-range of
the stack gives a pipeline stage's forward (parallel/pipeline.py).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from cake_tpu.models.llama.cache import (
    KVCache, update_layer_cache, update_layer_cache_per_row,
)
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.attention import (
    decode_mask, decode_mask_per_row, gqa_attention,
)
from cake_tpu.ops.flash_attention import (
    flash_attention, flash_attention_cached, flash_supported,
)
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.quant import qmatmul
from cake_tpu.ops.rope import (
    apply_rope, precompute_rope, rope_rows, rope_rows_per_row,
)


log = logging.getLogger(__name__)


class RopeTables(NamedTuple):
    cos: jnp.ndarray
    sin: jnp.ndarray

    @classmethod
    def create(cls, config: LlamaConfig, max_seq_len: int) -> "RopeTables":
        cos, sin = precompute_rope(
            config.head_dim, max_seq_len, config.rope_theta
        )
        return cls(cos, sin)


def block_skeleton(lp, x, config: LlamaConfig, attn_fn,
                   tp_axis: Optional[str] = None,
                   ep_axis: Optional[str] = None):
    """Decoder-block math with a pluggable attention:
    rms → qkv proj → attn_fn(q, k, v) → o_proj → residual → rms → FFN →
    residual (reference transformer.rs:51-73). attn_fn returns
    (attn [B,S,H,hd], extras) — extras carry e.g. updated caches.

    The FFN is dense SwiGLU (mlp.rs:15-18), or — when the layer params carry
    a `router` leaf (models/moe) — a sparse mixture-of-experts; every
    caller (scan, pipeline, ragged decode) works for both since blocks are
    just pytrees.

    tp_axis: when running *manually* tensor-parallel under shard_map, the
    mesh axis name to psum partial row-parallel outputs over (Megatron: o_proj
    and down_proj each produce partial sums). Head counts are derived from
    the weight shapes, so the same code runs on full or head-sharded weights.
    ep_axis: shard_map expert-parallel axis for the MoE path (ops/moe.py).
    """
    B, S, D = x.shape
    hd = config.head_dim
    H = lp["wq"].shape[-1] // hd      # local head count under TP
    KV = lp["wk"].shape[-1] // hd

    h = rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
    q = qmatmul(h, lp["wq"])
    k = qmatmul(h, lp["wk"])
    v = qmatmul(h, lp["wv"])
    if "bq" in lp:  # Qwen2-family QKV bias (config.attention_bias)
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    q = q.reshape(B, S, H, hd)
    k = k.reshape(B, S, KV, hd)
    v = v.reshape(B, S, KV, hd)
    attn, extras = attn_fn(q, k, v)
    attn_out = qmatmul(attn.reshape(B, S, H * hd), lp["wo"])
    if tp_axis is not None:
        attn_out = lax.psum(attn_out, tp_axis)
    x = x + attn_out

    h = rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
    if "router" in lp:
        from cake_tpu.ops.moe import moe_mlp
        # AttributeError here means MoE params were paired with a dense
        # LlamaConfig — a real mismatch that must not default silently.
        mlp_out = moe_mlp(h=h, lp=lp, ep_axis=ep_axis,
                          num_experts_per_tok=config.num_experts_per_tok)
    else:
        gate = jax.nn.silu(qmatmul(h, lp["w_gate"]))
        mlp_out = qmatmul(gate * qmatmul(h, lp["w_up"]), lp["w_down"])
    if tp_axis is not None:
        mlp_out = lax.psum(mlp_out, tp_axis)
    x = x + mlp_out
    return x, extras


def block_forward(lp, x, k_cache, v_cache, pos, rope_c, rope_s, mask,
                  config: LlamaConfig, tp_axis: Optional[str] = None,
                  ep_axis: Optional[str] = None,
                  is_prefill: bool = False, chunked: bool = False,
                  ring: bool = False, write_len=None):
    """One decoder block with KV-cache update.

    lp: single-layer param dict (leaves without the L axis)
    x:  [B, S, D]; k_cache/v_cache: [B, T, KV, hd]; pos: traced scalar
    rope_c/rope_s: [S, hd/2] rows for positions pos..pos+S
    mask: [S, T] boolean
    chunked: static — this prefill window continues an existing cache
    (pos may be > 0), so flash must use the cache-aware kernel; fresh
    whole-prompt prefill (pos == 0 by contract) uses the cheaper
    S-window kernel that never touches the cache tail.
    """
    S = x.shape[1]

    def attn_fn(q, k, v):
        H, KV = q.shape[2], k.shape[2]
        T = k_cache.shape[1]
        q = apply_rope(q, rope_c, rope_s)
        k = apply_rope(k, rope_c, rope_s)
        if ring:
            # Ring (sliding-window) uniform forward: attend the PRE-write
            # ring + the fresh window (a full-W window's write would
            # destroy in-window history its own early queries need), then
            # write. Ring slots permute key positions, which the flash
            # kernels' sequential-position masks cannot express -> einsum.
            from cake_tpu.models.llama.cache import update_layer_cache_ring
            k_full = jnp.concatenate(
                [k_cache, k.astype(k_cache.dtype)], axis=1)
            v_full = jnp.concatenate(
                [v_cache, v.astype(v_cache.dtype)], axis=1)
            attn = gqa_attention(q, k_full, v_full, mask=mask)
            kc, vc = update_layer_cache_ring(k_cache, v_cache, k, v, pos,
                                             n_real=write_len)
            return attn, (kc, vc)
        kc, vc = update_layer_cache(k_cache, v_cache, k, v, pos)
        use_flash = is_prefill and config.use_flash_attention
        if use_flash and not chunked and flash_supported(S, S, H, KV, hd=config.head_dim):
            # Fresh prompt at pos=0 with an empty cache: causal attention
            # over the in-window k/v IS the cached-decode mask, so the
            # kernel reads only the S fresh keys — no cache traffic.
            # Sliding-window models pass the window to the kernel (out-of-
            # window key blocks are skipped entirely).
            attn = flash_attention(q, k, v, causal=True,
                                   window=config.sliding_window)
        elif (use_flash and chunked and flash_supported(S, T, H, KV, hd=config.head_dim)
                and kc.dtype == q.dtype):
            # (dtype guard: the Pallas kernel reads the cache directly, so
            # fp8-stored KV takes the einsum path, which upcasts on read)
            # Continued prefill at pos>0: the cache-aware kernel attends
            # the cache under kj <= pos+qi; key blocks past the frontier
            # neither compute nor DMA (index-map clamp).
            attn = flash_attention_cached(q, kc, vc, pos,
                                          window=config.sliding_window)
        else:
            if use_flash:
                if (chunked and flash_supported(S, T, H, KV, hd=config.head_dim)
                        and kc.dtype != q.dtype):
                    # intended fallback, not a shape problem
                    log.debug(
                        "chunked prefill with %s-stored KV takes the "
                        "einsum path (upcast on read)", kc.dtype)
                else:
                    log.warning(
                        "flash attention requested but unsupported for "
                        "S=%d T=%d H=%d KV=%d (non-tileable shapes) — "
                        "falling back to the einsum path", S, T, H, KV)
            attn = gqa_attention(q, kc, vc, mask=mask)
        return attn, (kc, vc)

    x, (k_cache, v_cache) = block_skeleton(lp, x, config, attn_fn,
                                           tp_axis=tp_axis, ep_axis=ep_axis)
    return x, k_cache, v_cache


def run_blocks(blocks, x, cache: KVCache, pos, rope_c, rope_s, mask,
               config: LlamaConfig,
               tp_axis: Optional[str] = None,
               ep_axis: Optional[str] = None,
               is_prefill: bool = False,
               chunked: bool = False,
               ring: bool = False,
               write_len=None) -> Tuple[jnp.ndarray, KVCache]:
    """Scan the stacked blocks [L, ...] over the hidden state.

    This is the TPU equivalent of the reference's sequential block walk with
    contiguous-run batching (llama.rs:81-117): the scan compiles the whole
    contiguous range into one XLA program, so "batch blocks per hop" holds
    by construction.
    """
    def body(h, xs):
        lp, kc, vc = xs
        h, kc, vc = block_forward(lp, h, kc, vc, pos, rope_c, rope_s, mask,
                                  config, tp_axis=tp_axis, ep_axis=ep_axis,
                                  is_prefill=is_prefill, chunked=chunked,
                                  ring=ring, write_len=write_len)
        return h, (kc, vc)

    x, (k_new, v_new) = lax.scan(body, x, (blocks, cache.k, cache.v))
    return x, KVCache(k=k_new, v=v_new)


def forward(params, tokens, cache: KVCache, pos, rope: RopeTables,
            config: LlamaConfig, last_idx: Optional[jnp.ndarray] = None,
            return_hidden: bool = False, is_prefill: bool = False,
            chunked: bool = False, ring: bool = False, write_len=None):
    """Full forward: tokens [B, S] + cache @ pos -> (logits [B, V] f32, cache).

    last_idx: per-batch index of the final *real* token within the window
    (for right-padded prefill); defaults to S-1.
    """
    B, S = tokens.shape
    T = cache.max_seq_len
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows(rope.cos, rope.sin, pos, S)
    from cake_tpu.ops.attention import uniform_forward_mask
    mask = uniform_forward_mask(pos, S, T, config.sliding_window, ring,
                                n_real=write_len)
    x, cache = run_blocks(params["blocks"], x, cache, pos, rope_c, rope_s,
                          mask, config, is_prefill=is_prefill,
                          chunked=chunked, ring=ring, write_len=write_len)
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    if return_hidden:
        return x, cache
    if last_idx is None:
        last = x[:, -1]
    else:
        last = jnp.take_along_axis(
            x, last_idx.reshape(B, 1, 1).astype(jnp.int32), axis=1
        )[:, 0]
    logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def forward_logits_all(params, tokens, cache: KVCache, pos,
                       rope: RopeTables, config: LlamaConfig):
    """Logits at every position [B, S, V] (training / scoring path)."""
    x, cache = forward(params, tokens, cache, pos, rope, config,
                       return_hidden=True)
    return qmatmul(x, params["lm_head"]).astype(jnp.float32), cache


# -- jitted entry points -----------------------------------------------------

@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill(params, tokens, prompt_len, cache: KVCache, rope: RopeTables,
            config: LlamaConfig):
    """Process a (right-padded) prompt window starting at position 0.

    tokens:     [B, S_padded]
    prompt_len: [B] true lengths; logits taken at prompt_len-1.
    Padded slots write garbage KV beyond prompt_len, but decode masks by
    absolute position and overwrites slot `pos` before attending it, so the
    garbage is never observed.
    """
    last_idx = (prompt_len - 1).astype(jnp.int32)
    return forward(params, tokens, cache, jnp.int32(0), rope, config,
                   last_idx=last_idx, is_prefill=True)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def decode_step(params, token, pos, cache: KVCache, rope: RopeTables,
                config: LlamaConfig):
    """One KV-cached decode step: token [B, 1] at absolute pos -> logits."""
    return forward(params, token, cache, pos, rope, config)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_chunk(params, tokens, pos, last_idx, cache: KVCache,
                  rope: RopeTables, config: LlamaConfig):
    """Prefill ONE fixed-size window at absolute position `pos` (chunked
    prefill for long prompts). pos is traced, so every chunk of a prompt —
    and every prompt — reuses one compiled program per chunk shape. With
    flash enabled, attention runs the cache-aware Pallas kernel
    (ops/flash_attention.flash_attention_cached)."""
    return forward(params, tokens, cache, pos, rope, config,
                   last_idx=last_idx, is_prefill=True, chunked=True)


# -- ragged (per-row position) entry points for continuous batching ----------


def run_blocks_ragged(blocks, x, cache: KVCache, pos, active,
                      rope_c, rope_s, mask, config: LlamaConfig,
                      tp_axis: Optional[str] = None,
                      ep_axis: Optional[str] = None,
                      ring: bool = False,
                      cache_update=None
                      ) -> Tuple[jnp.ndarray, KVCache]:
    """Scan the stacked blocks for per-row-position ragged decode.

    x: [B, S, D]; pos/active: [B]; rope_c/rope_s: [B, S, hd/2] per-row
    rows; mask: [B, S, T]. S = 1 for single-token decode; the batched
    speculative verify passes S = gamma+1 windows with its own
    cache_update. Inactive rows compute garbage but leave their cache
    lines untouched. Shared by the single-device ragged decode, the
    pipelined engine step (parallel/pipeline.py — stage-local
    blocks/cache views), and forward_window_ragged, so the block-scan
    attention wiring exists exactly once.

    cache_update(kc, vc, k, v) -> (kc', vc'): override the per-layer KV
    write; default = single-token per-row write (ring-modular when
    ring=True)."""
    if cache_update is None:
        if ring:
            from cake_tpu.models.llama.cache import (
                update_layer_cache_per_row_ring,
            )

            def cache_update(kc, vc, k, v):
                return update_layer_cache_per_row_ring(kc, vc, k, v,
                                                       pos, active)
        else:
            def cache_update(kc, vc, k, v):
                return update_layer_cache_per_row(kc, vc, k, v, pos,
                                                  active)

    def body(h, xs):
        lp, kc, vc = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            kc2, vc2 = cache_update(kc, vc, k, v)
            return gqa_attention(q, kc2, vc2, mask=mask), (kc2, vc2)

        h, (kc, vc) = block_skeleton(lp, h, config, attn_fn,
                                     tp_axis=tp_axis, ep_axis=ep_axis)
        return h, (kc, vc)

    x, (k_new, v_new) = lax.scan(body, x, (blocks, cache.k, cache.v))
    return x, KVCache(k=k_new, v=v_new)


def ragged_decode(params, tokens, pos, active, cache: KVCache,
                  rope: RopeTables, config: LlamaConfig, blocks_runner,
                  ring: bool = False):
    """Shared frame for per-row-position single-token decode: embedding →
    per-row rope rows/masks → blocks_runner → final norm → logits.

    blocks_runner(blocks, x, cache, pos, active, rope_c, rope_s, mask)
    -> (y, cache) walks the decoder blocks — single-device scan here,
    shard_mapped pipeline in parallel/pipeline.make_engine_step_fns — so
    the ragged-decode frame exists exactly once.
    """
    T = cache.max_seq_len
    x = jnp.take(params["embed"], tokens, axis=0)
    rope_c, rope_s = rope_rows_per_row(rope.cos, rope.sin, pos)
    if ring:
        from cake_tpu.ops.attention import ring_decode_mask_per_row
        mask = ring_decode_mask_per_row(pos, T)
    else:
        mask = decode_mask_per_row(pos, T,
                                   window=config.sliding_window)
    x, cache = blocks_runner(params["blocks"], x, cache, pos, active,
                             rope_c, rope_s, mask)
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    logits = qmatmul(x[:, -1], params["lm_head"]).astype(jnp.float32)
    return logits, cache


def forward_ragged(params, tokens, cache: KVCache, pos, active,
                   rope: RopeTables, config: LlamaConfig):
    """Single-token decode where every batch row sits at its own position.

    tokens: [B, 1]; pos: [B] absolute positions; active: [B] bool —
    inactive rows (free slots between requests) compute garbage but leave
    their cache lines untouched. Returns (logits [B, V] f32, cache).
    """
    def runner(blocks, x, cache, pos, active, rope_c, rope_s, mask):
        return run_blocks_ragged(blocks, x, cache, pos, active,
                                 rope_c, rope_s, mask, config)

    return ragged_decode(params, tokens, pos, active, cache, rope, config,
                         runner)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def decode_step_ragged(params, tokens, pos, active, cache: KVCache,
                       rope: RopeTables, config: LlamaConfig):
    """Jitted ragged decode step (compiles once per batch size)."""
    return forward_ragged(params, tokens, cache, pos, active, rope, config)


def forward_window_ragged(params, tokens, cache: KVCache, pos0, active,
                          rope: RopeTables, config: LlamaConfig):
    """Score a W-token window per row, each row at its OWN start
    position — the batched speculative verify (one target pass scores
    every slot's [last_tok, drafts] burst concurrently, where the
    per-slot engine path ran B separate batch-1 passes, streaming the
    weights B times per round).

    tokens: [B, W]; pos0: [B] absolute start positions; active: [B].
    Row b's token j sits at position pos0[b]+j, attends cache slots
    <= pos0[b]+j, and writes its KV there. Returns
    (logits [B, W, V] f32, cache). Sliding-window configs are not
    supported (speculation is gated off them upstream)."""
    B, W = tokens.shape
    T = cache.max_seq_len
    x = jnp.take(params["embed"], tokens, axis=0)          # [B, W, D]
    # per-(row, offset) rope rows: [B, W, hd/2]
    p = pos0[:, None] + jnp.arange(W)[None]                # [B, W]
    p = jnp.clip(p, 0, T - 1)
    rope_c = jnp.take(rope.cos, p, axis=0)
    rope_s = jnp.take(rope.sin, p, axis=0)
    # [B, W, T]: query j of row b sees cache slots <= pos0[b]+j
    kj = jax.lax.broadcasted_iota(jnp.int32, (B, W, T), 2)
    mask = kj <= p[:, :, None]

    from cake_tpu.models.llama.cache import (
        update_layer_cache_window_per_row,
    )

    def window_update(kc, vc, k, v):
        return update_layer_cache_window_per_row(kc, vc, k, v, pos0,
                                                 active)

    x, cache = run_blocks_ragged(params["blocks"], x, cache, pos0,
                                 active, rope_c, rope_s, mask, config,
                                 cache_update=window_update)
    x = rms_norm(x, params["final_norm"], config.rms_norm_eps)
    logits = qmatmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def forward_ragged_ring(params, tokens, cache: KVCache, pos, active,
                        rope: RopeTables, config: LlamaConfig):
    """forward_ragged over a ring (sliding-window) cache: positions map
    to slot p % W and validity is ring-slot liveness
    (ops/attention.ring_decode_mask_per_row)."""
    def runner(blocks, x, cache, pos, active, rope_c, rope_s, mask):
        return run_blocks_ragged(blocks, x, cache, pos, active,
                                 rope_c, rope_s, mask, config, ring=True)

    return ragged_decode(params, tokens, pos, active, cache, rope, config,
                         runner, ring=True)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def decode_step_ragged_ring(params, tokens, pos, active, cache: KVCache,
                            rope: RopeTables, config: LlamaConfig):
    """Jitted ragged decode step over a ring cache (the engine's
    sliding-window serving path: KV memory = window, not max_seq)."""
    return forward_ragged_ring(params, tokens, cache, pos, active, rope,
                               config)


def slot_prefill(params, tokens, prompt_len, slot, cache: KVCache,
                 forward_fn, prefix: Optional[Tuple] = None, pos0=None):
    """Prefill ONE request into batch slot `slot` of a shared cache.

    tokens: [1, S_padded]; prompt_len: [1]; slot: traced scalar. The slot's
    cache lines are sliced out, prefilled via
    forward_fn(params, tokens, sub_cache, pos0, last_idx) -> (logits, sub),
    and written back — other slots' state is untouched, so requests can be
    admitted while their neighbors are mid-decode (continuous batching).
    Shared by the single-device and pipelined engine prefills; the slot
    slice/write-back splice lives in _slot_view/_slot_writeback.

    prefix: optional (k, v) [L, 1, P, KV, hd] — a cached prompt head
    installed into positions 0..P-1 first, with the window then starting
    at position P (prefix caching). pos0: optional traced start position
    for the window (chunked prefill); mutually exclusive with prefix.
    """
    assert prefix is None or pos0 is None, "prefix implies its own pos0"
    sub = _slot_view(cache, slot)
    if prefix is not None:
        sub = _install_prefix(sub, *prefix)
        pos0 = jnp.int32(prefix[0].shape[2])
    elif pos0 is None:
        pos0 = jnp.int32(0)
    last_idx = (prompt_len - 1).astype(jnp.int32)
    logits, sub = forward_fn(params, tokens, sub, pos0, last_idx)
    return logits, _slot_writeback(cache, sub, slot)


def _slot_view(cache: KVCache, slot) -> KVCache:
    """Slice one batch slot's cache lines out ([L, 1, T, KV, hd])."""
    return KVCache(
        k=lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1),
        v=lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1),
    )


def _install_prefix(sub: KVCache, pk, pv) -> KVCache:
    """Write cached-prefix KV [L, 1, P, KV, hd] at positions 0..P-1."""
    return KVCache(
        k=lax.dynamic_update_slice(
            sub.k, pk.astype(sub.k.dtype), (0, 0, 0, 0, 0)),
        v=lax.dynamic_update_slice(
            sub.v, pv.astype(sub.v.dtype), (0, 0, 0, 0, 0)),
    )


def _slot_writeback(cache: KVCache, sub: KVCache, slot) -> KVCache:
    """Splice one slot's updated lines back into the shared cache."""
    return KVCache(
        k=lax.dynamic_update_slice_in_dim(cache.k, sub.k, slot, axis=1),
        v=lax.dynamic_update_slice_in_dim(cache.v, sub.v, slot, axis=1),
    )


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_slot(params, tokens, prompt_len, slot, cache: KVCache,
                 rope: RopeTables, config: LlamaConfig):
    """Jitted single-device slot prefill (compiles once per bucket length)."""
    def fwd(p, t, sub, pos, last_idx):
        return forward(p, t, sub, pos, rope, config,
                       last_idx=last_idx, is_prefill=True)

    return slot_prefill(params, tokens, prompt_len, slot, cache, fwd)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_slot_chunk(params, tokens, n_real, slot, pos0,
                       cache: KVCache, rope: RopeTables,
                       config: LlamaConfig):
    """One fixed-size prefill window into batch slot `slot` at absolute
    position `pos0` (engine-side chunked prefill: every chunk of every
    prompt in any slot hits ONE compiled program per window shape).
    tokens: [1, C]; n_real: [1] count of real tokens in the window.
    """
    def fwd(p, t, sub, pos, last_idx):
        return forward(p, t, sub, pos, rope, config,
                       last_idx=last_idx, is_prefill=True, chunked=True)

    return slot_prefill(params, tokens, n_real, slot, cache, fwd,
                        pos0=pos0)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_slot_chunk_ring(params, tokens, n_real, slot, pos0,
                            cache: KVCache, rope: RopeTables,
                            config: LlamaConfig):
    """prefill_slot_chunk over a ring (sliding-window) cache: queries
    attend the pre-write ring + fresh window (ops/attention
    .ring_concat_mask), then the window writes ring slots (pos0+i) % W
    with junk-masked padding. Every prompt in ring mode walks through
    this (windows <= W keep scatter indices unique)."""
    def fwd(p, t, sub, pos, last_idx):
        return forward(p, t, sub, pos, rope, config,
                       last_idx=last_idx, is_prefill=True, chunked=True,
                       ring=True, write_len=n_real[0])

    return slot_prefill(params, tokens, n_real, slot, cache, fwd,
                        pos0=pos0)


@partial(jax.jit, donate_argnames=("cache",))
def install_prefix_slot(cache: KVCache, prefix_k, prefix_v, slot):
    """Copy cached-prefix KV [L, 1, P, KV, hd] into slot `slot` at
    positions 0..P-1 (prefix caching + chunked suffix: the install and
    the windows are separate programs)."""
    sub = _install_prefix(_slot_view(cache, slot), prefix_k, prefix_v)
    return _slot_writeback(cache, sub, slot)


@partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def prefill_slot_prefixed(params, tokens, suffix_len, slot,
                          prefix_k, prefix_v, cache: KVCache,
                          rope: RopeTables, config: LlamaConfig):
    """Slot prefill continuing a cached prefix (prefix/prompt caching).

    prefix_k/v: [L, 1, P, KV, hd] precomputed KV of the shared prompt
    head — installed into the slot's cache lines at positions 0..P-1,
    then the suffix window `tokens` [1, S_padded] prefills at position P
    through the cache-aware (chunked) path. Compiles once per
    (P, suffix bucket) pair; P is a registered-prefix property, so the
    set stays small.
    """
    def fwd(p, t, sub, pos, last_idx):
        return forward(p, t, sub, pos, rope, config,
                       last_idx=last_idx, is_prefill=True, chunked=True)

    return slot_prefill(params, tokens, suffix_len, slot, cache, fwd,
                        prefix=(prefix_k, prefix_v))
