"""Offline weight splitting: per-stage safetensors bundles.

Capability parity with `cake-split-model` (cake-split-model/src/main.rs):
for each topology node, select the tensors whose names prefix-match the
node's layers (main.rs:86-100), copy them into
`{worker}-node/model/reduced.safetensors` with a rewritten index plus a
single-entry topology.yml (main.rs:158-221), and round-trip-validate the
output (main.rs:199-205).

On TPU this tool matters for multi-host serving: each host pre-stages only
its pipeline stage's weights so model load is O(params/hosts) per host.
(For single-host meshes, `load_params_from_hf(layer_range=...)` already
loads stage-locally without any offline step.)
"""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

from cake_tpu.topology import Node, Topology
from cake_tpu.utils.loading import (
    load_weight_index, load_weights, save_safetensors,
)

# Tensors every stage needs regardless of block range (embedding, final
# norm, lm_head live on the first/last stage; we bundle them with any node
# that doesn't claim blocks, and with the first/last stages otherwise).
SHARED_TENSOR_PREFIXES = ("model.embed_tokens", "model.norm", "lm_head")


def reduce_for_node(model_dir: str, node: Node,
                    include_shared: bool = False) -> Dict[str, np.ndarray]:
    """Select this node's tensors (reference reduce_for_worker semantics)."""
    def want(name: str) -> bool:
        if node.owns_layer(name):
            return True
        if include_shared and name.startswith(SHARED_TENSOR_PREFIXES):
            return True
        return False

    return load_weights(model_dir, filter_fn=want)


def split_model(model_dir: str, topology_path: str, output_dir: str) -> list:
    """Write one `{node}-node/` bundle per topology entry.

    Layout matches the reference (main.rs:158-221):
      {output}/{node}-node/model/reduced.safetensors
      {output}/{node}-node/model/model.safetensors.index.json
      {output}/{node}-node/topology.yml
      + config.json / tokenizer.json copied alongside when present.
    """
    topo = Topology.from_path(topology_path)
    index = load_weight_index(model_dir)
    written = []

    for i, (name, node) in enumerate(topo.items()):
        tensors = reduce_for_node(model_dir, node, include_shared=(i == 0))
        if not tensors:
            raise ValueError(f"node '{name}' matches no tensors in the index")
        missing = [t for t in tensors if t not in index]
        if missing:
            raise ValueError(f"tensors not in source index: {missing[:5]}")

        node_dir = os.path.join(output_dir, f"{name}-node", "model")
        os.makedirs(node_dir, exist_ok=True)
        st_path = os.path.join(node_dir, "reduced.safetensors")
        tensors_np = {k: np.asarray(v) for k, v in tensors.items()}
        save_safetensors(st_path, tensors_np)

        # rewritten single-file index
        new_index = {
            "metadata": {"total_size": sum(
                v.nbytes for v in tensors_np.values())},
            "weight_map": {k: "reduced.safetensors" for k in tensors_np},
        }
        with open(os.path.join(node_dir, "model.safetensors.index.json"),
                  "w") as f:
            json.dump(new_index, f, indent=1)

        # single-node topology
        single = Topology.from_dict({name: {
            "host": node.host, "description": node.description,
            "layers": list(node.layers),
        }})
        with open(os.path.join(output_dir, f"{name}-node", "topology.yml"),
                  "w") as f:
            f.write(single.to_yaml())

        for extra in ("config.json", "tokenizer.json"):
            src = os.path.join(model_dir, extra)
            if os.path.exists(src):
                import shutil
                shutil.copy(src, os.path.join(node_dir, extra))

        # round-trip validation (reference main.rs:199-205)
        reloaded = load_weights(node_dir)
        if set(reloaded) != set(tensors_np):
            raise RuntimeError(f"validation failed for node '{name}'")
        for k in tensors_np:
            if reloaded[k].shape != tuple(tensors_np[k].shape):
                raise RuntimeError(f"shape mismatch for {k}")
        written.append((name, st_path, len(tensors_np)))
    return written


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="cake-split-model")
    p.add_argument("--model-path", required=True)
    p.add_argument("--topology", required=True)
    p.add_argument("--output", required=True)
    a = p.parse_args(argv)
    for name, path, n in split_model(a.model_path, a.topology, a.output):
        print(f"{name}: {n} tensors -> {path}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
