"""Tooling: weight splitting, cluster introspection, profiling helpers."""
