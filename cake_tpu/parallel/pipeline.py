"""Microbatched pipeline parallelism via shard_map + ppermute over ICI.

This is the TPU-native replacement for the reference's distribution model
(SURVEY.md §2.6-2.7): where the reference walks layer-range workers
sequentially over TCP — a depth-1 pipeline with one request in flight
(llama.rs:81-117) — here the stacked block parameters are sharded over a
`stage` mesh axis, hidden states move stage-to-stage with
`lax.ppermute` over ICI, and a GPipe-style schedule keeps every stage busy
once `num_microbatches >= num_stages`. Setting num_microbatches=1
reproduces the reference's depth-1 behavior exactly (useful for latency
comparisons), and the contiguous-block-batching optimization holds by
construction: a stage's whole block range is one fused XLA computation.

Composability: the stage body optionally runs manually tensor-parallel
(`tp` axis, Megatron psums inside the block — see
`model.block_forward(tp_axis=...)`) and data-parallel (`dp` axis shards the
batch; no collectives in the block math), so one shard_mapped program covers
dp x pp x tp.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import (
    RopeTables, run_blocks, run_blocks_ragged,
)
from cake_tpu.ops.attention import decode_mask
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.quant import expand_specs_for_quant, qmatmul
from cake_tpu.ops.rope import rope_rows


def _gpipe_stage_loop(k, v, x, run_microbatch, *, num_microbatches: int):
    """Shared GPipe tick schedule (runs under shard_map, per-device views).

    k, v: [L_local, B, T, KV_local, hd]; x: [B, S, D] (replicated over
    stage). `run_microbatch(inp, k_mb, v_mb, idx, mb)` runs this stage's
    blocks on one microbatch and returns (y, k_mb_new, v_mb_new); callers
    close over whatever per-row state they need and slice it with
    (idx, mb). Returns (out, k, v) with out valid on every stage after the
    final broadcast.
    """
    nstages = lax.axis_size("stage")
    sid = lax.axis_index("stage")
    M = num_microbatches
    B, S, D = x.shape
    assert B % M == 0, f"local batch {B} not divisible by microbatches {M}"
    mb = B // M

    buf = jnp.zeros((mb, S, D), x.dtype)     # incoming hidden state
    out = jnp.zeros_like(x)                  # final-stage outputs

    def tick(t, state):
        buf, out, k, v = state
        my_mb = t - sid                       # microbatch this stage handles
        live = jnp.logical_and(my_mb >= 0, my_mb < M)  # pipeline bubble?
        idx = jnp.clip(my_mb, 0, M - 1) * mb

        fresh = lax.dynamic_slice_in_dim(x, idx, mb, axis=0)
        inp = jnp.where(sid == 0, fresh, buf)

        k_mb = lax.dynamic_slice_in_dim(k, idx, mb, axis=1)
        v_mb = lax.dynamic_slice_in_dim(v, idx, mb, axis=1)
        y, k_new, v_new = run_microbatch(inp, k_mb, v_mb, idx, mb)
        # mask side effects when this stage has no live microbatch
        k_wr = jnp.where(live, k_new, k_mb)
        v_wr = jnp.where(live, v_new, v_mb)
        k = lax.dynamic_update_slice_in_dim(k, k_wr, idx, axis=1)
        v = lax.dynamic_update_slice_in_dim(v, v_wr, idx, axis=1)

        is_last = sid == nstages - 1
        cur = lax.dynamic_slice_in_dim(out, idx, mb, axis=0)
        out = lax.dynamic_update_slice_in_dim(
            out, jnp.where(jnp.logical_and(live, is_last), y, cur),
            idx, axis=0,
        )
        # hand this stage's result to the next stage over ICI
        buf = lax.ppermute(
            y, "stage", [(i, (i + 1) % nstages) for i in range(nstages)]
        )
        return buf, out, k, v

    buf, out, k, v = lax.fori_loop(0, M + nstages - 1, tick,
                                   (buf, out, k, v))
    # broadcast the last stage's result to every stage (tiny: [B,S,D])
    out = lax.psum(
        jnp.where(sid == nstages - 1, out, jnp.zeros_like(out)), "stage"
    )
    return out, k, v


def _stage_pipeline_body(blocks, k, v, x, pos, wlen, rope_c, rope_s,
                         mask, *,
                         config: LlamaConfig, num_microbatches: int,
                         tp_axis: Optional[str], is_prefill: bool = False,
                         chunked: bool = False, ring: bool = False):
    """Per-device body for uniform-position forward (prefill / batch
    decode): pos, rope rows and mask are shared across the batch.
    ring/wlen: sliding-window ring cache (stage-local [L_local, B, W]
    slices; writes wrap at W with wlen junk-masking — model.run_blocks
    ring semantics, identical per stage).
    """
    def run_microbatch(inp, k_mb, v_mb, idx, mb):
        y, cache_mb = run_blocks(
            blocks, inp, KVCache(k_mb, v_mb), pos, rope_c, rope_s, mask,
            config, tp_axis=tp_axis, is_prefill=is_prefill,
            chunked=chunked, ring=ring, write_len=wlen,
        )
        return y, cache_mb.k, cache_mb.v

    return _gpipe_stage_loop(k, v, x, run_microbatch,
                             num_microbatches=num_microbatches)


def _blocks_in_specs(config: LlamaConfig, tp_axis, params=None):
    """shard_map in_specs for the stacked block params; QTensor leaves get
    their (q, scale) spec pair expanded when an example params tree is
    given (required for --quant int8 under any topology)."""
    from cake_tpu.models.llama.params import block_param_keys, block_specs
    specs = block_specs(block_param_keys(config),
                        stage_axis="stage", tp_axis=tp_axis)
    if params is not None:
        specs = {k: specs[k] for k in params["blocks"]}
        specs = expand_specs_for_quant({"blocks": params["blocks"]},
                                       {"blocks": specs})["blocks"]
    return specs


def make_pipeline_forward(mesh: Mesh, config: LlamaConfig,
                          num_microbatches: int = 1,
                          tp: bool = False, dp: bool = False,
                          params=None, ring: bool = False):
    """Build a jitted pipelined forward(params, tokens, cache, pos, rope,
    last_idx, is_prefill) -> (logits, cache) for the given mesh.

    Sharding contract:
      params["blocks"]: layer axis over "stage" (+ head/ffn over "tp" if tp)
      cache:            layer over "stage", batch over "dp", kv-heads "tp"
      embed/lm_head/final_norm: replicated (or vocab-sharded by GSPMD)
    params: optional example pytree — pass when weights are int8-quantized
    so the QTensor leaves get matching in_specs.
    """
    tp_axis = "tp" if tp else None
    blocks_specs = _blocks_in_specs(config, tp_axis, params)

    dp_axis = "dp" if dp else None
    cache_spec = P("stage", dp_axis, None, tp_axis, None)
    x_spec = P(dp_axis, None, None)

    def make_stage_fn(is_prefill: bool, chunked: bool = False):
        return jax.shard_map(
            partial(_stage_pipeline_body, config=config,
                    num_microbatches=num_microbatches, tp_axis=tp_axis,
                    is_prefill=is_prefill, chunked=chunked, ring=ring),
            mesh=mesh,
            in_specs=(blocks_specs, cache_spec, cache_spec, x_spec,
                      P(), P(), P(), P(), P()),
            out_specs=(x_spec, cache_spec, cache_spec),
            check_vma=False,
        )

    stage_fns = {(False, False): make_stage_fn(False),
                 (True, False): make_stage_fn(True),
                 (True, True): make_stage_fn(True, chunked=True)}

    def forward_body(params, tokens, cache: KVCache, pos, rope: RopeTables,
                     last_idx=None, is_prefill: bool = False,
                     chunked: bool = False, write_len=None):
        B, S = tokens.shape
        T = cache.max_seq_len
        x = jnp.take(params["embed"], tokens, axis=0)
        rope_c, rope_s = rope_rows(rope.cos, rope.sin, pos, S)
        from cake_tpu.ops.attention import uniform_forward_mask
        mask = uniform_forward_mask(pos, S, T, config.sliding_window,
                                    ring, n_real=write_len)
        wlen = (jnp.int32(S) if write_len is None
                else jnp.asarray(write_len, jnp.int32))
        y, k, v = stage_fns[(is_prefill, chunked)](
            params["blocks"], cache.k, cache.v,
            x, pos, wlen, rope_c, rope_s, mask)
        y = rms_norm(y, params["final_norm"], config.rms_norm_eps)
        if last_idx is None:
            last = y[:, -1]
        else:
            last = jnp.take_along_axis(
                y, last_idx.reshape(B, 1, 1).astype(jnp.int32), axis=1
            )[:, 0]
        logits = qmatmul(last, params["lm_head"]).astype(jnp.float32)
        return logits, KVCache(k, v)

    jitted = jax.jit(forward_body, donate_argnames=("cache",),
                     static_argnames=("is_prefill", "chunked"))

    def pipeline_forward(*args, **kwargs):
        return jitted(*args, **kwargs)

    pipeline_forward.body = forward_body  # un-jitted, for embedding callers
    return pipeline_forward


# -- ragged (continuous-batching) pipeline ------------------------------------


def _stage_pipeline_body_ragged(blocks, k, v, x, pos, active,
                                rope_c, rope_s, mask, *,
                                config: LlamaConfig, num_microbatches: int,
                                tp_axis: Optional[str],
                                ring: bool = False):
    """Per-device GPipe body for per-row-position single-token decode:
    every per-row quantity (pos, active, rope rows, mask) is sliced per
    microbatch and the stage runs `run_blocks_ragged`. x: [B, 1, D].
    """
    def run_microbatch(inp, k_mb, v_mb, idx, mb):
        sl = partial(lax.dynamic_slice_in_dim, start_index=idx,
                     slice_size=mb, axis=0)
        y, cache_mb = run_blocks_ragged(
            blocks, inp, KVCache(k_mb, v_mb), sl(pos), sl(active),
            sl(rope_c), sl(rope_s), sl(mask), config, tp_axis=tp_axis,
            ring=ring,
        )
        return y, cache_mb.k, cache_mb.v

    return _gpipe_stage_loop(k, v, x, run_microbatch,
                             num_microbatches=num_microbatches)


def make_engine_step_fns(mesh: Mesh, config: LlamaConfig,
                         num_microbatches: int = 1, tp: bool = False,
                         params=None, ring: bool = False):
    """Pipelined replacements for the engine's jitted steps.

    Returns (prefill_slot_fn, decode_ragged_fn, decode_scan_fn,
    prefill_chunk_fn) with the exact call signatures of
    model.prefill_slot / model.decode_step_ragged / the engine's
    decode-scan / model.prefill_slot_chunk, so serve/engine.py runs
    continuous batching — including K-step scanned decode and chunked
    prefill — over a topology-sharded model unchanged. The batch (slot)
    axis is NOT dp-sharded — slots are admitted one at a time and sliced
    dynamically, which must stay local.
    """
    tp_axis = "tp" if tp else None
    blocks_specs = _blocks_in_specs(config, tp_axis, params)
    cache_spec = P("stage", None, None, tp_axis, None)
    x_spec = P(None, None, None)

    from cake_tpu.models.llama.model import ragged_decode, slot_prefill

    fwd = make_pipeline_forward(mesh, config, num_microbatches=1, tp=tp,
                                dp=False, params=params, ring=ring)
    model_config = config

    ragged_stage = jax.shard_map(
        partial(_stage_pipeline_body_ragged, config=config,
                num_microbatches=num_microbatches, tp_axis=tp_axis,
                ring=ring),
        mesh=mesh,
        in_specs=(blocks_specs, cache_spec, cache_spec, x_spec,
                  P(), P(), P(), P(), P()),
        out_specs=(x_spec, cache_spec, cache_spec),
        check_vma=False,
    )

    # logits leave the program fully replicated: multi-host serving
    # localizes them per-process (np.asarray) so sampling needs no
    # cross-process collective; single-host this is what GSPMD picks
    # anyway for a [B, V] tensor computed from replicated operands
    logits_repl = NamedSharding(mesh, P())

    def ragged_forward(params, tokens, cache, pos, active, rope, config):
        """model.forward_ragged-shaped pipelined forward (un-jitted:
        traced inside decode_ragged_fn and the decode scan)."""
        def runner(blocks, x, cache, pos, active, rope_c, rope_s, mask):
            y, k, v = ragged_stage(blocks, cache.k, cache.v, x,
                                   pos, active, rope_c, rope_s, mask)
            return y, KVCache(k, v)

        return ragged_decode(params, tokens, pos, active, cache,
                             rope, model_config, runner, ring=ring)

    @partial(jax.jit, donate_argnames=("cache",),
             static_argnames=("config",))
    def prefill_slot_fn(params, tokens, prompt_len, slot, cache: KVCache,
                        rope: RopeTables, config=None):
        if ring:
            # the engine routes EVERY ring prompt through chunk windows;
            # a whole-bucket prefill could exceed the ring capacity
            raise RuntimeError(
                "whole-bucket prefill is not available on the ring "
                "pipelined path (engine forces chunked prefill)")

        def pipelined(p, t, sub, pos, last_idx):
            return fwd.body(p, t, sub, pos, rope,
                            last_idx=last_idx, is_prefill=True)

        logits, cache = slot_prefill(params, tokens, prompt_len, slot,
                                     cache, pipelined)
        return jax.lax.with_sharding_constraint(logits, logits_repl), cache

    @partial(jax.jit, donate_argnames=("cache",),
             static_argnames=("config",))
    def decode_ragged_fn(params, tokens, pos, active, cache: KVCache,
                         rope: RopeTables, config=None):
        logits, cache = ragged_forward(params, tokens, cache, pos, active,
                                       rope, config)
        return jax.lax.with_sharding_constraint(logits, logits_repl), cache

    from cake_tpu.serve.engine import make_decode_scan
    decode_scan_fn = make_decode_scan(ragged_forward,
                                      out_sharding=logits_repl)

    @partial(jax.jit, donate_argnames=("cache",),
             static_argnames=("config",))
    def prefill_chunk_fn(params, tokens, n_real, slot, pos0,
                         cache: KVCache, rope: RopeTables, config=None):
        """Pipelined analog of model.prefill_slot_chunk: one fixed-size
        window into slot `slot` at absolute position pos0, through the
        cache-aware (chunked) pipelined forward."""
        def pipelined(p, t, sub, pos, last_idx):
            return fwd.body(p, t, sub, pos, rope, last_idx=last_idx,
                            is_prefill=True, chunked=True,
                            write_len=n_real[0] if ring else None)

        logits, cache = slot_prefill(params, tokens, n_real, slot, cache,
                                     pipelined, pos0=pos0)
        return jax.lax.with_sharding_constraint(logits, logits_repl), cache

    return prefill_slot_fn, decode_ragged_fn, decode_scan_fn, prefill_chunk_fn


def pipeline_param_specs(blocks_keys, tp_axis: Optional[str] = None):
    """The param PartitionSpec tree make_pipeline_forward expects: stacked
    layer dim over "stage" (the reference's topology.yml block-range
    assignment), heads/ffn over tp; embed/lm_head/norms replicated."""
    from cake_tpu.models.llama.params import block_specs
    return {
        "embed": P(None, None),
        "blocks": block_specs(blocks_keys, stage_axis="stage",
                              tp_axis=tp_axis),
        "final_norm": P(None),
        "lm_head": P(None, None),
    }


def place_for_pipeline(params, cache: KVCache, mesh: Mesh, *,
                       tp: bool = False, dp: bool = False):
    """device_put params/cache with the shardings make_pipeline_forward
    expects. QTensor leaves place via their expanded (q, scale) specs."""
    from cake_tpu.parallel.sharding import tree_shard
    tp_axis = "tp" if tp else None
    dp_axis = "dp" if dp else None

    specs = pipeline_param_specs(params["blocks"].keys(), tp_axis)
    out = tree_shard(params, mesh, specs)
    from cake_tpu.parallel.sharding import shard_cache
    cache = shard_cache(cache, mesh, tp_axis=tp_axis, dp_axis=dp_axis,
                        stage_axis="stage")
    return out, cache
