"""Microbatched pipeline parallelism via shard_map + ppermute over ICI.

This is the TPU-native replacement for the reference's distribution model
(SURVEY.md §2.6-2.7): where the reference walks layer-range workers
sequentially over TCP — a depth-1 pipeline with one request in flight
(llama.rs:81-117) — here the stacked block parameters are sharded over a
`stage` mesh axis, hidden states move stage-to-stage with
`lax.ppermute` over ICI, and a GPipe-style schedule keeps every stage busy
once `num_microbatches >= num_stages`. Setting num_microbatches=1
reproduces the reference's depth-1 behavior exactly (useful for latency
comparisons), and the contiguous-block-batching optimization holds by
construction: a stage's whole block range is one fused XLA computation.

Composability: the stage body optionally runs manually tensor-parallel
(`tp` axis, Megatron psums inside the block — see
`model.block_forward(tp_axis=...)`) and data-parallel (`dp` axis shards the
batch; no collectives in the block math), so one shard_mapped program covers
dp x pp x tp.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import RopeTables, run_blocks
from cake_tpu.ops.attention import decode_mask
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_rows


def _stage_pipeline_body(blocks, k, v, x, pos, rope_c, rope_s, mask, *,
                         config: LlamaConfig, num_microbatches: int,
                         tp_axis: Optional[str], is_prefill: bool = False):
    """Per-device body (runs under shard_map; all views are local shards).

    blocks: [L_local, ...] — this stage's contiguous block range
    k, v:   [L_local, B_local, T, KV_local, hd]
    x:      [B_local, S, D] input hidden states (replicated over stage)
    Returns out [B_local, S, D] (valid on every stage after the final
    broadcast) and the updated local cache.
    """
    nstages = lax.axis_size("stage")
    sid = lax.axis_index("stage")
    M = num_microbatches
    B, S, D = x.shape
    assert B % M == 0, f"local batch {B} not divisible by microbatches {M}"
    mb = B // M

    buf = jnp.zeros((mb, S, D), x.dtype)     # incoming hidden state
    out = jnp.zeros_like(x)                  # final-stage outputs

    def tick(t, state):
        buf, out, k, v = state
        my_mb = t - sid                       # microbatch this stage handles
        active = jnp.logical_and(my_mb >= 0, my_mb < M)
        idx = jnp.clip(my_mb, 0, M - 1) * mb

        fresh = lax.dynamic_slice_in_dim(x, idx, mb, axis=0)
        inp = jnp.where(sid == 0, fresh, buf)

        k_mb = lax.dynamic_slice_in_dim(k, idx, mb, axis=1)
        v_mb = lax.dynamic_slice_in_dim(v, idx, mb, axis=1)
        y, cache_mb = run_blocks(
            blocks, inp, KVCache(k_mb, v_mb), pos, rope_c, rope_s, mask,
            config, tp_axis=tp_axis, is_prefill=is_prefill,
        )
        # mask side effects when this stage has no live microbatch
        k_wr = jnp.where(active, cache_mb.k, k_mb)
        v_wr = jnp.where(active, cache_mb.v, v_mb)
        k = lax.dynamic_update_slice_in_dim(k, k_wr, idx, axis=1)
        v = lax.dynamic_update_slice_in_dim(v, v_wr, idx, axis=1)

        is_last = sid == nstages - 1
        cur = lax.dynamic_slice_in_dim(out, idx, mb, axis=0)
        out = lax.dynamic_update_slice_in_dim(
            out, jnp.where(jnp.logical_and(active, is_last), y, cur),
            idx, axis=0,
        )
        # hand this stage's result to the next stage over ICI
        buf = lax.ppermute(
            y, "stage", [(i, (i + 1) % nstages) for i in range(nstages)]
        )
        return buf, out, k, v

    buf, out, k, v = lax.fori_loop(0, M + nstages - 1, tick,
                                   (buf, out, k, v))
    # broadcast the last stage's result to every stage (tiny: [B,S,D])
    out = lax.psum(
        jnp.where(sid == nstages - 1, out, jnp.zeros_like(out)), "stage"
    )
    return out, k, v


def make_pipeline_forward(mesh: Mesh, config: LlamaConfig,
                          num_microbatches: int = 1,
                          tp: bool = False, dp: bool = False):
    """Build a jitted pipelined forward(params, tokens, cache, pos, rope,
    last_idx) -> (logits, cache) for the given mesh.

    Sharding contract:
      params["blocks"]: layer axis over "stage" (+ head/ffn over "tp" if tp)
      cache:            layer over "stage", batch over "dp", kv-heads "tp"
      embed/lm_head/final_norm: replicated (or vocab-sharded by GSPMD)
    """
    from cake_tpu.models.llama.params import block_param_keys, block_specs
    tp_axis = "tp" if tp else None
    blocks_specs = block_specs(block_param_keys(config),
                               stage_axis="stage", tp_axis=tp_axis)

    dp_axis = "dp" if dp else None
    cache_spec = P("stage", dp_axis, None, tp_axis, None)
    x_spec = P(dp_axis, None, None)

    def make_stage_fn(is_prefill: bool):
        return jax.shard_map(
            partial(_stage_pipeline_body, config=config,
                    num_microbatches=num_microbatches, tp_axis=tp_axis,
                    is_prefill=is_prefill),
            mesh=mesh,
            in_specs=(blocks_specs, cache_spec, cache_spec, x_spec,
                      P(), P(), P(), P()),
            out_specs=(x_spec, cache_spec, cache_spec),
            check_vma=False,
        )

    stage_fns = {False: make_stage_fn(False), True: make_stage_fn(True)}

    @partial(jax.jit, donate_argnames=("cache",),
             static_argnames=("is_prefill",))
    def pipeline_forward(params, tokens, cache: KVCache, pos,
                         rope: RopeTables, last_idx=None,
                         is_prefill: bool = False):
        B, S = tokens.shape
        T = cache.max_seq_len
        x = jnp.take(params["embed"], tokens, axis=0)
        rope_c, rope_s = rope_rows(rope.cos, rope.sin, pos, S)
        mask = decode_mask(pos, S, T)
        y, k, v = stage_fns[is_prefill](params["blocks"], cache.k, cache.v,
                                        x, pos, rope_c, rope_s, mask)
        y = rms_norm(y, params["final_norm"], config.rms_norm_eps)
        if last_idx is None:
            last = y[:, -1]
        else:
            last = jnp.take_along_axis(
                y, last_idx.reshape(B, 1, 1).astype(jnp.int32), axis=1
            )[:, 0]
        logits = (last @ params["lm_head"]).astype(jnp.float32)
        return logits, KVCache(k, v)

    return pipeline_forward


def place_for_pipeline(params, cache: KVCache, mesh: Mesh, *,
                       tp: bool = False, dp: bool = False):
    """device_put params/cache with the shardings make_pipeline_forward
    expects. The stacked layer dim maps contiguous ranges onto stages —
    exactly the reference's topology.yml block-range assignment."""
    tp_axis = "tp" if tp else None
    dp_axis = "dp" if dp else None

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    from cake_tpu.models.llama.params import block_specs
    blocks = params["blocks"]
    bspec = block_specs(blocks.keys(), stage_axis="stage", tp_axis=tp_axis)
    out = {
        "embed": put(params["embed"], P(None, None)),
        "blocks": {kk: put(blocks[kk], bspec[kk]) for kk in blocks},
        "final_norm": put(params["final_norm"], P(None)),
        "lm_head": put(params["lm_head"], P(None, None)),
    }
    cspec = P("stage", dp_axis, None, tp_axis, None)
    cache = KVCache(k=put(cache.k, cspec), v=put(cache.v, cspec))
    return out, cache
