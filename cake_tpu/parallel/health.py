"""Failure detection: device probes, heartbeats, progress watchdog.

The reference has **none** (SURVEY.md §5): a worker crash mid-generation
bubbles an error and kills the request, with no heartbeat, retry, or
detection. This module provides the three detection layers a long-running
TPU serving deployment needs:

  * `probe_devices(timeout_s)` — runs a tiny computation on every local
    device in a watchdog thread; a hung accelerator/tunnel (which blocks
    forever rather than raising) is reported as wedged instead of hanging
    the caller.
  * `HeartbeatMonitor` / `HeartbeatSender` — coordinator-side liveness
    tracking of worker hosts over plain TCP (JAX's control plane has no
    user-visible liveness API; a stale heartbeat is the signal to alert or
    restart before a collective deadlocks on the dead host).
  * `Watchdog` — generic progress monitor: polls a counter (e.g.
    `engine.stats.steps`) and fires a callback when it stops advancing.

All components are dependency-free and run in daemon threads; tests drive
them on localhost/CPU.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cake_tpu.obs import metrics as obs_metrics

log = logging.getLogger(__name__)

# stall detections are rare and load-bearing (each one failed every
# in-flight request): a counter so dashboards see them without log
# spelunking
_WATCHDOG_STALLS = obs_metrics.counter(
    "cake_watchdog_stalls_total",
    "Progress-watchdog stall detections (engine stopped advancing "
    "with active requests)")

# reconnect storms are the classic monitor-restart failure mode; the
# counter makes a flapping heartbeat channel visible per worker
_HEARTBEAT_RECONNECTS = obs_metrics.counter(
    "cake_heartbeat_reconnects_total",
    "Heartbeat-sender reconnection attempts after a lost or refused "
    "monitor connection, by worker",
    labelnames=("worker",))

# wire latency of the liveness plane itself: the monitor acks each
# beat with one byte, and the sender times send->ack. A rising RTT is
# the early signal of a congested/flaky coordinator link — before the
# staleness gauge trips anything
_HEARTBEAT_RTT = obs_metrics.histogram(
    "cake_heartbeat_rtt_seconds",
    "Heartbeat round-trip time (send 'name\\n' -> monitor ack byte), "
    "by worker — wire latency of the coordinator liveness channel",
    labelnames=("worker",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5))


# -- device probe ------------------------------------------------------------

@dataclass
class DeviceProbe:
    device: str
    ok: bool
    latency_s: float
    error: Optional[str] = None


def probe_devices(timeout_s: float = 30.0, devices=None) -> List[DeviceProbe]:
    """Health-check local devices with a wall-clock timeout each.

    A tiny computation is dispatched from a worker thread; if it neither
    completes nor raises within timeout_s the device is reported wedged
    (ok=False, error='timeout') — unlike a bare jnp op, this never hangs
    the caller on a dead accelerator or tunnel.
    """
    import jax
    import jax.numpy as jnp

    devices = list(devices) if devices is not None else jax.local_devices()
    out: List[DeviceProbe] = []
    for dev in devices:
        result: Dict = {}

        def work(dev=dev, result=result):
            try:
                t0 = time.perf_counter()
                x = jax.device_put(jnp.arange(8, dtype=jnp.float32), dev)
                float((x * 2).sum())  # block until the device answers
                result["latency"] = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — report, don't raise
                result["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=work, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            out.append(DeviceProbe(str(dev), False, timeout_s,
                                   error="timeout"))
        elif "error" in result:
            out.append(DeviceProbe(str(dev), False, 0.0, result["error"]))
        else:
            out.append(DeviceProbe(str(dev), True, result["latency"]))
    return out


# -- heartbeats --------------------------------------------------------------

class HeartbeatMonitor:
    """Coordinator-side liveness tracker.

    Workers connect over TCP and send `name\\n` lines periodically; the
    monitor records last-seen times. `stale(threshold_s)` lists workers
    whose heartbeat lapsed; `on_failure`, if set, fires once per worker
    when it first goes stale (checked by a background sweeper).
    """

    def __init__(self, address: str = "127.0.0.1:0",
                 on_failure: Optional[Callable[[str], None]] = None,
                 stale_after_s: float = 10.0, sweep_interval_s: float = 1.0,
                 expected: Optional[List[str]] = None):
        host, port = address.rsplit(":", 1)
        self.last_seen: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._failed: set = set()
        self._on_failure = on_failure
        self._stale_after = stale_after_s
        monitor = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    name = line.decode("utf-8", "replace").strip()
                    if name:
                        monitor.beat(name)
                        try:
                            # one-byte ack: the sender times send->ack
                            # into cake_heartbeat_rtt_seconds; a peer
                            # that never reads it just buffers a byte
                            self.wfile.write(b"\x06")
                            self.wfile.flush()
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, int(port)), Handler)
        self.address = "%s:%d" % self._server.server_address[:2]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="cake-heartbeat-server")
        self._serve_thread.start()
        self._stop = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep, args=(sweep_interval_s,), daemon=True,
            name="cake-heartbeat-sweeper")
        self._sweeper.start()

        if expected:
            self.expect(*expected)

    def expect(self, *names: str) -> None:
        """Register workers that MUST beat. Registration starts the stale
        clock, so a worker that dies before its first heartbeat is reported
        after stale_after_s instead of staying invisible (a monitor that
        only tracks seen workers cannot detect a never-started one —
        precisely the failure the subsystem exists for)."""
        now = time.monotonic()
        with self._lock:
            for name in names:
                self.last_seen.setdefault(name, now)

    def beat(self, name: str) -> None:
        with self._lock:
            self.last_seen[name] = time.monotonic()
            self._failed.discard(name)

    def stale(self, threshold_s: Optional[float] = None) -> List[str]:
        thr = threshold_s if threshold_s is not None else self._stale_after
        now = time.monotonic()
        with self._lock:
            return [n for n, t in self.last_seen.items() if now - t > thr]

    def staleness(self) -> Dict[str, float]:
        """Seconds since each tracked worker's last heartbeat (the
        /metrics staleness gauge's source)."""
        now = time.monotonic()
        with self._lock:
            return {n: now - t for n, t in self.last_seen.items()}

    def _sweep(self, interval: float) -> None:
        while not self._stop.wait(interval):
            for name in self.stale():
                with self._lock:
                    first = name not in self._failed
                    self._failed.add(name)
                if first:
                    log.warning("heartbeat lost: %s", name)
                    if self._on_failure is not None:
                        try:
                            self._on_failure(name)
                        except Exception:  # noqa: BLE001
                            log.exception("on_failure callback failed")

    def close(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


class HeartbeatSender:
    """Worker-side pinger: connects to the monitor and sends `name\\n`
    every interval_s from a daemon thread until close().

    CONNECT_TIMEOUT_S bounds each (re)dial; worst_case_gap_s budgets
    it, so raising one without the other cannot silently shrink the
    follower liveness window below the sender's real quiet gap.

    Reconnects back off exponentially (capped, with seeded per-worker
    jitter): a restarted monitor on a large fleet used to get every
    sender re-dialing in interval_s lockstep — a thundering herd right
    when the coordinator is busiest coming back. The jitter stream is
    seeded from the worker name, so a chaos run's reconnect schedule
    is reproducible."""

    CONNECT_TIMEOUT_S = 5.0

    def __init__(self, address: str, name: str, interval_s: float = 2.0,
                 max_backoff_s: float = 30.0):
        import random as _random

        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._name = name
        self._interval = interval_s
        self._max_backoff = max_backoff_s
        self._failures = 0        # consecutive connect/send failures
        self.reconnects = 0       # lifetime reconnect attempts
        # deterministic per-worker jitter: same worker name -> same
        # desynchronization offsets, run after run
        self._rng = _random.Random(
            int.from_bytes(name.encode()[:8].ljust(8, b"\0"), "big"))
        # monotonic time of the last SUCCESSFUL send — the follower
        # liveness probe (engine.run_follower_loop) reads it: the
        # monitor lives in the coordinator process, so a recent
        # successful send proves the peer is up
        self._last_ok: float = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cake-heartbeat-{name}")
        self._thread.start()

    def alive_within(self, threshold_s: float) -> bool:
        """True when a heartbeat send succeeded within threshold_s —
        evidence the monitor (and so the coordinator process hosting
        it) is alive."""
        return (self._last_ok > 0
                and time.monotonic() - self._last_ok < threshold_s)

    @property
    def worst_case_gap_s(self) -> float:
        """Upper bound on the quiet gap between SUCCESSFUL sends while
        the monitor stays reachable: one send interval, plus a full
        backoff sleep at the cap with its 1.5x jitter, plus one
        connect timeout. A liveness threshold below this misreads a
        sender mid-backoff (monitor blipped, already back) as a dead
        coordinator."""
        return (self._interval + 1.5 * self._max_backoff
                + self.CONNECT_TIMEOUT_S)

    def _run(self) -> None:
        sock = None
        while not self._stop.is_set():
            try:
                if sock is None:
                    if self._failures:
                        self.reconnects += 1
                        _HEARTBEAT_RECONNECTS.labels(
                            worker=self._name).inc()
                    sock = socket.create_connection(
                        self._addr, timeout=self.CONNECT_TIMEOUT_S)
                t_beat = time.perf_counter()
                sock.sendall(f"{self._name}\n".encode())
                try:
                    # read the monitor's one-byte ack and observe the
                    # RTT. A timeout (busy monitor, or one predating
                    # the ack) is NOT a failure — the send succeeded,
                    # we only lose this sample. A late ack read by the
                    # NEXT beat shortens that sample; acceptable noise
                    # for a wire-latency trend signal.
                    sock.settimeout(min(2.0, self._interval))
                    ack = sock.recv(64)
                    if not ack:
                        raise OSError("heartbeat monitor closed")
                    _HEARTBEAT_RTT.labels(worker=self._name).observe(
                        time.perf_counter() - t_beat)
                except socket.timeout:
                    pass
                finally:
                    sock.settimeout(self.CONNECT_TIMEOUT_S)
                self._failures = 0
                self._last_ok = time.monotonic()
                self._stop.wait(self._interval)
            except OSError:
                if sock is not None:
                    sock.close()
                    sock = None
                self._failures += 1
                # capped exponential backoff + jitter: spread the
                # fleet's re-dials instead of stampeding the monitor
                delay = min(self._max_backoff,
                            self._interval * (2.0 ** (self._failures - 1)))
                delay *= 0.5 + self._rng.random()   # 0.5x..1.5x
                self._stop.wait(delay)
        if sock is not None:
            sock.close()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# -- serving glue ------------------------------------------------------------

class ServingHealth:
    """Failure detection wired into the serving path (SURVEY §5).

    Composes the detectors around a live engine so a failure actually
    does something: the API flips /api/v1/health to "failed", new chat
    requests get 503s (api/server.py gates on `failed`), and every
    in-flight request is failed immediately instead of hanging its
    client until timeout.

      * a Watchdog on tokens_generated fires when the engine stops
        making progress with active requests (wedged device, dead host
        blocking a collective);
      * `expect_workers()` (multi-host serving) starts a
        HeartbeatMonitor over the follower hosts — a lapsed heartbeat
        fails serving before the next collective deadlocks on the dead
        host (cli._serve_multihost wires the followers' senders).
    """

    def __init__(self, engine, stall_after_s: float = 600.0):
        self.engine = engine
        self.reason: Optional[str] = None
        self._lock = threading.Lock()
        self._recoverable = False
        self._failed_at_tokens = 0
        self.monitor: Optional[HeartbeatMonitor] = None
        # device/page gauge refresh rides the watchdog's poll (the
        # "existing heartbeat"), rate-limited so memory_stats isn't
        # called every 0.5s
        self._gauges_at = 0.0
        self._gauge_interval_s = 5.0
        # tokens_generated advances on prefill first-tokens too, so a
        # long prefill is not a false stall; stall_after_s must exceed
        # worst-case first-request compile time (configurable via
        # --stall-timeout; a too-small value + giant compile would
        # false-fail, which is why stall failures self-recover below)
        self._watchdog = Watchdog(
            self._progress_counter,
            stall_after_s,
            on_stall=self._on_stall,
            active=lambda: engine.active > 0,
        )
        self._stall_after = stall_after_s

    def _on_stall(self) -> None:
        _WATCHDOG_STALLS.inc()
        self.fail(
            f"engine made no progress for {self._stall_after:.0f}s "
            "with active requests", recoverable=True)

    def observe_metrics(self) -> None:
        """Sync health state into the metrics registry — called by
        ApiServer.metrics() at scrape time, so the staleness gauge
        reflects the instant of the scrape (not the last sweep)."""
        if self.monitor is not None:
            g = obs_metrics.gauge(
                "cake_heartbeat_staleness_seconds",
                "Seconds since each worker's last heartbeat",
                labelnames=("worker",))
            for name, age in self.monitor.staleness().items():
                g.labels(worker=name).set(round(age, 3))
        self._refresh_gauges(force=True)

    def _refresh_gauges(self, force: bool = False) -> None:
        """Per-device HBM gauges (obs/steps.py; no-op on CPU) and
        page-pool occupancy, refreshed on the watchdog heartbeat so
        dashboards fed only by --step-log / pushed expositions stay
        current without scrapes. force=True (scrape time) bypasses the
        rate limit."""
        now = time.monotonic()
        if not force and now - self._gauges_at < self._gauge_interval_s:
            return
        self._gauges_at = now
        try:
            from cake_tpu.obs import steps as obs_steps
            obs_steps.refresh_device_gauges()
            obs_steps.refresh_page_gauges(self.engine)
        except Exception:  # noqa: BLE001 — telemetry must never fail health
            log.debug("device gauge refresh failed", exc_info=True)

    def _progress_counter(self) -> int:
        """Watchdog counter; doubles as the recovery probe: a stall
        failure (recoverable) clears itself the moment tokens flow again
        — e.g. a false positive from an extra-long XLA compile must not
        brick an otherwise healthy server. Heartbeat failures (a dead
        host) never self-clear."""
        self._refresh_gauges()
        v = self.engine.stats.tokens_generated
        with self._lock:
            if (self.reason is not None and self._recoverable
                    and v != self._failed_at_tokens):
                log.warning("serving health: RECOVERED (progress resumed "
                            "after: %s)", self.reason)
                self.reason = None
        return v

    @property
    def failed(self) -> bool:
        return self.reason is not None

    def expect_workers(self, names: List[str], bind_host: str = "",
                       stale_after_s: float = 15.0) -> str:
        """Start heartbeat monitoring for worker hosts that MUST stay
        alive. Returns the monitor's bound address for distribution to
        the workers (cli broadcasts it on the control handshake)."""
        self.monitor = HeartbeatMonitor(
            address=f"{bind_host}:0",
            on_failure=lambda n: self.fail(f"worker {n} heartbeat lost"),
            stale_after_s=stale_after_s,
            expected=list(names),
        )
        return self.monitor.address

    def fail(self, reason: str, recoverable: bool = False) -> None:
        """Idempotent: first failure wins; later detections are logged
        only. Fails every in-flight engine request so clients see an
        error now, not a timeout. (The engine thread may be wedged in a
        collective — _fail_all from this thread releases the waiters;
        request teardown races are benign because _emit re-checks
        _slot_req identity.) recoverable: the condition can clear itself
        when progress resumes (watchdog stalls); non-recoverable
        failures (dead hosts) latch until restart."""
        with self._lock:
            if self.reason is not None:
                log.warning("serving health (already failed): %s", reason)
                return
            self.reason = reason
            self._recoverable = recoverable
            self._failed_at_tokens = self.engine.stats.tokens_generated
        log.error("serving health: FAILED — %s", reason)
        try:
            # non-recoverable failures (dead host) are fatal: snapshot
            # the in-flight requests for restart-and-resume before
            # failing them. Recoverable stalls may clear — no snapshot.
            self.engine._fail_all(
                RuntimeError(f"serving failed: {reason}"),
                snapshot=not recoverable)
        except Exception:  # noqa: BLE001
            log.exception("failing in-flight requests failed")

    def close(self) -> None:
        self._watchdog.close()
        if self.monitor is not None:
            self.monitor.close()


# -- progress watchdog -------------------------------------------------------

class Watchdog:
    """Fires on_stall when a monotonically-advancing counter stops moving.

    counter: zero-arg callable (e.g. `lambda: engine.stats.steps`).
    A stall is `active()` holding true for stall_after_s with no counter
    advance — including before the counter's FIRST advance, so a request
    that hangs before producing any token (wedged compile, dead tunnel)
    still fires. While `active()` is false the deadline keeps refreshing:
    an idle engine with an empty queue is never a stall, and a later
    request always gets the full window.
    """

    def __init__(self, counter: Callable[[], int], stall_after_s: float,
                 on_stall: Callable[[], None],
                 active: Optional[Callable[[], bool]] = None,
                 poll_interval_s: float = 0.5):
        self._counter = counter
        self._active = active or (lambda: True)
        self._stall_after = stall_after_s
        self._on_stall = on_stall
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(poll_interval_s,), daemon=True,
            name="cake-watchdog")
        self._thread.start()

    def _run(self, poll: float) -> None:
        last_value = self._counter()
        last_change = time.monotonic()
        fired = False
        while not self._stop.wait(poll):
            cur = self._counter()
            now = time.monotonic()
            if cur != last_value:
                last_value, last_change, fired = cur, now, False
                continue
            if not self._active():
                # an idle interval ends the stall episode: refresh the
                # deadline AND clear the fired latch so the next request
                # gets both the full window and a fresh detection (the
                # latch only suppresses re-firing within one episode)
                last_change = now
                fired = False
                continue
            if not fired and now - last_change > self._stall_after:
                fired = True
                log.warning("watchdog: no progress for %.1fs",
                            now - last_change)
                try:
                    self._on_stall()
                except Exception:  # noqa: BLE001
                    log.exception("on_stall callback failed")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
