"""Parameter / cache placement on the mesh.

The reference achieves location transparency through the `Forwarder` trait
(local Transformer vs remote TCP Client, cake/mod.rs:104-146). Here the same
job is done by `NamedSharding` annotations: the forward functions are
location-free, and placement alone decides which chips hold which weights
and where collectives appear.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.params import block_specs, cache_specs
from cake_tpu.ops.quant import expand_specs_for_quant


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def tree_shard(tree, mesh: Mesh, spec_tree):
    """device_put every leaf with its PartitionSpec.

    QTensor leaves (int8 q + reduced-rank scale) first get their spec
    expanded from the logical weight spec (ops/quant.expand_specs_for_quant),
    so `--quant int8` composes with every placement path."""
    spec_tree = expand_specs_for_quant(tree, spec_tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, spec_tree,
        is_leaf=lambda x: x is None,
    )


def shard_params(params, mesh: Mesh, *, tp_axis: str = "tp",
                 stage_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
    """Place a text-model param pytree: Megatron TP (+ optional stage on
    layers, + expert axis for MoE families). Specs derive from the actual
    block leaves, so dense and MoE pytrees both place correctly."""
    specs = {
        "embed": P(tp_axis, None),
        "blocks": block_specs(params["blocks"].keys(), stage_axis=stage_axis,
                              tp_axis=tp_axis, ep_axis=ep_axis),
        "final_norm": P(None),
        "lm_head": P(None, tp_axis),
    }
    return tree_shard(params, mesh, specs)


def shard_cache(cache: KVCache, mesh: Mesh, *, tp_axis: str = "tp",
                dp_axis: str = "dp",
                stage_axis: Optional[str] = None) -> KVCache:
    specs = cache_specs(tp_axis=tp_axis, dp_axis=dp_axis,
                        stage_axis=stage_axis)
    return KVCache(
        k=jax.device_put(cache.k, NamedSharding(mesh, specs.k)),
        v=jax.device_put(cache.v, NamedSharding(mesh, specs.v)),
    )


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))


def create_sharded_cache(config, batch_size: int, max_seq_len: int,
                         mesh: Mesh, *, tp_axis: Optional[str] = None,
                         dp_axis: Optional[str] = None,
                         stage_axis: Optional[str] = "stage",
                         dtype=None) -> KVCache:
    """Allocate a KV cache directly in its sharded layout.

    `KVCache.create` + `shard_cache` would first materialise the full zeros
    buffer on the default device — for 8B-class models that transient can
    exceed a chip whose budget was sized for the *sharded* slice. jit with
    out_shardings allocates each shard in place instead.
    """
    import jax.numpy as jnp
    dtype = dtype if dtype is not None else jnp.bfloat16
    specs = cache_specs(tp_axis=tp_axis, dp_axis=dp_axis,
                        stage_axis=stage_axis)
    shardings = KVCache(k=NamedSharding(mesh, specs.k),
                        v=NamedSharding(mesh, specs.v))
    make = jax.jit(
        lambda: KVCache.create(config, batch_size, max_seq_len, dtype=dtype),
        out_shardings=shardings,
    )
    return make()
