"""Parameter / cache placement on the mesh.

The reference achieves location transparency through the `Forwarder` trait
(local Transformer vs remote TCP Client, cake/mod.rs:104-146). Here the same
job is done by `NamedSharding` annotations: the forward functions are
location-free, and placement alone decides which chips hold which weights
and where collectives appear.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.params import block_specs, cache_specs


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def tree_shard(tree, mesh: Mesh, spec_tree):
    """device_put every leaf with its PartitionSpec."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, spec_tree,
        is_leaf=lambda x: x is None,
    )


def shard_params(params, mesh: Mesh, *, tp_axis: str = "tp",
                 stage_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None):
    """Place a text-model param pytree: Megatron TP (+ optional stage on
    layers, + expert axis for MoE families). Specs derive from the actual
    block leaves, so dense and MoE pytrees both place correctly."""
    specs = {
        "embed": P(tp_axis, None),
        "blocks": block_specs(params["blocks"].keys(), stage_axis=stage_axis,
                              tp_axis=tp_axis, ep_axis=ep_axis),
        "final_norm": P(None),
        "lm_head": P(None, tp_axis),
    }
    return tree_shard(params, mesh, specs)


def shard_cache(cache: KVCache, mesh: Mesh, *, tp_axis: str = "tp",
                dp_axis: str = "dp",
                stage_axis: Optional[str] = None) -> KVCache:
    specs = cache_specs(tp_axis=tp_axis, dp_axis=dp_axis,
                        stage_axis=stage_axis)
    return KVCache(
        k=jax.device_put(cache.k, NamedSharding(mesh, specs.k)),
        v=jax.device_put(cache.v, NamedSharding(mesh, specs.v)),
    )


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
