"""ParallelPlan: topology.yml + Args -> mesh shape and stage layout.

The reference's topology maps layer ranges to worker hosts; here the same
file maps contiguous block ranges onto pipeline stages of the mesh
(SURVEY.md §2.7 "TPU-native equivalent"). Stage count comes from the
topology (or explicit Args.tp/dp for pure TP/DP runs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax

from cake_tpu.args import Args
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.parallel.mesh import make_mesh
from cake_tpu.topology import Topology


@dataclass
class ParallelPlan:
    dp: int
    stages: int
    tp: int
    stage_layout: List[Tuple[str, List[int]]]  # (node name, block indices)

    @classmethod
    def from_topology(
        cls,
        config: LlamaConfig,
        topology: Optional[Topology],
        args: Optional[Args] = None,
        num_devices: Optional[int] = None,
    ) -> "ParallelPlan":
        L = config.num_hidden_layers
        dp = args.dp if args else 1
        tp = args.tp if args else 1

        if topology is None or len(topology) == 0:
            return cls(dp=dp, stages=1, tp=tp,
                       stage_layout=[("master", list(range(L)))])

        layout = topology.stage_assignments(L)
        sizes = {len(blocks) for _, blocks in layout}
        if len(sizes) != 1:
            raise ValueError(
                "SPMD pipeline requires equal-size stages; topology gives "
                f"ranges of sizes {sorted(len(b) for _, b in layout)}. "
                "Rebalance topology.yml block ranges."
            )
        stages = len(layout)
        n = num_devices if num_devices is not None else len(jax.devices())
        if dp * stages * tp > n:
            raise ValueError(
                f"plan dp={dp} stages={stages} tp={tp} needs "
                f"{dp * stages * tp} devices, have {n}"
            )
        return cls(dp=dp, stages=stages, tp=tp, stage_layout=layout)

    def build_mesh(self, devices=None, dcn_axis: str = "dp"):
        """Build the mesh; on multi-slice topologies the `dcn_axis` is laid
        out so only that axis crosses the inter-slice (DCN) boundary."""
        from cake_tpu.parallel.distributed import make_multihost_mesh
        return make_multihost_mesh(dp=self.dp, stage=self.stages,
                                   tp=self.tp, dcn_axis=dcn_axis,
                                   devices=devices)

    def describe(self) -> str:
        lines = [f"mesh: dp={self.dp} x stage={self.stages} x tp={self.tp}"]
        for name, blocks in self.stage_layout:
            lines.append(
                f"  stage[{name}]: blocks {blocks[0]}..{blocks[-1]}"
            )
        return "\n".join(lines)
