"""ParallelPlan: topology.yml + Args -> mesh shape and stage layout.

The reference's topology maps layer ranges to worker hosts; here the same
file maps contiguous block ranges onto pipeline stages of the mesh
(SURVEY.md §2.7 "TPU-native equivalent"). Stage count comes from the
topology (or explicit Args.tp/dp for pure TP/DP runs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax

from cake_tpu.args import Args
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.parallel.mesh import make_mesh
from cake_tpu.topology import Topology


@dataclass
class ParallelPlan:
    dp: int
    stages: int
    tp: int
    stage_layout: List[Tuple[str, List[int]]]  # (node name, block indices)

    @classmethod
    def from_topology(
        cls,
        config: LlamaConfig,
        topology: Optional[Topology],
        args: Optional[Args] = None,
        num_devices: Optional[int] = None,
    ) -> "ParallelPlan":
        L = config.num_hidden_layers
        dp = args.dp if args else 1
        tp = args.tp if args else 1

        if topology is None or len(topology) == 0:
            return cls(dp=dp, stages=1, tp=tp,
                       stage_layout=[("master", list(range(L)))])

        layout = topology.stage_assignments(L)
        sizes = {len(blocks) for _, blocks in layout}
        if len(sizes) != 1:
            raise ValueError(
                "SPMD pipeline requires equal-size stages; topology gives "
                f"ranges of sizes {sorted(len(b) for _, b in layout)}. "
                "Rebalance topology.yml block ranges."
            )
        stages = len(layout)
        n = num_devices if num_devices is not None else len(jax.devices())
        if dp * stages * tp > n:
            raise ValueError(
                f"plan dp={dp} stages={stages} tp={tp} needs "
                f"{dp * stages * tp} devices, have {n}"
            )
        return cls(dp=dp, stages=stages, tp=tp, stage_layout=layout)

    def build_mesh(self, devices=None, dcn_axis: Optional[str] = None):
        """Build the mesh; on multi-slice topologies the `dcn_axis` is laid
        out so only that axis crosses the inter-slice (DCN) boundary.

        dcn_axis=None auto-selects: the first of dp -> stage -> tp whose
        size the slice count divides. dp replicas are fully independent
        (best DCN tenant); stage crosses DCN once per pipeline hop — the
        reference's machine-per-layer-range shape (SURVEY §2.7); tp is
        the last resort (per-matmul collectives over DCN).
        """
        from cake_tpu.parallel.distributed import (
            _slice_ids, make_multihost_mesh,
        )
        if dcn_axis is None:
            import jax
            devs = list(devices) if devices is not None else jax.devices()
            n_slices = len(set(_slice_ids(devs)))
            sizes = {"dp": self.dp, "stage": self.stages, "tp": self.tp}
            dcn_axis = next((a for a in ("dp", "stage", "tp")
                             if sizes[a] % n_slices == 0), "dp")
        return make_multihost_mesh(dp=self.dp, stage=self.stages,
                                   tp=self.tp, dcn_axis=dcn_axis,
                                   devices=devices)

    def describe(self) -> str:
        lines = [f"mesh: dp={self.dp} x stage={self.stages} x tp={self.tp}"]
        for name, blocks in self.stage_layout:
            lines.append(
                f"  stage[{name}]: blocks {blocks[0]}..{blocks[-1]}"
            )
        return "\n".join(lines)


# chip HBM budgets (bytes) for fits-in-memory validation
HBM_BUDGET = {
    "v5e": 16 * 2**30,
    "v5p": 95 * 2**30,
    "v4": 32 * 2**30,
    "v6e": 32 * 2**30,
}


def placement_memory(config, *, dp: int = 1, stages: int = 1, tp: int = 1,
                     batch_size: int = 1, max_seq_len: int = 4096,
                     dtype=None,
                     quant: "bool | str" = False) -> dict:
    """Per-device HBM estimate for a pipeline placement — without
    materializing anything (shapes via jax.eval_shape).

    quant: False = full precision, True or "int8" = per-channel int8,
    "int4" = packed group-wise int4 (lm_head stays int8).

    Uses the exact PartitionSpecs place_for_pipeline applies, so the
    estimate can't drift from the real placement. This is the
    plan-validation path for configs too big for the chips at hand
    (BASELINE config #3: Llama-3-70B over a v5p pod) — the reference has
    no equivalent; it discovers misfits by OOM at load time.
    """
    import jax.numpy as jnp

    from cake_tpu.models.llama.params import (
        cache_specs, init_params, init_params_quantized,
    )
    from cake_tpu.ops.quant import expand_specs_for_quant
    from cake_tpu.parallel.pipeline import pipeline_param_specs

    dtype = dtype if dtype is not None else jnp.bfloat16
    if quant:
        from functools import partial
        bits = 4 if quant == "int4" else 8
        init = partial(init_params_quantized, bits=bits)
    else:
        init = init_params
    shapes = jax.eval_shape(
        lambda: init(config, jax.random.PRNGKey(0), dtype=dtype))

    axis_size = {"dp": dp, "stage": stages, "tp": tp, None: 1}
    tp_axis = "tp" if tp > 1 else None
    specs = pipeline_param_specs(shapes["blocks"].keys(), tp_axis)
    specs = expand_specs_for_quant(shapes, specs)

    def per_device(leaf, spec):
        n = 1
        for entry in spec:
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                n *= axis_size[ax]
        return leaf.size * leaf.dtype.itemsize / n

    leaves = jax.tree.leaves(
        jax.tree.map(per_device, shapes, specs, is_leaf=lambda x: x is None))
    params_bytes = sum(leaves)

    cspec = cache_specs(tp_axis=tp_axis or "tp",
                        dp_axis="dp" if dp > 1 else None,
                        stage_axis="stage").k
    L = config.num_hidden_layers
    KV, hd = config.num_key_value_heads, config.head_dim
    cache_elems = L * batch_size * max_seq_len * KV * hd
    div = 1
    for entry in cspec:
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            div *= axis_size.get(ax, 1)
    cache_bytes = 2 * cache_elems * 2 / div  # k+v, bf16

    total = params_bytes + cache_bytes
    return {
        "dp": dp, "stages": stages, "tp": tp,
        "devices": dp * stages * tp,
        "params_bytes_per_device": int(params_bytes),
        "cache_bytes_per_device": int(cache_bytes),
        "total_bytes_per_device": int(total),
        "total_gib_per_device": round(total / 2**30, 2),
    }
