"""Device mesh construction.

Axis convention (order matters for ICI locality):
  ("dp", "stage", "tp") — data parallel, pipeline stage, tensor parallel.
`tp` is innermost so tensor-parallel collectives ride nearest-neighbour ICI
links; `stage` transfers are point-to-point ppermutes; `dp` only reduces at
sampling (never in the decode hot path).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("dp", "stage", "tp")


def make_mesh(dp: int = 1, stage: int = 1, tp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ("dp","stage","tp") mesh over the given (or all) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    need = dp * stage * tp
    if need > len(devices):
        raise ValueError(
            f"mesh dp={dp} x stage={stage} x tp={tp} = {need} devices, "
            f"but only {len(devices)} available"
        )
    arr = np.array(devices[:need]).reshape(dp, stage, tp)
    return Mesh(arr, AXES)


def single_device_mesh(device=None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return Mesh(np.array([dev]).reshape(1, 1, 1), AXES)
