"""Multi-host / multi-slice distributed runtime.

The reference's distribution story is master/worker processes over TCP
(SURVEY.md §2.7): the master dials each worker listed in topology.yml and
request/responses hidden states per hop. The TPU-native story is one SPMD
program launched on every host of a pod (or several pod slices):

  * `initialize()` — `jax.distributed.initialize` wrapper. On TPU pods all
    coordinates are auto-detected; elsewhere they come from
    CAKE_COORDINATOR / CAKE_NUM_PROCESSES / CAKE_PROCESS_ID (the moral
    equivalent of the reference's --address/--name flags, lib.rs:21-88).
  * `make_multihost_mesh()` — a ("dp","stage","tp") mesh whose slowest
    varying axis crosses the DCN (inter-slice) boundary, so cross-slice
    traffic is confined to ONE axis: "dp" (gradient-free inference
    replicas; cross-slice collectives only at admission) or "stage"
    (pipeline hop per decode step crosses DCN once — how the reference's
    multi-machine layer split maps onto multi-slice TPU).
  * `is_coordinator()` / `coordinator_only()` — process-0 gating; the REST
    API binds on the coordinator, matching "the master serves the API"
    (api/mod.rs:23-48) without a separate master process.

Host→stage placement parity: the reference's topology.yml names workers by
host (topology.rs:14-21). Here `assign_hosts_to_stages` maps topology
nodes onto slice ids so a node's block range lands on the slice that
"is" that worker.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from cake_tpu.parallel.mesh import AXES

log = logging.getLogger(__name__)


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               env: Optional[Dict[str, str]] = None) -> bool:
    """Initialise JAX's distributed runtime for multi-host execution.

    Returns True if distributed init ran, False for single-process runs.
    Explicit args beat CAKE_* env vars beat auto-detection. Safe to call
    unconditionally: with no coordinator configured and a single process,
    it is a no-op.
    """
    env = dict(os.environ if env is None else env)
    coordinator = coordinator or env.get("CAKE_COORDINATOR") or None
    if num_processes is None and env.get("CAKE_NUM_PROCESSES"):
        num_processes = int(env["CAKE_NUM_PROCESSES"])
    if process_id is None and env.get("CAKE_PROCESS_ID"):
        process_id = int(env["CAKE_PROCESS_ID"])

    on_pod = bool(env.get("TPU_WORKER_HOSTNAMES") or env.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if coordinator is None and not on_pod:
        return False  # single host, nothing to do

    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    multi_worker = (
        bool(env.get("MEGASCALE_COORDINATOR_ADDRESS"))
        or len([h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
                if h.strip()]) > 1
    )
    if not kwargs and not multi_worker:
        # Single-worker pod-ish env (e.g. a TPU VM image or tunnel exports
        # TPU_WORKER_HOSTNAMES with one entry): there are no peers to
        # coordinate with, and attempting auto-init after the XLA backend
        # is live (library use, REPL, tests) raises RuntimeError.
        return False
    # Explicit config or a genuine multi-worker signal: let failures
    # propagate — silently downgrading one worker to single-process
    # would hang its peers in their first collective.
    jax.distributed.initialize(**kwargs)
    log.info("distributed: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())
    return True


def is_coordinator() -> bool:
    """True on the process that owns coordination (serves the REST API)."""
    return jax.process_index() == 0


def coordinator_only(fn):
    """Decorator: run fn only on the coordinator; others return None."""
    def wrapper(*a, **kw):
        if is_coordinator():
            return fn(*a, **kw)
        return None
    return wrapper


def _slice_ids(devices: Sequence) -> List[int]:
    """Slice index per device; falls back to process index (one slice per
    host) when the backend doesn't expose slice topology — either by
    returning None, or on the CPU backend, which reports slice_index 0
    everywhere even across processes (there, the process boundary IS the
    DCN/Gloo boundary). Real TPU pods keep their reported slice ids: a
    multi-host single-slice pod (e.g. v5p-16) is genuinely one
    ICI-connected slice and must not be split by process."""
    sids = [getattr(d, "slice_index", None) for d in devices]
    is_cpu = bool(devices) and getattr(devices[0], "platform", "") == "cpu"
    procs = {d.process_index for d in devices}
    if any(s is None for s in sids) or (is_cpu and len(set(sids)) == 1
                                        and len(procs) > 1):
        return [d.process_index for d in devices]
    return list(sids)


def make_multihost_mesh(dp: int = 1, stage: int = 1, tp: int = 1,
                        dcn_axis: str = "dp",
                        devices: Optional[Sequence] = None) -> Mesh:
    """("dp","stage","tp") mesh aware of slice (DCN) boundaries.

    The `dcn_axis` dimension is factored as (num_slices x per-slice) with
    the slice factor slowest-varying, so neighbouring coordinates along
    every other axis always live in the same slice and their collectives
    ride ICI. With one slice this degrades to `make_mesh` exactly.
    """
    if dcn_axis not in AXES:
        raise ValueError(f"dcn_axis must be one of {AXES}")
    devices = list(devices) if devices is not None else jax.devices()
    need = dp * stage * tp
    if need > len(devices):
        raise ValueError(
            f"mesh dp={dp} x stage={stage} x tp={tp} = {need} devices, "
            f"but only {len(devices)} available")

    sids = _slice_ids(devices)
    num_slices = len(set(sids))
    if num_slices == 1:
        arr = np.array(devices[:need]).reshape(dp, stage, tp)
        return Mesh(arr, AXES)

    sizes = {"dp": dp, "stage": stage, "tp": tp}
    if sizes[dcn_axis] % num_slices != 0:
        raise ValueError(
            f"dcn axis '{dcn_axis}'={sizes[dcn_axis]} must be divisible by "
            f"num_slices={num_slices}")
    per_slice_need = need // num_slices

    # group devices by slice, order groups by slice id
    by_slice: Dict[int, List] = {}
    for d, sid in zip(devices, sids):
        by_slice.setdefault(sid, []).append(d)
    groups = [by_slice[s] for s in sorted(by_slice)]
    if any(len(g) < per_slice_need for g in groups):
        raise ValueError(
            f"every slice needs {per_slice_need} devices for this mesh; "
            f"got {[len(g) for g in groups]}")

    # build [num_slices, per_slice_dcn, other axes...] then move the slice
    # factor into the dcn axis's slow position
    inner = {a: sizes[a] for a in AXES}
    inner[dcn_axis] = sizes[dcn_axis] // num_slices
    stacked = np.stack([
        np.array(g[:per_slice_need]).reshape(
            inner["dp"], inner["stage"], inner["tp"])
        for g in groups
    ])  # [S, dp_i, stage_i, tp_i]
    axis_pos = AXES.index(dcn_axis)
    # move S next to (before) the dcn axis and merge
    stacked = np.moveaxis(stacked, 0, axis_pos)
    arr = stacked.reshape(dp, stage, tp)
    return Mesh(arr, AXES)


def assign_hosts_to_stages(topology, num_slices: int) -> Dict[str, int]:
    """Map topology node names -> slice ids, preserving file order
    (reference: worker name -> host, topology.rs:14-21). With more nodes
    than slices, nodes wrap round-robin (several stages per slice)."""
    names = list(topology.keys())
    return {name: i % num_slices for i, name in enumerate(names)}


def cluster_info() -> dict:
    """Introspection snapshot (reference WorkerInfo, proto/message.rs:42-58,
    surfaced at /api/v1/cluster)."""
    devs = jax.devices()
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "device_count": len(devs),
        "local_device_count": jax.local_device_count(),
        "slices": sorted(set(_slice_ids(devs))),
        "platform": devs[0].platform if devs else None,
        "device_kind": devs[0].device_kind if devs else None,
    }
