"""Sequence parallelism composed with pipeline stages: ("stage","sp"[,"tp"]).

The round-4 gap this closes: long-context serving (`--sp`, ring attention)
and model-capacity sharding (`--topology` stages) were mutually exclusive,
yet the one deployment that needs both — a 70B-class model over a pod at
long context — is exactly their intersection (the reference's distribution
seam being replaced: cake-core/src/cake/topology.rs:50-76 feeding
llama.rs:203-220, which shards *layers* but caps context at 4096).

Design: the stacked block params are layer-sharded over the "stage" mesh
axis (same placement rule as parallel/pipeline.py); within every stage the
context sequence is sharded over "sp", so each stage's sp group runs ring
attention (prefill) / merged-stats decode (parallel/context_parallel.py)
over its own block range. Hidden states hop stage-to-stage with
`lax.ppermute` over ICI. The chain is depth-1 — one request in flight,
matching the reference's sequential layer-range walk — because this mode
exists for capacity + context, not batch throughput (the batching engine's
GPipe path covers that). With "tp" in the mesh, heads additionally shard
Megatron-style inside each (stage, sp) cell; ring hops then move KV chunks
of LOCAL heads only, so the per-hop ICI payload shrinks by 1/tp.

Under SPMD every stage executes every tick (masked where not live —
`jnp.where` keeps cache/output writes of the live stage only); on hardware
the off-tick compute overlaps with nothing and costs no wall-clock vs
stages idling, and XLA still fuses each stage's whole block range into one
computation (the contiguous-op-batching invariant, SURVEY §2.6).

The cache layout is context_parallel.SPCache with one more sharded axis:
ctx_*: [L, B, S_ctx, KV, hd] — L over "stage", S_ctx over "sp"
tail_*: [L, B, T_tail, KV, hd] — L over "stage", tail replicated over sp
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import RopeTables
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.quant import qmatmul
from cake_tpu.parallel.context_parallel import (
    SPCache, make_sp_decode_scan, sp_decode_layer, sp_decode_masks,
    sp_prefill_layer, sp_select_last,
)


def _stage_chain(h, run_my_blocks, init_state):
    """Depth-1 pipeline over the "stage" axis (runs under shard_map).

    Every tick all stages run `run_my_blocks(h) -> (y, state)` on their
    current buffer; only the live stage (sid == t) keeps its state writes
    and forwards its output over ICI. After nstages ticks the final
    stage's output has visited every block range; it is broadcast back
    with a psum so each device can run the (replicated) lm_head.

    Returns (h_final [replicated over stage], state).
    """
    nstages = lax.axis_size("stage")
    sid = lax.axis_index("stage")
    perm = [(i, (i + 1) % nstages) for i in range(nstages)]

    def tick(t, carry):
        h, out, state = carry
        y, new_state = run_my_blocks(h)
        live = sid == t
        state = jax.tree.map(
            lambda new, old: jnp.where(live, new.astype(old.dtype), old),
            new_state, state)
        # capture the final stage's result on its tick
        out = jnp.where(jnp.logical_and(live, sid == nstages - 1), y, out)
        h = lax.ppermute(jnp.where(live, y, h), "stage", perm)
        return h, out, state

    out0 = jnp.zeros_like(h)
    _, out, state = lax.fori_loop(0, nstages, tick, (h, out0, init_state))
    # broadcast the last stage's hidden state to every stage (tiny vs KV)
    out = lax.psum(jnp.where(sid == nstages - 1, out,
                             jnp.zeros_like(out)), "stage")
    return out, state


def make_sp_stage_prefill_body(config: LlamaConfig, kv_store, tp_axis,
                               Sl: int, nstages: int, tp_size: int):
    """THE stage-chained ring-prefill shard_map body — single source for
    make_sp_stage_forward (the generator adapter) and
    make_sp_stage_engine_step_fns (the batching engine), mirroring
    context_parallel.make_sp_prefill_body's role for the plain-sp
    factories."""
    def prefill_body(blocks, embed, final_norm, lm_head, tokens, plen,
                     cos, sin):
        isp = lax.axis_index("sp")
        B = tokens.shape[0]
        KV_local = config.num_key_value_heads // tp_size
        Ll = config.num_hidden_layers // nstages
        x = jnp.take(embed, tokens, axis=0)                 # [B, Sl, D]
        rope_c = lax.dynamic_slice_in_dim(cos, isp * Sl, Sl, axis=0)
        rope_s = lax.dynamic_slice_in_dim(sin, isp * Sl, Sl, axis=0)
        layer = sp_prefill_layer(config, rope_c, rope_s, kv_store,
                                 tp_axis)

        def run_my_blocks(h):
            return lax.scan(layer, h, blocks)

        store = kv_store or x.dtype
        ks0 = jnp.zeros((Ll, B, Sl, KV_local, config.head_dim), store)
        x, (ks, vs) = _stage_chain(x, run_my_blocks, (ks0, ks0))
        x = rms_norm(x, final_norm, config.rms_norm_eps)
        logits = sp_select_last(x, plen, isp, Sl, lm_head)
        return logits, ks, vs
    return prefill_body


def make_sp_stage_forward(mesh: Mesh, config: LlamaConfig, ctx_len: int,
                          tail_len: int, kv_dtype=None, tp: bool = False,
                          params=None):
    """Build (sp_prefill, sp_decode) jitted over a ("stage","sp"[,"tp"])
    mesh — the same call contract as context_parallel.make_sp_forward, so
    SPGeneratorForward drives either factory unchanged.

    sp_prefill(params, tokens [B, ctx_len], plen [B], rope)
        -> (logits [B, V] f32, SPCache)
    sp_decode(params, token [B, 1], pos, plen, cache, rope)
        -> (logits, SPCache)    # cache donated
    """
    nstages = mesh.shape["stage"]
    sp_size = mesh.shape["sp"]
    assert ctx_len % sp_size == 0, (ctx_len, sp_size)
    assert config.num_hidden_layers % nstages == 0, (
        config.num_hidden_layers, nstages)
    Sl = ctx_len // sp_size
    tp_axis = "tp" if tp else None
    kv_store = kv_dtype

    prefill_body = make_sp_stage_prefill_body(
        config, kv_store, tp_axis, Sl, nstages,
        mesh.shape["tp"] if tp else 1)

    def decode_body(blocks, embed, final_norm, lm_head, token, pos, plen,
                    ctx_k, ctx_v, tail_k, tail_v, cos, sin):
        isp = lax.axis_index("sp")
        B = token.shape[0]
        x = jnp.take(embed, token, axis=0)                  # [B, 1, D]
        rope_c = lax.dynamic_slice_in_dim(cos, pos, 1, axis=0)
        rope_s = lax.dynamic_slice_in_dim(sin, pos, 1, axis=0)
        t_slot = pos - ctx_len
        ctx_valid, tail_valid = sp_decode_masks(
            isp, Sl, plen, tail_k.shape[2], t_slot, B)
        layer = sp_decode_layer(config, rope_c, rope_s, t_slot,
                                ctx_valid, tail_valid, tp_axis)

        def run_my_blocks(h):
            return lax.scan(layer, h, (blocks, ctx_k, ctx_v,
                                       tail_k, tail_v))

        x, (tk_new, tv_new) = _stage_chain(
            x, run_my_blocks, (tail_k, tail_v))
        x = rms_norm(x, final_norm, config.rms_norm_eps)
        logits = qmatmul(x[:, -1], lm_head).astype(jnp.float32)
        return logits, tk_new, tv_new

    # specs: blocks layer-sharded over stage (+ heads over tp) — the SAME
    # rule as the GPipe pipeline, via its quant-aware helper
    from cake_tpu.parallel.pipeline import _blocks_in_specs
    blocks_spec = _blocks_in_specs(config, tp_axis, params)
    ctx_spec = P("stage", None, "sp", tp_axis, None)
    tail_spec = P("stage", None, None, tp_axis, None)
    rep = P()

    prefill_sm = jax.shard_map(
        prefill_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, P(None, "sp"), rep, rep, rep),
        out_specs=(rep, ctx_spec, ctx_spec),
        check_vma=False,
    )
    decode_sm = jax.shard_map(
        decode_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, rep, rep, rep,
                  ctx_spec, ctx_spec, tail_spec, tail_spec, rep, rep),
        out_specs=(rep, tail_spec, tail_spec),
        check_vma=False,
    )

    @jax.jit
    def sp_prefill(params, tokens, plen, rope: RopeTables):
        logits, ks, vs = prefill_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], tokens, plen, rope.cos, rope.sin)
        B = tokens.shape[0]
        KV, hd = config.num_key_value_heads, config.head_dim
        store = ks.dtype
        shape = (config.num_hidden_layers, B, tail_len, KV, hd)
        tspec = NamedSharding(mesh, tail_spec)
        # two allocations: aliasing would break tail donation (see
        # context_parallel.make_sp_forward)
        tail_k = lax.with_sharding_constraint(jnp.zeros(shape, store),
                                              tspec)
        tail_v = lax.with_sharding_constraint(jnp.zeros(shape, store),
                                              tspec)
        return logits, SPCache(ks, vs, tail_k, tail_v)

    @partial(jax.jit, donate_argnames=("cache",))
    def sp_decode(params, token, pos, plen, cache: SPCache,
                  rope: RopeTables):
        logits, tk, tv = decode_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], token, pos, plen,
            cache.ctx_k, cache.ctx_v, cache.tail_k, cache.tail_v,
            rope.cos, rope.sin)
        return logits, SPCache(cache.ctx_k, cache.ctx_v, tk, tv)

    sp_prefill.decode_scan = make_sp_decode_scan(decode_sm, ctx_len)
    return sp_prefill, sp_decode


def place_sp_stage_params(mesh: Mesh, config: LlamaConfig, params,
                          tp: bool = False):
    """device_put a param tree with the specs make_sp_stage_forward's
    shard_map expects: blocks layer-over-"stage" (+ tp heads),
    embed/lm_head/final_norm replicated — pipeline_param_specs IS that
    rule, reused so the two paths cannot drift."""
    from cake_tpu.parallel.pipeline import pipeline_param_specs
    from cake_tpu.parallel.sharding import tree_shard

    specs = pipeline_param_specs(params["blocks"].keys(),
                                 "tp" if tp else None)
    return tree_shard(params, mesh, specs)


# -- continuous-batching engine over the ("stage","sp"[,"tp"]) mesh -----------


def create_sp_stage_engine_cache(mesh: Mesh, config: LlamaConfig,
                                 slots: int, ctx_len: int, tail_len: int,
                                 kv_dtype=jnp.bfloat16,
                                 tp: bool = False):
    """SPEngineCache over the stage x sp mesh — the shared factory with
    the layer dim additionally sharded over "stage" (each stage holds
    only its block range's KV)."""
    from cake_tpu.parallel.context_parallel import create_sp_engine_cache
    return create_sp_engine_cache(mesh, config, slots, ctx_len,
                                  tail_len, kv_dtype=kv_dtype, tp=tp,
                                  stage=True)


def make_sp_stage_engine_step_fns(mesh: Mesh, config: LlamaConfig,
                                  ctx_len: int, tail_len: int,
                                  kv_dtype=None, tp: bool = False,
                                  params=None):
    """Engine step-fn contract over the ("stage","sp"[,"tp"]) mesh —
    the long-context 70B POD deployment (layer ranges over stages, ring
    attention within each stage's sp group), now serving CONCURRENT
    requests through the batching engine instead of the locked path.
    Same signatures/semantics as context_parallel
    .make_sp_engine_step_fns (position-contiguous per-row layout); the
    stage pipeline rides _stage_chain exactly as the generator
    adapter's forward does."""
    nstages = mesh.shape["stage"]
    sp_size = mesh.shape["sp"]
    assert ctx_len % sp_size == 0, (ctx_len, sp_size)
    assert config.num_hidden_layers % nstages == 0, (
        config.num_hidden_layers, nstages)
    Sl = ctx_len // sp_size
    tp_axis = "tp" if tp else None
    kv_store = kv_dtype

    from cake_tpu.parallel.pipeline import _blocks_in_specs
    blocks_spec = _blocks_in_specs(config, tp_axis, params)
    ctx_spec = P("stage", None, "sp", tp_axis, None)
    tail_spec = P("stage", None, None, tp_axis, None)
    rep = P()

    def chain(x, layer, blocks, ctx_k, ctx_v, tail_k, tail_v):
        def run_my_blocks(h):
            return lax.scan(layer, h, (blocks, ctx_k, ctx_v,
                                       tail_k, tail_v))
        return _stage_chain(x, run_my_blocks, (tail_k, tail_v))

    from cake_tpu.parallel.context_parallel import (
        make_sp_engine_decode_body,
    )
    decode_body = make_sp_engine_decode_body(config, tp_axis, Sl, chain)

    decode_sm = jax.shard_map(
        decode_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, rep, rep, rep,
                  ctx_spec, ctx_spec, tail_spec, tail_spec, rep, rep,
                  rep),
        out_specs=(rep, tail_spec, tail_spec),
        check_vma=False,
    )

    mode = "stage_sp_tp" if tp else "stage_sp"
    from cake_tpu.parallel.context_parallel import make_decode_ragged_fns
    decode_ragged_forward, decode_ragged_fn = make_decode_ragged_fns(
        decode_sm, mode=mode)

    prefill_body = make_sp_stage_prefill_body(
        config, kv_store, tp_axis, Sl, nstages,
        mesh.shape["tp"] if tp else 1)

    prefill_sm = jax.shard_map(
        prefill_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, P(None, "sp"), rep, rep,
                  rep),
        out_specs=(rep, ctx_spec, ctx_spec),
        check_vma=False,
    )

    from cake_tpu.parallel.context_parallel import (
        instrument_sp_engine, make_slot_prefill_fn,
    )
    prefill_slot_fn = make_slot_prefill_fn(prefill_sm, ctx_len,
                                           mode=mode)

    from cake_tpu.serve.engine import make_decode_scan
    # shared instrumentation tail: every step fn dispatch-counted and
    # wall-timed (cake_sp_dispatch_total/_seconds{op,mode}), identical
    # to the plain-sp factory so the two modes' metrics cannot drift
    return instrument_sp_engine(
        (prefill_slot_fn, decode_ragged_fn,
         make_decode_scan(decode_ragged_forward)),
        mode, ctx_len, tail_len)
