"""Distributed execution: device mesh, shardings, pipeline schedule.

This package replaces the reference's entire distributed runtime
(master/worker processes + TCP wire protocol, SURVEY.md §2.7) with SPMD
programs over a `jax.sharding.Mesh`:

  * `mesh.py`     — mesh construction from parallelism degrees / topology
  * `sharding.py` — NamedSharding placement of params/cache (TP, DP)
  * `pipeline.py` — microbatched pipeline parallelism via shard_map+ppermute
                    (the TPU-native equivalent of layer-range workers;
                    contiguous-block batching per hop holds by construction)
  * `plan.py`     — topology.yml -> mesh/stage plan
"""

from cake_tpu.parallel.mesh import make_mesh  # noqa: F401
from cake_tpu.parallel.plan import ParallelPlan  # noqa: F401
from cake_tpu.parallel.distributed import (  # noqa: F401
    cluster_info, initialize, is_coordinator, make_multihost_mesh,
)
