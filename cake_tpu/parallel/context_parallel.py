"""Sequence/context parallelism: ring attention over an "sp" mesh axis.

The reference has no long-context story at all — a hard MAX_SEQ_LEN = 4096
(llama3/config.rs:6) and the whole sequence resident on whichever device
owns a layer (SURVEY.md §5 "Long-context"). Here long context is first
class: the token sequence is sharded over the `sp` mesh axis, each device
computes attention for its query chunk while KV chunks rotate around the
ring over ICI (`lax.ppermute`), accumulated with online softmax — so the
context length a model can serve scales with the number of chips, and the
per-hop transfer (one KV chunk) overlaps with the chunk's attention
compute.

Decode after a context-parallel prefill keeps the prefilled KV sharded
where it was computed and gives every device a small replicated "tail"
cache for newly generated tokens: a decode step computes partial attention
(m, l, o) against the local context shard, merges the per-shard statistics
with a logsumexp reduction over `sp` (two psums), and adds the tail — no
resharding of the long context, ever.

All functions here are *per-device* bodies meant to run under
`jax.shard_map`; `make_sp_forward` wraps the whole Llama forward.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import RopeTables, block_skeleton
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.quant import qmatmul
from cake_tpu.ops.rope import apply_rope

NEG_INF = -1e30

# host-side dispatch counters/timers for the sp/stage-sp engine step
# fns: the forwards themselves are jitted (no per-call Python), so the
# instrumentation wraps the dispatch wrappers — one inc + one wall
# observation per device program launch, labeled by op and serving
# mode. Shared with sp_pipeline via the fn factories.
_SP_DISPATCH = obs_metrics.counter(
    "cake_sp_dispatch_total",
    "Device-program dispatches of the sp engine step fns",
    labelnames=("op", "mode"))
_SP_DISPATCH_SECONDS = obs_metrics.histogram(
    "cake_sp_dispatch_seconds",
    "Wall seconds per sp engine step-fn dispatch",
    labelnames=("op", "mode"))


def _counted(fn, op: str, mode: str):
    import functools
    import time as _time
    child = _SP_DISPATCH.labels(op=op, mode=mode)
    hist = _SP_DISPATCH_SECONDS.labels(op=op, mode=mode)

    # functools.wraps exposes __wrapped__, so obs/steps.lower_cost can
    # reach the jitted fn through this wrapper for MFU cost accounting
    @functools.wraps(fn)
    def wrapper(*args, **kw):
        child.inc()
        t0 = _time.perf_counter()
        try:
            return fn(*args, **kw)
        finally:
            hist.observe(_time.perf_counter() - t0)
    return wrapper


def instrument_sp_engine(step_fns, mode: str, ctx_len: int,
                         tail_len: int):
    """Shared observability tail of every sp-engine step-fn factory
    (plain sp here, stage x sp in sp_pipeline): wrap EVERY step fn's
    dispatch with the op counter + wall histogram and publish the
    window-layout gauges — one definition, so the two factories'
    metrics cannot drift. Takes and returns the engine step-fn tuple
    (prefill_slot, decode_ragged, decode_scan); None entries pass
    through untouched."""
    obs_metrics.gauge(
        "cake_sp_ctx_window_tokens",
        "Sequence-sharded prompt window of the sp engine",
        labelnames=("mode",)).labels(mode=mode).set(ctx_len)
    obs_metrics.gauge(
        "cake_sp_tail_window_tokens",
        "Replicated decode tail of the sp engine",
        labelnames=("mode",)).labels(mode=mode).set(tail_len)
    ops = ("prefill", "decode", "decode_scan")
    return tuple(
        _counted(fn, op, mode) if fn is not None else None
        for fn, op in zip(step_fns, ops))


def _chunk_scores(q, k, *, scale):
    """[B,Sq,KV,G,hd] x [B,Sk,KV,hd] -> f32 [B,KV,G,Sq,Sk]."""
    return jnp.einsum("bskgd,btkd->bkgst", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention(q, k, v, axis_name: str = "sp", *, causal: bool = True,
                   scale: float | None = None):
    """Ring attention for one device's query chunk (runs under shard_map).

    q:   [B, Sl, H, hd] local query chunk (global rows idx*Sl..)
    k,v: [B, Sl, KV, hd] local key/value chunk
    Rotates k/v around the `axis_name` ring sp times; each step computes the
    partial attention of the local queries against the visiting chunk and
    folds it into online-softmax state. Masking uses *global* positions, so
    the result equals full causal attention over the gathered sequence.
    """
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, hd = q.shape
    KV = k.shape[2]
    G = H // KV
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    qg = q.reshape(B, Sl, KV, G, hd)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    m0 = jnp.full((B, KV, G, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Sl, 1), jnp.float32)
    acc0 = jnp.zeros((B, KV, G, Sl, hd), jnp.float32)

    def fold(t, m, l, acc, k_cur, v_cur):
        src = (idx - t) % sp                 # chunk id currently held
        s = _chunk_scores(qg, k_cur, scale=scale)
        if causal:
            qi = idx * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
            kj = src * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
            mask = (kj <= qi)[None, None, None]
            s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        # exp(NEG_INF - NEG_INF) would be 1 for fully-masked rows; zero the
        # probabilities explicitly instead
        p = jnp.exp(s - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bkgst,btkd->bkgsd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def body(t, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = fold(t, m, l, acc, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    # sp-1 rotated hops, then fold the final visiting chunk without paying
    # for a rotation whose result would be discarded
    m, l, acc, k_last, v_last = lax.fori_loop(
        0, sp - 1, body, (m0, l0, acc0, k, v))
    m, l, acc = fold(sp - 1, m, l, acc, k_last, v_last)
    l = jnp.where(l == 0.0, 1.0, l)
    # [B, KV, G, Sl, hd] -> [B, Sl, KV, G, hd] -> [B, Sl, H, hd]
    out = jnp.transpose(acc / l, (0, 3, 1, 2, 4)).reshape(B, Sl, H, hd)
    return out.astype(q.dtype)


def partial_attention_stats(q, k, v, valid, *, scale: float | None = None):
    """Partial attention of q against a local KV shard, returning
    unnormalised online-softmax stats for cross-shard merging.

    q: [B, S, H, hd]; k, v: [B, T, KV, hd]; valid: bool [B, 1, 1, S, T]
    (or broadcastable) marking which local slots may be attended.
    Returns (m, l, o): [B,KV,G,S,1], [B,KV,G,S,1], [B,KV,G,S,hd] f32.
    """
    B, S, H, hd = q.shape
    KV = k.shape[2]
    G = H // KV
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    if k.dtype != q.dtype:
        # fp8 KV storage (--kv-dtype): upcast on read, fused into the dot
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    qg = q.reshape(B, S, KV, G, hd)
    s = _chunk_scores(qg, k, scale=scale)
    s = jnp.where(valid, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bkgst,btkd->bkgsd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return m, l, o


def merge_attention_stats(stats_list):
    """Merge per-shard (m, l, o) stats (already psum'd or local list)."""
    ms = jnp.stack([m for m, _, _ in stats_list])
    m_g = jnp.max(ms, axis=0)
    l_g = 0.0
    o_g = 0.0
    for m, l, o in stats_list:
        scale = jnp.exp(m - m_g)
        l_g = l_g + scale * l
        o_g = o_g + scale * o
    l_g = jnp.where(l_g == 0.0, 1.0, l_g)
    return o_g / l_g


def sp_merged_attention(q, ctx_k, ctx_v, tail_k, tail_v, ctx_valid,
                        tail_valid, axis_name: str = "sp"):
    """Decode attention over (sharded context) + (replicated tail).

    Runs under shard_map. Computes local partial stats against this
    device's context shard, reduces (m, l, o) across `sp` with a
    numerically-stable logsumexp merge (pmax + two psums), folds in the
    replicated tail stats, and normalises.

    q: [B, S, H, hd] (replicated); ctx_k/v: [B, Tl, KV, hd] local shard;
    tail_k/v: [B, Ttail, KV, hd] replicated.
    Returns [B, S, H, hd] in q.dtype (replicated).
    """
    B, S, H, hd = q.shape

    m_c, l_c, o_c = partial_attention_stats(q, ctx_k, ctx_v, ctx_valid)
    # stable cross-device merge of the context shards
    m_g = lax.pmax(m_c, axis_name)
    scale = jnp.exp(m_c - m_g)
    l_cg = lax.psum(scale * l_c, axis_name)
    o_cg = lax.psum(scale * o_c, axis_name)

    m_t, l_t, o_t = partial_attention_stats(q, tail_k, tail_v, tail_valid)
    out = merge_attention_stats([(m_g, l_cg, o_cg), (m_t, l_t, o_t)])
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(B, S, H, hd).astype(
        q.dtype)


# -- shared per-layer bodies --------------------------------------------------
# Single source for the sp layer step, decode masks, and K-step decode
# scan: make_sp_forward (("sp",)/("sp","tp") meshes) and
# sp_pipeline.make_sp_stage_forward (("stage","sp"[,"tp"])) both build
# from these, so a fix to one path cannot silently miss the other.


def sp_prefill_layer(config: LlamaConfig, rope_c, rope_s, kv_dtype,
                     tp_axis):
    """lax.scan layer fn for ring-attention prefill: h, lp -> h, (k, v).
    Runs under shard_map with an "sp" axis in scope."""
    def layer(h, lp):
        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            out = ring_attention(q, k, v, "sp", causal=True)
            # cast to the storage dtype HERE so the scan stacks the
            # cache directly at fp8 width — casting after the scan
            # would hold full-precision and fp8 copies concurrently,
            # raising peak HBM instead of halving it
            if kv_dtype is not None:
                k = k.astype(kv_dtype)
                v = v.astype(kv_dtype)
            return out, (k, v)
        return block_skeleton(lp, h, config, attn_fn, tp_axis=tp_axis)
    return layer


def sp_decode_layer(config: LlamaConfig, rope_c, rope_s, t_slot,
                    ctx_valid, tail_valid, tp_axis, tail_update=None):
    """lax.scan layer fn for merged-stats decode:
    h, (lp, ck, cv, tk, tv) -> h, (tk', tv').

    tail_update(tk, tv, k, v) -> (tk', tv') writes the step's KV into
    the tail cache; the default is the lockstep batch write at scalar
    slot `t_slot` (the --sp generator adapter). The continuous-batching
    engine passes a per-row active-masked writer instead — everything
    else (rope, merged-stats attention, block skeleton) is THIS single
    implementation for both."""
    if tail_update is None:
        def tail_update(tk, tv, k, v):
            tk2 = lax.dynamic_update_slice_in_dim(
                tk, k.astype(tk.dtype), t_slot, axis=1)
            tv2 = lax.dynamic_update_slice_in_dim(
                tv, v.astype(tv.dtype), t_slot, axis=1)
            return tk2, tv2

    def layer(h, xs):
        lp, ck, cv, tk, tv = xs

        def attn_fn(q, k, v):
            q = apply_rope(q, rope_c, rope_s)
            k = apply_rope(k, rope_c, rope_s)
            tk2, tv2 = tail_update(tk, tv, k, v)
            out = sp_merged_attention(q, ck, cv, tk2, tv2,
                                      ctx_valid, tail_valid, "sp")
            return out, (tk2, tv2)

        return block_skeleton(lp, h, config, attn_fn, tp_axis=tp_axis)
    return layer


def sp_decode_masks(idx, Sl: int, plen, tail_T: int, t_slot, B: int):
    """(ctx_valid, tail_valid) for one decode step: context slots below
    each row's prompt length (global slot ids from this device's sp
    index), tail slots up to and including the one being written.
    t_slot: scalar (lockstep batch — the --sp generator adapter) or [B]
    per-row (the continuous-batching sp engine's ragged decode)."""
    slot_g = idx * Sl + jnp.arange(Sl)
    ctx_valid = (slot_g[None] < plen[:, None])[:, None, None, None, :]
    t = jnp.asarray(t_slot)
    if t.ndim == 0:
        t = t[None]
    tail_valid = jnp.arange(tail_T)[None] <= t[:, None]
    tail_valid = jnp.broadcast_to(
        tail_valid, (B, tail_T))[:, None, None, None, :]
    return ctx_valid, tail_valid


def make_sp_prefill_body(config: LlamaConfig, kv_dtype, tp_axis,
                         Sl: int):
    """THE ring-prefill shard_map body — single source for
    make_sp_forward (the --sp generator adapter, [B, Sl] rows) and
    make_sp_engine_step_fns (the continuous-batching engine, [1, Sl]
    per-slot prefill), so a layer/mask fix to one cannot miss the
    other."""
    def prefill_body(blocks, embed, final_norm, lm_head, tokens, plen,
                     cos, sin):
        idx = lax.axis_index("sp")
        x = jnp.take(embed, tokens, axis=0)             # [B, Sl, D]
        rope_c = lax.dynamic_slice_in_dim(cos, idx * Sl, Sl, axis=0)
        rope_s = lax.dynamic_slice_in_dim(sin, idx * Sl, Sl, axis=0)
        layer = sp_prefill_layer(config, rope_c, rope_s, kv_dtype,
                                 tp_axis)
        x, (ks, vs) = lax.scan(layer, x, blocks)
        x = rms_norm(x, final_norm, config.rms_norm_eps)
        logits = sp_select_last(x, plen, idx, Sl, lm_head)
        return logits, ks, vs
    return prefill_body


def sp_select_last(x, plen, idx, Sl: int, lm_head):
    """Select the hidden state at plen-1 (it lives on ONE sp shard),
    psum it to every shard, and project: [B, Sl, D] -> logits [B, V]."""
    B = x.shape[0]
    last = (plen - 1).astype(jnp.int32)
    local = jnp.clip(last - idx * Sl, 0, Sl - 1)
    val = jnp.take_along_axis(x, local.reshape(B, 1, 1), axis=1)[:, 0]
    mine = (last >= idx * Sl) & (last < (idx + 1) * Sl)
    val = lax.psum(jnp.where(mine[:, None], val, 0.0), "sp")
    return qmatmul(val, lm_head).astype(jnp.float32)


def make_sp_decode_scan(decode_sm, ctx_len: int):
    """K decode+sample steps as ONE compiled program — the long-context
    analog of the engine's decode scan: host/tunnel dispatch amortizes
    across num_steps tokens instead of paying a round-trip per token
    (the dominant cost of sp serving at small batch). Sampling (incl.
    the repeat-penalty ring) runs inside the scan with the same ops the
    host loop uses. Shared by the plain-sp and stage x sp factories."""
    @partial(jax.jit, static_argnames=("num_steps", "sampling"),
             donate_argnames=("cache",))
    def sp_decode_scan(params, token, pos0, plen, cache: SPCache,
                       rope: RopeTables, rng, ring, num_steps: int,
                       sampling):
        from cake_tpu.ops.sampling import sample_tokens, update_ring

        def body(carry, step):
            tok, pos, tk, tv, ring, rng = carry
            logits, tk, tv = decode_sm(
                params["blocks"], params["embed"], params["final_norm"],
                params["lm_head"], tok, pos, plen,
                cache.ctx_k, cache.ctx_v, tk, tv, rope.cos, rope.sin)
            rng, sub = jax.random.split(rng)
            nxt = sample_tokens(sub, logits, ring, sampling)
            ring = update_ring(ring, nxt, step)
            return (nxt[:, None], pos + 1, tk, tv, ring, rng), nxt

        # ring steps continue from the input token's step index (the
        # pos0 operand encodes it: k0 = pos0 - ctx_len), so a mid-session
        # continuation writes the same penalty-ring slots the host loop
        # would
        k0 = pos0 - ctx_len
        (tok, pos, tk, tv, ring, rng), toks = lax.scan(
            body,
            (token, pos0, cache.tail_k, cache.tail_v, ring, rng),
            k0 + jnp.arange(1, num_steps + 1))
        return (jnp.transpose(toks, (1, 0)),
                SPCache(cache.ctx_k, cache.ctx_v, tk, tv), ring, rng)

    return sp_decode_scan


# -- whole-model sequence-parallel forward -----------------------------------


class SPCache(NamedTuple):
    """Long-context KV cache: prefilled context sharded over sp, decode tail
    replicated. ctx_*: [L, B, S_ctx, KV, hd] (seq axis sharded over "sp");
    tail_*: [L, B, T_tail, KV, hd] (replicated)."""
    ctx_k: jnp.ndarray
    ctx_v: jnp.ndarray
    tail_k: jnp.ndarray
    tail_v: jnp.ndarray

    def fresh(self) -> "SPCache":
        """Zeroed cache with identical spec/sharding (the generator's
        session-reset contract, models/llama/cache.KVCache.fresh)."""
        return SPCache(*(jnp.zeros_like(x) for x in self))



def sp_block_specs(config: LlamaConfig, tp: bool, params=None):
    """THE block-param specs for the sp mesh — single source for both
    make_sp_forward's shard_map in_specs and place_sp_params' placement,
    so the two cannot drift. With tp and quantized params, QTensor
    leaves expand to (q, scale) spec pairs; tp + quant REQUIRES the
    params example tree (without it the specs stay unexpanded and
    shard_map fails with a structural mismatch)."""
    from cake_tpu.models.llama.params import block_param_keys, block_specs
    if not tp:
        return {kk: P() for kk in block_param_keys(config)}
    specs = block_specs(block_param_keys(config), stage_axis=None,
                        tp_axis="tp")
    if params is not None:
        from cake_tpu.ops.quant import expand_specs_for_quant
        specs = {k: specs[k] for k in params["blocks"]}
        specs = expand_specs_for_quant(params["blocks"], specs)
    return specs


def make_sp_forward(mesh: Mesh, config: LlamaConfig, ctx_len: int,
                    tail_len: int, kv_dtype=None, tp: bool = False,
                    params=None, dp: bool = False):
    """Build (sp_prefill, sp_decode) jitted over the mesh's "sp" axis.

    tp: the mesh also carries a "tp" axis — attention/ffn heads shard
    Megatron-style within each sequence shard (block_skeleton's tp
    psums), so ring attention rotates KV chunks of LOCAL heads: sp x tp
    composes sequence and tensor parallelism on one mesh. dp: the mesh
    also carries a "dp" axis — the BATCH shards over it and each dp
    group runs its own sp ring (no cross-group collectives: the ring
    ppermutes and the last-token psum name only "sp", so shard_map
    scopes them per group). Long-context batched serving: dp x sp(x tp)
    on one mesh. (stage x sp lives in parallel/sp_pipeline; stage x dp
    remains excluded.)

    kv_dtype: storage dtype for the SPCache (fp8 halves the sharded
    long-context cache — the dominant allocation of this mode); values
    upcast into attention on read. None = compute dtype.

    sp_prefill(params, tokens [B, ctx_len], plen [B], rope)
        -> (logits [B, V] f32, SPCache)   # tokens right-padded to ctx_len;
                                          # allocates the cache itself
    sp_decode(params, token [B, 1], pos scalar, plen [B], cache, rope)
        -> (logits, SPCache)              # pos in [ctx_len, ctx_len+tail);
                                          # cache is donated
    """
    sp_size = mesh.shape["sp"]
    assert ctx_len % sp_size == 0, (ctx_len, sp_size)
    Sl = ctx_len // sp_size
    tp_axis = "tp" if tp else None

    prefill_body = make_sp_prefill_body(config, kv_dtype, tp_axis, Sl)

    def decode_body(blocks, embed, final_norm, lm_head, token, pos, plen,
                    ctx_k, ctx_v, tail_k, tail_v, cos, sin):
        idx = lax.axis_index("sp")
        B = token.shape[0]
        x = jnp.take(embed, token, axis=0)                  # [B, 1, D]
        rope_c = lax.dynamic_slice_in_dim(cos, pos, 1, axis=0)
        rope_s = lax.dynamic_slice_in_dim(sin, pos, 1, axis=0)
        t_slot = pos - ctx_len                               # tail write slot
        ctx_valid, tail_valid = sp_decode_masks(
            idx, Sl, plen, tail_k.shape[2], t_slot, B)
        layer = sp_decode_layer(config, rope_c, rope_s, t_slot,
                                ctx_valid, tail_valid, tp_axis)
        x, (tk_new, tv_new) = lax.scan(
            layer, x, (blocks, ctx_k, ctx_v, tail_k, tail_v))
        x = rms_norm(x, final_norm, config.rms_norm_eps)
        logits = qmatmul(x[:, -1], lm_head).astype(jnp.float32)
        return logits, tk_new, tv_new

    dp_axis = "dp" if dp else None
    ctx_spec = P(None, dp_axis, "sp", tp_axis, None)
    tail_spec = (P(None, dp_axis, None, tp_axis, None) if (tp or dp)
                 else P())
    batch = P(dp_axis)                       # plen / logits rows
    rep = P()
    blocks_spec = sp_block_specs(config, tp, params)

    prefill_sm = jax.shard_map(
        prefill_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, P(dp_axis, "sp"), batch,
                  rep, rep),
        out_specs=(batch, ctx_spec, ctx_spec),
        check_vma=False,
    )
    decode_sm = jax.shard_map(
        decode_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, P(dp_axis, None), rep,
                  batch, ctx_spec, ctx_spec, tail_spec, tail_spec, rep,
                  rep),
        out_specs=(batch, tail_spec, tail_spec),
        check_vma=False,
    )

    @jax.jit
    def sp_prefill(params, tokens, plen, rope: RopeTables):
        logits, ks, vs = prefill_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], tokens, plen, rope.cos, rope.sin)
        B = tokens.shape[0]
        KV, hd = config.num_key_value_heads, config.head_dim
        store = ks.dtype  # prefill_body already stacks at the storage dtype
        # two separate allocations: aliased tail_k/tail_v would make the
        # first donated sp_decode try to donate one buffer twice (JAX
        # falls back to a copy, defeating the donation)
        shape = (config.num_hidden_layers, B, tail_len, KV, hd)
        tspec = NamedSharding(mesh, tail_spec)
        tail_k = lax.with_sharding_constraint(jnp.zeros(shape, store),
                                              tspec)
        tail_v = lax.with_sharding_constraint(jnp.zeros(shape, store),
                                              tspec)
        return logits, SPCache(ks, vs, tail_k, tail_v)

    @partial(jax.jit, donate_argnames=("cache",))
    def sp_decode(params, token, pos, plen, cache: SPCache,
                  rope: RopeTables):
        logits, tk, tv = decode_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], token, pos, plen,
            cache.ctx_k, cache.ctx_v, cache.tail_k, cache.tail_v,
            rope.cos, rope.sin)
        return logits, SPCache(cache.ctx_k, cache.ctx_v, tk, tv)

    sp_prefill.decode_scan = make_sp_decode_scan(decode_sm, ctx_len)
    return sp_prefill, sp_decode


def place_sp_params(mesh: Mesh, config: LlamaConfig, params,
                    tp: bool = False):
    """device_put the block params with the specs make_sp_forward's
    shard_map expects (tp head sharding when tp; replicated otherwise) —
    the single placement rule for every sp caller, so call sites cannot
    drift from the in_specs."""
    if not tp:
        return params
    from cake_tpu.ops.quant import QTensor
    bspecs = sp_block_specs(config, tp, params)

    def put(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    out = dict(params)
    out["blocks"] = {
        k: (QTensor(q=put(v.q, bspecs[k].q), scale=put(v.scale,
                                                       bspecs[k].scale))
            if isinstance(v, QTensor) else put(v, bspecs[k]))
        for k, v in params["blocks"].items()}
    return out


class SPSessionCache(NamedTuple):
    """SPCache + the session's prompt lengths: carrying plen IN the cache
    keeps the adapter stateless, so a scratch-cache generation
    (generate_on_device) cannot clobber a live interactive session's
    decode positions."""
    sp: SPCache
    plen: jnp.ndarray

    def fresh(self) -> "SPSessionCache":
        return SPSessionCache(self.sp.fresh(), jnp.zeros_like(self.plen))


class SPGeneratorForward:
    """forward_fn adapter: (sp_prefill, sp_decode) under the generator's
    pluggable-forward contract, making `--sp N` a serving mode instead of
    a library-only capability (cli --sp N --max-seq-len ...).

    Window layout: the prompt is right-padded into the sp-sharded context
    window [0, ctx_len); generated tokens live in the replicated tail at
    window positions ctx_len+k. With a full prompt (len == ctx_len — the
    long-context case this mode exists for) positions coincide with the
    dense path exactly; shorter prompts carry a positional gap between
    prompt and generation (documented SP-mode semantics, masked
    correctly either way).
    """

    def __init__(self, mesh: Mesh, config: LlamaConfig, ctx_len: int,
                 tail_len: int, kv_dtype=None, tp: bool = False,
                 params=None, stages: int = 1, dp: bool = False):
        if ctx_len % mesh.shape["sp"] != 0:
            raise ValueError(
                f"sp context window {ctx_len} must divide over sp="
                f"{mesh.shape['sp']}")
        if dp and stages > 1:
            raise ValueError("sp x dp does not compose with stages")
        self.ctx_len = ctx_len
        self.tail_len = tail_len
        # bounds the generator enforces: inclusive prompt length at encode
        # time, and the number of decode steps the replicated tail holds
        # (past it, dynamic_update_slice would clamp over live entries)
        self.max_prompt_len = ctx_len
        self.max_decode_tokens = tail_len
        # the prefill allocates its own SPCache and ignores the passed-in
        # cache (generator skips its fresh() copy accordingly)
        self.allocates_cache = True
        # kept for engine_pieces (master.make_engine builds the sp
        # continuous-batching engine from the same mesh/window layout)
        self._mesh = mesh
        self._config = config
        self._kv_dtype = kv_dtype
        self._tp = tp
        self._stages = stages
        self._dp = dp
        if stages > 1:
            # sp x pipeline-stage composition: layers sharded over "stage",
            # sequence over "sp" (parallel/sp_pipeline) — same call
            # contract, so everything below is factory-agnostic
            from cake_tpu.parallel.sp_pipeline import make_sp_stage_forward
            self._prefill, self._decode = make_sp_stage_forward(
                mesh, config, ctx_len, tail_len, kv_dtype=kv_dtype,
                tp=tp, params=params)
        else:
            self._prefill, self._decode = make_sp_forward(
                mesh, config, ctx_len, tail_len, kv_dtype=kv_dtype,
                tp=tp, params=params, dp=dp)

    def __call__(self, params, tokens, cache, pos, rope,
                 last_idx=None, is_prefill: bool = False):
        if is_prefill:
            B, S = tokens.shape
            if S >= self.ctx_len:
                # bucket padding may exceed the window; real tokens cannot
                # (max_prompt_len) — trim pad, keep the window
                toks = tokens[:, : self.ctx_len]
            else:
                toks = jnp.pad(tokens, ((0, 0), (0, self.ctx_len - S)))
            plen = ((last_idx + 1).astype(jnp.int32)
                    if last_idx is not None
                    else jnp.full((B,), S, jnp.int32))
            logits, spc = self._prefill(params, toks, plen, rope)
            return logits, SPSessionCache(spc, plen)
        # generator positions count from the prompt end; SP decode slots
        # count from the context window end
        k = pos - jnp.max(cache.plen)
        logits, spc = self._decode(params, tokens,
                                   jnp.int32(self.ctx_len) + k, cache.plen,
                                   cache.sp, rope)
        return logits, SPSessionCache(spc, cache.plen)

    def engine_pieces(self, slots: int, params):
        """(step_fns, cache, ctx_len, tail_len) for the continuous-
        batching engine over this adapter's mesh. stage x sp routes to
        sp_pipeline's stage-chained factory (the long-context 70B pod
        config, served batched); dp x sp shards the slot axis over dp
        (requires max_slots divisible by dp)."""
        dtype = (self._kv_dtype if self._kv_dtype is not None
                 else params["embed"].dtype)
        if self._dp and slots % self._mesh.shape["dp"] != 0:
            raise ValueError(
                f"--max-slots {slots} must be divisible by --dp "
                f"{self._mesh.shape['dp']} (the sp engine shards "
                f"slots over dp)")
        if self._stages > 1:
            from cake_tpu.parallel.sp_pipeline import (
                create_sp_stage_engine_cache,
                make_sp_stage_engine_step_fns,
            )
            fns = make_sp_stage_engine_step_fns(
                self._mesh, self._config, self.ctx_len, self.tail_len,
                kv_dtype=self._kv_dtype, tp=self._tp, params=params)
            cache = create_sp_stage_engine_cache(
                self._mesh, self._config, slots, self.ctx_len,
                self.tail_len, kv_dtype=dtype, tp=self._tp)
            return fns, cache, self.ctx_len, self.tail_len
        fns = make_sp_engine_step_fns(
            self._mesh, self._config, self.ctx_len, self.tail_len,
            kv_dtype=self._kv_dtype, tp=self._tp, params=params,
            dp=bool(self._dp))
        cache = create_sp_engine_cache(
            self._mesh, self._config, slots, self.ctx_len,
            self.tail_len, kv_dtype=dtype, tp=self._tp,
            dp=bool(self._dp))
        return fns, cache, self.ctx_len, self.tail_len

    def decode_scan(self, params, token, k0: int, cache, rope, rng, ring,
                    num_steps: int, sampling):
        """num_steps on-device decode+sample steps (see sp_decode_scan).
        k0: decode step index of `token` (0 = the prefill's first sampled
        token). Returns (tokens [B, num_steps], cache, ring, rng)."""
        toks, spc, ring, rng = self._prefill.decode_scan(
            params, token, jnp.int32(self.ctx_len + k0), cache.plen,
            cache.sp, rope, rng, ring, num_steps=num_steps,
            sampling=sampling)
        return toks, SPSessionCache(spc, cache.plen), ring, rng


# -- continuous-batching engine over the sp mesh ------------------------------


class SPEngineCache(NamedTuple):
    """SPCache plus the per-slot prompt lengths, so the engine's generic
    step-fn contract (which passes only pos/active) still reaches the
    per-row window layout: ctx region [0, plen[b]) holds slot b's ring-
    prefilled prompt, tail slot t holds its (plen[b]+t)-positioned
    generated token. plen rides the cache pytree through donated decode
    dispatches and chained scans unchanged."""
    ctx_k: jnp.ndarray          # [L, B, S_ctx, KV, hd] seq-sharded "sp"
    ctx_v: jnp.ndarray
    tail_k: jnp.ndarray         # [L, B, T_tail, KV, hd] replicated
    tail_v: jnp.ndarray
    plen: jnp.ndarray           # [B] int32

    def fresh(self) -> "SPEngineCache":
        return SPEngineCache(*(jnp.zeros_like(x) for x in self))


def create_sp_engine_cache(mesh: Mesh, config: LlamaConfig, slots: int,
                           ctx_len: int, tail_len: int,
                           kv_dtype=jnp.bfloat16,
                           tp: bool = False,
                           stage: bool = False,
                           dp: bool = False) -> SPEngineCache:
    """Allocate the engine's multi-slot sp cache with the shardings
    make_sp_engine_step_fns' shard_maps expect (stage=True: the layer
    dim additionally shards over "stage" for the stage x sp engine;
    dp=True: the SLOT dim shards over "dp" — requires slots % dp == 0).
    jit-with-out_shardings (not device_put): each shard allocates in
    place — no full-buffer transient, and it works over a multi-process
    mesh, where device_put to non-addressable devices is invalid
    (create_sharded_cache precedent)."""
    KV, hd = config.num_key_value_heads, config.head_dim
    L = config.num_hidden_layers
    tp_axis = "tp" if tp else None
    stage_axis = "stage" if stage else None
    dp_axis = "dp" if dp else None
    if dp:
        assert slots % mesh.shape["dp"] == 0, (slots, mesh.shape["dp"])
    tail = (P(stage_axis, dp_axis, None, tp_axis, None)
            if (tp or stage or dp) else P())
    shardings = SPEngineCache(
        ctx_k=NamedSharding(mesh, P(stage_axis, dp_axis, "sp", tp_axis,
                                    None)),
        ctx_v=NamedSharding(mesh, P(stage_axis, dp_axis, "sp", tp_axis,
                                    None)),
        tail_k=NamedSharding(mesh, tail),
        tail_v=NamedSharding(mesh, tail),
        plen=NamedSharding(mesh, P(dp_axis)),
    )
    make = jax.jit(
        lambda: SPEngineCache(
            ctx_k=jnp.zeros((L, slots, ctx_len, KV, hd), kv_dtype),
            ctx_v=jnp.zeros((L, slots, ctx_len, KV, hd), kv_dtype),
            tail_k=jnp.zeros((L, slots, tail_len, KV, hd), kv_dtype),
            tail_v=jnp.zeros((L, slots, tail_len, KV, hd), kv_dtype),
            plen=jnp.zeros((slots,), jnp.int32),
        ),
        out_shardings=shardings,
    )
    return make()


def make_sp_engine_step_fns(mesh: Mesh, config: LlamaConfig,
                            ctx_len: int, tail_len: int,
                            kv_dtype=None, tp: bool = False,
                            params=None, dp: bool = False):
    """Engine step-fn contract over the sp(x tp) mesh: long-context
    CONTINUOUS-BATCHING serving — every slot's prompt ring-prefills over
    the sequence shards and concurrent requests decode together with
    merged-stats attention, instead of the single-tenant locked path the
    --sp adapter served through before.

    Returns (prefill_slot_fn, decode_ragged_fn, decode_scan_fn): the
    same signatures as model.prefill_slot / decode_step_ragged /
    engine.make_decode_scan's product, over an SPEngineCache.

    Unlike the batch-1 SPGeneratorForward (whose tail positions start at
    ctx_len, leaving a documented rope gap for short prompts), the
    engine layout is position-contiguous: row b's generated token t sits
    at rope position plen[b]+t and tail slot t, so outputs match the
    dense engine exactly for any prompt length. Composition: sp alone,
    sp x tp, dp x sp(x tp) — dp shards the SLOT axis, each dp group
    running its own sp ring (the body's collectives name only "sp"/
    "tp", so shard_map scopes them per group; decode throughput scales
    with dp at long context) — or, via sp_pipeline
    .make_sp_stage_engine_step_fns sharing this layout, stage x sp."""
    sp_size = mesh.shape["sp"]
    assert ctx_len % sp_size == 0, (ctx_len, sp_size)
    Sl = ctx_len // sp_size
    tp_axis = "tp" if tp else None
    mode = "_".join((["dp"] if dp else []) + ["sp"]
                    + (["tp"] if tp else []))
    blocks_spec = sp_block_specs(config, tp, params)
    rep = P()

    # -- ragged decode over [B] per-row positions -------------------------
    def chain(x, layer, blocks, ctx_k, ctx_v, tail_k, tail_v):
        return lax.scan(layer, x, (blocks, ctx_k, ctx_v, tail_k,
                                   tail_v))

    decode_body = make_sp_engine_decode_body(config, tp_axis, Sl, chain)

    dp_axis = "dp" if dp else None
    batch = P(dp_axis)                  # slot-axis sharding over dp
    ctx_spec = P(None, dp_axis, "sp", tp_axis, None)
    tail_spec = (P(None, dp_axis, None, tp_axis, None)
                 if (tp or dp) else P())
    decode_sm = jax.shard_map(
        decode_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, batch, batch, batch,
                  ctx_spec, ctx_spec, tail_spec, tail_spec, batch, rep,
                  rep),
        out_specs=(batch, tail_spec, tail_spec),
        check_vma=False,
    )

    decode_ragged_forward, decode_ragged_fn = make_decode_ragged_fns(
        decode_sm, mode=mode)

    # -- slot prefill: ring-prefill one prompt, scatter into the slot -----
    prefill_body = make_sp_prefill_body(config, kv_dtype, tp_axis, Sl)

    # prefill output is a SINGLE slot ([L, 1, Sl, ...]) — its specs
    # never carry the dp axis (a size-1 dim cannot shard over dp); the
    # scatter into the dp-sharded cache happens in the jitted slot
    # wrapper, where XLA reshards the one-slot update onto its owner
    pf_ctx_spec = P(None, None, "sp", tp_axis, None)
    prefill_sm = jax.shard_map(
        prefill_body, mesh=mesh,
        in_specs=(blocks_spec, rep, rep, rep, P(None, "sp"), rep,
                  rep, rep),
        out_specs=(rep, pf_ctx_spec, pf_ctx_spec),
        check_vma=False,
    )
    prefill_slot_fn = make_slot_prefill_fn(prefill_sm, ctx_len,
                                           mode=mode)

    from cake_tpu.serve.engine import make_decode_scan
    return instrument_sp_engine(
        (prefill_slot_fn, decode_ragged_fn,
         make_decode_scan(decode_ragged_forward)),
        mode, ctx_len, tail_len)


def make_slot_prefill_fn(prefill_sm, ctx_len: int, mode: str = "sp"):
    """The engine's slot-prefill wrapper, shared by the plain-sp and
    stage x sp factories (only their prefill shard_maps differ):
    [1, bucket] prompt -> trim/pad to [1, ctx_len] -> ring prefill ->
    scatter the slot's ctx shards + plen. Bucket padding beyond ctx_len
    is trimmed (real tokens are capped at ctx_len by the engine's
    prompt_limit); shorter buckets zero-pad up to the window."""

    @partial(jax.jit, static_argnames=("config_",),
             donate_argnames=("cache",))
    def prefill_slot_fn(params, tokens, prompt_len, slot,
                        cache: SPEngineCache, rope: RopeTables,
                        config_: LlamaConfig):
        S = tokens.shape[1]
        if S >= ctx_len:
            toks = tokens[:, :ctx_len]
        else:
            toks = jnp.pad(tokens, ((0, 0), (0, ctx_len - S)))
        logits, ks, vs = prefill_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], toks, prompt_len.astype(jnp.int32),
            rope.cos, rope.sin)
        ctx_k = lax.dynamic_update_slice_in_dim(
            cache.ctx_k, ks.astype(cache.ctx_k.dtype), slot, axis=1)
        ctx_v = lax.dynamic_update_slice_in_dim(
            cache.ctx_v, vs.astype(cache.ctx_v.dtype), slot, axis=1)
        plen = cache.plen.at[slot].set(prompt_len[0].astype(jnp.int32))
        return logits, SPEngineCache(ctx_k, ctx_v, cache.tail_k,
                                     cache.tail_v, plen)

    # instrumentation (dispatch counter + wall histogram) is applied by
    # instrument_sp_engine over the whole step-fn tuple — wrapping here
    # too would double-count every prefill dispatch
    return prefill_slot_fn


def make_sp_engine_decode_body(config: LlamaConfig, tp_axis, Sl: int,
                               chain):
    """THE ragged engine decode shard_map body — single source for the
    plain-sp and stage x sp engine factories, which differ only in how
    the blocks run: chain(x, layer, blocks, ctx_k, ctx_v, tail_k,
    tail_v) -> (x', (tail_k', tail_v')) is lax.scan for plain sp and
    sp_pipeline._stage_chain for the stage pipeline."""
    from cake_tpu.models.llama.cache import update_layer_cache_per_row
    from cake_tpu.ops.rope import rope_rows_per_row

    def decode_body(blocks, embed, final_norm, lm_head, token, pos,
                    active, ctx_k, ctx_v, tail_k, tail_v, plen, cos,
                    sin):
        idx = lax.axis_index("sp")
        B = token.shape[0]
        tail_T = tail_k.shape[2]
        x = jnp.take(embed, token, axis=0)               # [B, 1, D]
        rope_c, rope_s = rope_rows_per_row(cos, sin, pos)
        # contiguous positions: tail slot = generated index = pos - plen
        t_slot = jnp.clip(pos - plen, 0, tail_T - 1)     # [B]
        ctx_valid, tail_valid = sp_decode_masks(idx, Sl, plen, tail_T,
                                                t_slot, B)

        def tail_update(tk, tv, k, v):
            # per-row active-masked write (ragged slots), vs the
            # lockstep scalar-slot default
            return update_layer_cache_per_row(tk, tv, k, v, t_slot,
                                              active)

        layer = sp_decode_layer(config, rope_c, rope_s, None, ctx_valid,
                                tail_valid, tp_axis,
                                tail_update=tail_update)
        x, (tk_new, tv_new) = chain(x, layer, blocks, ctx_k, ctx_v,
                                    tail_k, tail_v)
        x = rms_norm(x, final_norm, config.rms_norm_eps)
        logits = qmatmul(x[:, -1], lm_head).astype(jnp.float32)
        return logits, tk_new, tv_new

    return decode_body


def make_decode_ragged_fns(decode_sm, mode: str = "sp"):
    """(decode_ragged_forward, jitted decode_ragged_fn) over a ragged
    sp decode shard_map — shared by the plain-sp and stage x sp engine
    factories. Only the jitted dispatch wrapper gets dispatch-counted
    (by instrument_sp_engine, over the whole step-fn tuple);
    decode_ragged_forward also gets traced INSIDE decode scans, where a
    host-side counter would be meaningless (and silently ignored)."""

    def decode_ragged_forward(params, tokens, cache: SPEngineCache, pos,
                              active, rope: RopeTables,
                              config_: LlamaConfig):
        logits, tk, tv = decode_sm(
            params["blocks"], params["embed"], params["final_norm"],
            params["lm_head"], tokens, pos.astype(jnp.int32),
            active, cache.ctx_k, cache.ctx_v, cache.tail_k,
            cache.tail_v, cache.plen, rope.cos, rope.sin)
        return logits, SPEngineCache(cache.ctx_k, cache.ctx_v, tk, tv,
                                     cache.plen)

    @partial(jax.jit, static_argnames=("config_",),
             donate_argnames=("cache",))
    def decode_ragged_fn(params, tokens, pos, active,
                         cache: SPEngineCache, rope: RopeTables,
                         config_: LlamaConfig):
        return decode_ragged_forward(params, tokens, cache, pos, active,
                                     rope, config_)

    return decode_ragged_forward, decode_ragged_fn
