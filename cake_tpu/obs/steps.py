"""Step-level performance telemetry: flight recorder, MFU/cost
accounting, recompile counters, device gauges, live profiler capture.

PR 1 gave serving *request*-level observability; this module opens the
engine's *step* loop — where all the throughput lives — with four
pieces, all dependency-free:

  * **Step flight recorder** (`StepTelemetry`): one bounded-ring record
    per engine step (kind prefill/decode/decode_scan/spec/mixed —
    mixed records additionally split occupancy into decode rows vs
    prefill-chunk rows vs idle rows and feed the
    `cake_mixed_step_rows_total{kind}` counters —, attention
    impl, batch occupancy, tokens emitted, page-pool free/total,
    dispatch wall seconds, device seconds, per-step MFU / HBM
    utilization, whether the step compiled). Served at
    `GET /api/v1/steps`, optionally appended as JSONL (`--step-log`,
    via the shared obs/jsonl.py writer).

  * **XLA cost accounting** (`JitAccountant` + `lower_cost`): the first
    dispatch of each (step fn, signature) pair runs one extra *lowering*
    (trace only — no XLA compile) and reads
    ``Lowered.cost_analysis()`` FLOPs + bytes-accessed. Combined with
    the measured step time this yields `cake_step_mfu{kind}` and
    `cake_step_hbm_util{kind}`; every new signature also bumps
    `cake_jit_compiles_total{fn}` and lands in the compile-seconds
    histogram. A rising compile counter during steady-state decode is a
    shape-leak recompilation storm — previously invisible.

  * **Device gauges** (`refresh_device_gauges`): per-device HBM
    live/peak/limit bytes from `Device.memory_stats()` — a graceful
    no-op on backends without stats (CPU). Refreshed at scrape time and
    on the serving heartbeat (parallel/health.py).

  * **Live profiler capture** (`ProfileCapture` / module `PROFILER`):
    `POST /api/v1/profile {"seconds": N}` grabs a jax.profiler
    Perfetto trace from the *running* serving process
    (utils/profiling.capture_trace), single-flight-guarded — a second
    concurrent capture gets `ProfileBusyError` (HTTP 409).

MFU here is model-FLOPs utilization: (program FLOPs from
cost_analysis) / (peak chip FLOP/s x measured step seconds), clamped to
1.0. On backends whose peak is unknown (CPU) a conservative fallback
peak keeps the number well-defined — treat it as relative, not
absolute, off-TPU. HBM utilization is bytes-accessed over the chip's
HBM bandwidth the same way. Both are estimates from *unoptimized* HLO:
fusion changes the real byte traffic, but the trend per step and the
fold-vs-pallas/bucket-vs-bucket comparisons are exactly what they are
for.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.jsonl import JsonlAppender

log = logging.getLogger(__name__)

# Peak dense bf16 matmul FLOP/s by device_kind substring (public TPU
# specs), first match wins. THE single table for the whole repo —
# bench.py delegates here, so the measured (flight recorder) and
# analytic (roofline) utilization numbers in one BENCH row can never
# use different hardware constants. Unknown-TPU / CPU fallbacks differ:
# an unknown accelerator gets a conservative TPU-class figure, a CPU
# lane a host-class one (the CPU numbers are relative either way).
PEAK_FLOPS = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
]
DEFAULT_PEAK_FLOPS = 197e12        # unknown accelerator: v5e-class
CPU_PEAK_FLOPS = 1e12

# HBM bandwidth (bytes/s) by device_kind substring (same entries and
# defaults bench.py historically used, now sourced from here only).
HBM_BPS = [
    ("v5 lite", 819e9), ("v5e", 819e9),
    ("v5p", 2765e9), ("v5", 2765e9),
    ("v6", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
]
DEFAULT_HBM_BPS = 819e9            # unknown accelerator: v5e-class
CPU_HBM_BPS = 100e9


def _is_cpu_kind(k: str) -> bool:
    return not k or "cpu" in k


def peak_flops_for(kind: str) -> float:
    k = (kind or "").lower()
    for sub, v in PEAK_FLOPS:
        if sub in k:
            return v
    return CPU_PEAK_FLOPS if _is_cpu_kind(k) else DEFAULT_PEAK_FLOPS


def hbm_bps_for(kind: str) -> float:
    k = (kind or "").lower()
    for sub, v in HBM_BPS:
        if sub in k:
            return v
    return CPU_HBM_BPS if _is_cpu_kind(k) else DEFAULT_HBM_BPS


# -- metric families (module-level so the lint/README coverage gate sees
#    them whether or not an engine ran) --------------------------------------

_STEPS_TOTAL = _m.counter(
    "cake_steps_total",
    "Engine steps recorded by the flight recorder, by step kind",
    labelnames=("kind",))
_STEP_DISPATCH = _m.histogram(
    "cake_step_dispatch_seconds",
    "Per-step dispatch wall seconds, by step kind",
    labelnames=("kind",))
_STEP_MFU = _m.gauge(
    "cake_step_mfu",
    "Last step's model-FLOPs utilization (cost_analysis FLOPs / peak "
    "chip FLOPs x step seconds), by step kind",
    labelnames=("kind",))
_STEP_HBM = _m.gauge(
    "cake_step_hbm_util",
    "Last step's HBM-bandwidth utilization (cost_analysis bytes / HBM "
    "bandwidth x step seconds), by step kind",
    labelnames=("kind",))
_JIT_COMPILES = _m.counter(
    "cake_jit_compiles_total",
    "New jit signatures dispatched per step fn (a rise during "
    "steady-state decode is a shape-leak recompilation storm)",
    labelnames=("fn",))
_JIT_COMPILE_SECONDS = _m.histogram(
    "cake_jit_compile_seconds",
    "Wall seconds of step-fn dispatches that compiled a new signature")
_DEV_HBM_IN_USE = _m.gauge(
    "cake_device_hbm_bytes_in_use",
    "Live HBM bytes per device (Device.memory_stats; absent on CPU)",
    labelnames=("device",))
_DEV_HBM_PEAK = _m.gauge(
    "cake_device_hbm_peak_bytes",
    "Peak HBM bytes per device since process start",
    labelnames=("device",))
_DEV_HBM_LIMIT = _m.gauge(
    "cake_device_hbm_bytes_limit",
    "HBM byte capacity per device",
    labelnames=("device",))
_MIXED_ROWS = _m.counter(
    "cake_mixed_step_rows_total",
    "Row-slots processed by mixed continuous-batching steps, by row "
    "kind (decode = one-token decode rows, prefill = prefill-chunk "
    "rows, idle = empty slots in the launch)",
    labelnames=("kind",))


def refresh_page_gauges(engine) -> None:
    """KV page-pool occupancy gauges for a paged engine (no-op for
    dense). THE single definition — called at scrape time
    (api/server.py) and on the serving heartbeat (parallel/health.py),
    so the two sites cannot drift in names or help text."""
    if not getattr(engine, "paged", False):
        return
    try:
        # the pager is engine-thread state swapped wholesale by a live
        # reconfigure; its declared lock (_switch_lock) pins one
        # consistent pool for this scrape. NON-blocking on purpose: the
        # watchdog and /metrics run through here, and a switch wedged
        # on device work must never take the stall detector (or
        # observability) down with it — on contention the gauges keep
        # their last values for one scrape.
        if engine._switch_lock.acquire(blocking=False):
            try:
                n_total = engine.cache.n_pages
                # cakelint: skip[affinity] _switch_lock held via the non-blocking acquire above (the with-form the checker recognizes would block a wedged switch forever)
                n_free = engine._pager.free_pages
            finally:
                engine._switch_lock.release()
            _m.gauge("cake_engine_kv_pages_total",
                     "KV pages in the pool").set(n_total)
            _m.gauge("cake_engine_kv_pages_free",
                     "KV pages currently free").set(n_free)
        # prefix sharing (serve/engine.py sets this at admission /
        # release; re-set at scrape so a restarted scraper converges
        # without waiting for the next admission)
        _m.gauge("cake_prefix_pages_shared",
                 "Shared prefix pages currently mapped into admitted "
                 "slots' table rows (pool pages saved vs unshared "
                 "admission)").set(
            getattr(engine, "_prefix_pages_shared", 0))
        # KV tiering (cake_tpu/kv): host_tier owns the cake_kv_* gauges
        # AND their refresh — one public seam, so a scrape converges
        # without this module re-implementing the tier's accounting
        from cake_tpu.kv import host_tier as kv_host_tier
        kv_host_tier.refresh_gauges(engine.cache,
                                    getattr(engine, "_host_tier", None))
    except Exception:  # noqa: BLE001 — telemetry must never fail serving
        log.debug("page gauge refresh failed", exc_info=True)


def refresh_device_gauges() -> None:
    """Sync per-device HBM gauges from Device.memory_stats(). Graceful
    no-op on backends without stats (CPU): the gauges simply stay
    sample-less. Called at scrape time (api/server.py) and on the
    serving heartbeat (parallel/health.py)."""
    try:
        from cake_tpu.utils.profiling import device_memory_stats
        stats = device_memory_stats()
    except Exception:  # noqa: BLE001 — a scrape must never fail
        log.debug("device memory stats unavailable", exc_info=True)
        return
    for s in stats:
        if s.get("bytes_in_use") is None:
            continue   # backend without memory_stats (CPU)
        dev = str(s["device"])
        _DEV_HBM_IN_USE.labels(device=dev).set(float(s["bytes_in_use"]))
        if s.get("peak_bytes_in_use") is not None:
            _DEV_HBM_PEAK.labels(device=dev).set(
                float(s["peak_bytes_in_use"]))
        if s.get("bytes_limit") is not None:
            _DEV_HBM_LIMIT.labels(device=dev).set(float(s["bytes_limit"]))


# -- XLA cost accounting ------------------------------------------------------


@dataclass(frozen=True)
class CostInfo:
    """One compiled program's cost_analysis numbers (unoptimized HLO)."""

    flops: float = 0.0
    bytes_accessed: float = 0.0


def _normalize_cost(ca) -> Optional[CostInfo]:
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops") or 0.0)
    nbytes = float(ca.get("bytes accessed") or 0.0)
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return CostInfo(flops=flops, bytes_accessed=nbytes)


def lower_cost(fn, args: tuple, kwargs: Optional[dict] = None
               ) -> Optional[CostInfo]:
    """FLOPs + bytes-accessed of fn(*args, **kwargs) via one extra
    LOWERING (trace only — `Lowered.cost_analysis()` runs HLO cost
    analysis without invoking the XLA backend compiler, so this costs a
    trace, not a compile). functools.partial layers and @wraps wrappers
    are unwrapped to reach the jitted callable; anything without
    `.lower` (or whose lowering/analysis raises) yields None — cost
    accounting is best-effort and must never fail a dispatch."""
    kwargs = dict(kwargs or {})
    seen = 0
    while seen < 8:   # bounded unwrap: partial chains + wraps chains
        if isinstance(fn, functools.partial):
            kwargs = {**fn.keywords, **kwargs}
            args = tuple(fn.args) + tuple(args)
            fn = fn.func
        elif getattr(fn, "__wrapped__", None) is not None \
                and not hasattr(fn, "lower"):
            fn = fn.__wrapped__
        else:
            break
        seen += 1
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        return _normalize_cost(lower(*args, **kwargs).cost_analysis())
    except Exception:  # noqa: BLE001 — best-effort accounting
        log.debug("cost_analysis unavailable for %r",
                  getattr(fn, "__name__", fn), exc_info=True)
        return None


class JitAccountant:
    """Process-global compile/cost tracker keyed by (fn name, caller
    signature key). The engine's jit cache is process-global too (its
    step fns are module-level jitted functions), so a global accountant
    mirrors real retrace behavior: a second engine dispatching an
    already-compiled signature counts no compile."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seen: Dict[tuple, Optional[CostInfo]] = {}

    def begin(self, name: str, key: tuple,
              cost_cb) -> Tuple[bool, Optional[CostInfo]]:
        """(is_new_signature, cost). On a new signature: increments the
        per-fn compile counter and captures cost via cost_cb() (called
        BEFORE the dispatch executes, while donated buffers are still
        alive)."""
        with self._lock:
            if key in self._seen:
                return False, self._seen[key]
        cost = None
        try:
            cost = cost_cb()
        except Exception:  # noqa: BLE001
            log.debug("cost callback failed for %s", name, exc_info=True)
        with self._lock:
            if key in self._seen:   # racing thread won
                return False, self._seen[key]
            self._seen[key] = cost
        _JIT_COMPILES.labels(fn=name).inc()
        return True, cost

    def compile_seconds(self, seconds: float) -> None:
        _JIT_COMPILE_SECONDS.observe(seconds)


ACCOUNTANT = JitAccountant()


class _JitStep:
    """Handle returned by StepTelemetry.jit_step: `.new` says this
    dispatch compiles a fresh signature, `.cost` carries the program's
    CostInfo; call `.finish(elapsed)` after the dispatch so compile
    wall time lands in the histogram."""

    __slots__ = ("new", "cost", "_acct")

    def __init__(self, new: bool, cost: Optional[CostInfo],
                 acct: JitAccountant):
        self.new = new
        self.cost = cost
        self._acct = acct

    def finish(self, seconds: float) -> None:
        if self.new:
            self._acct.compile_seconds(seconds)


# -- flight recorder ----------------------------------------------------------

# step kinds whose records carry decode throughput (utilization
# aggregation weights these; prefill is reported per-kind only).
# "mixed" belongs here: a mixed step IS the decode step with prefill
# chunks riding along — excluding it would blind the MFU gauge to the
# very path token-level continuous batching exists to improve.
_DECODE_KINDS = ("decode", "decode_scan", "spec", "mixed")


def _sig(v: Optional[float], digits: int = 6) -> Optional[float]:
    """Round to significant digits (utilization exports: decimal-place
    rounding would collapse legitimately tiny values to 0.0)."""
    return float(f"%.{digits}g" % v) if v is not None else None


@dataclass
class StepRecord:
    """One engine step. dispatch_s is host wall to get the work onto
    the device (for double-buffered bursts, the dispatch half alone);
    device_s is the measured completion wall (the fetch half, a proxy
    for device time on sync paths); wall_s the whole step."""

    step: int
    ts: float                      # wall-clock
    kind: str                      # prefill | decode | decode_scan | spec
                                   # | mixed
    impl: str                      # dense | ring | paged-fold | ... | custom
    rows: int                      # batch occupancy this step
    tokens: int                    # tokens emitted by this step
    dispatch_s: float
    device_s: float
    wall_s: float
    mfu: Optional[float] = None
    hbm_util: Optional[float] = None
    pages_free: Optional[int] = None
    pages_total: Optional[int] = None
    compiled: bool = False         # this step compiled a new signature
    # mixed-step occupancy split (token-level continuous batching):
    # decode rows vs prefill-chunk rows vs idle rows in the launch
    rows_decode: Optional[int] = None
    rows_prefill: Optional[int] = None
    rows_idle: Optional[int] = None
    # rids whose rows this step's dispatched batch contained (bounded
    # by the engine's slot count) — the per-request explain endpoint
    # (obs/timeline.py) selects a request's steps through this
    rids: Optional[Tuple[int, ...]] = None

    def to_dict(self) -> Dict:
        out = {
            "step": self.step,
            "ts": round(self.ts, 6),
            "kind": self.kind,
            "impl": self.impl,
            "rows": self.rows,
            "tokens": self.tokens,
            "dispatch_s": round(self.dispatch_s, 6),
            "device_s": round(self.device_s, 6),
            "wall_s": round(self.wall_s, 6),
            # significant digits, not decimal places: a compile-inflated
            # step's 1e-7 MFU must stay nonzero in the export
            "mfu": _sig(self.mfu),
            "hbm_util": _sig(self.hbm_util),
            "compiled": self.compiled,
        }
        if self.pages_total is not None:
            out["pages_free"] = self.pages_free
            out["pages_total"] = self.pages_total
        if self.rows_decode is not None:
            out["rows_decode"] = self.rows_decode
            out["rows_prefill"] = self.rows_prefill
            out["rows_idle"] = self.rows_idle
        if self.rids is not None:
            out["rids"] = list(self.rids)
        return out


class StepTelemetry:
    """Per-engine step flight recorder + jit/cost accounting front end.

    capacity bounds the in-memory ring (GET /api/v1/steps); log_path
    additionally appends every record as one JSON line (--step-log,
    shared obs/jsonl.py durability semantics). key_prefix namespaces
    the accountant keys so engines with different configs cannot alias
    each other's signatures. peak_flops/hbm_bps override the
    device-kind tables (tests pin them for exact MFU math)."""

    # cakelint guards discipline: the event bus is an optional plane
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, *, impl: str = "dense", capacity: int = 512,
                 log_path: Optional[str] = None,
                 key_prefix: tuple = (),
                 peak_flops: Optional[float] = None,
                 hbm_bps: Optional[float] = None,
                 accountant: Optional[JitAccountant] = None,
                 events=None):
        self.impl = impl
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._next = 1
        self._log = JsonlAppender(log_path) if log_path else None
        self._acct = accountant or ACCOUNTANT
        self._prefix = tuple(key_prefix)
        self._peak = peak_flops
        self._bps = hbm_bps
        # obs/events.EventBus (None = disabled plane, one attribute
        # test per publish): new jit signatures publish a "recompile"
        # event, so a shape-leak recompilation storm shows up on the
        # event timeline, not only as a rising counter
        self._events = events

    def rebind(self, *, impl: Optional[str] = None,
               key_prefix: Optional[tuple] = None) -> None:
        """Re-namespace this recorder after a live engine config switch
        (serve/engine.reconfigure): the ring, the --step-log appender
        and the accountant survive — only the impl tag and the
        signature prefix move, so the new config's compiled programs
        can never alias the old config's in the seen-set."""
        if impl is not None:
            self.impl = impl
        if key_prefix is not None:
            self._prefix = tuple(key_prefix)

    # -- jit/cost accounting ------------------------------------------------

    def jit_step(self, fn_name: str, key: tuple, cost_cb) -> _JitStep:
        """Account one dispatch of `fn_name` under signature `key`
        (caller-chosen: the shapes/statics that select the compiled
        program). cost_cb() -> CostInfo|None runs once per new key —
        typically `lambda: lower_cost(fn, args, kwargs)`."""
        new, cost = self._acct.begin(
            fn_name, self._prefix + (fn_name,) + tuple(key), cost_cb)
        if new and self._events is not None:
            self._events.publish("recompile", fn=fn_name, impl=self.impl)
        return _JitStep(new, cost, self._acct)

    def _peaks(self) -> Tuple[float, float]:
        if self._peak is None or self._bps is None:
            kind = ""
            try:
                import jax
                kind = jax.devices()[0].device_kind
            except Exception:  # noqa: BLE001
                pass
            if self._peak is None:
                self._peak = peak_flops_for(kind)
            if self._bps is None:
                self._bps = hbm_bps_for(kind)
        return self._peak, self._bps

    # -- recording ----------------------------------------------------------

    def record(self, kind: str, *, rows: int = 0, tokens: int = 0,
               dispatch_s: Optional[float] = None,
               device_s: Optional[float] = None,
               wall_s: Optional[float] = None,
               cost: Optional[CostInfo] = None,
               compiled: bool = False,
               pages_free: Optional[int] = None,
               pages_total: Optional[int] = None,
               rows_decode: Optional[int] = None,
               rows_prefill: Optional[int] = None,
               rows_idle: Optional[int] = None,
               rids: Optional[Sequence[int]] = None) -> StepRecord:
        """Append one step record; derives MFU / HBM utilization from
        `cost` and the step's device seconds. Any subset of the three
        timings may be given; missing ones fall back to the others.
        rows_decode/rows_prefill/rows_idle carry a mixed step's
        occupancy split and feed the cake_mixed_step_rows_total
        counters. rids: the requests whose rows rode this dispatch
        (the per-request explain's step linkage)."""
        wall = wall_s if wall_s is not None else (
            (dispatch_s or 0.0) + (device_s or 0.0))
        disp = dispatch_s if dispatch_s is not None else wall
        dev = device_s if device_s is not None else wall
        mfu = hbm = None
        if cost is not None and dev > 0:
            peak, bps = self._peaks()
            if cost.flops > 0 and peak > 0:
                mfu = min(1.0, cost.flops / (peak * dev))
            if cost.bytes_accessed > 0 and bps > 0:
                hbm = min(1.0, cost.bytes_accessed / (bps * dev))
        with self._lock:
            rec = StepRecord(
                step=self._next, ts=time.time(), kind=kind,
                impl=self.impl, rows=int(rows), tokens=int(tokens),
                dispatch_s=float(disp), device_s=float(dev),
                wall_s=float(wall), mfu=mfu, hbm_util=hbm,
                pages_free=pages_free, pages_total=pages_total,
                compiled=bool(compiled),
                rows_decode=rows_decode, rows_prefill=rows_prefill,
                rows_idle=rows_idle,
                rids=(tuple(int(r) for r in rids)
                      if rids is not None else None))
            self._next += 1
            self._ring.append(rec)
        _STEPS_TOTAL.labels(kind=kind).inc()
        _STEP_DISPATCH.labels(kind=kind).observe(disp)
        for k, v in (("decode", rows_decode), ("prefill", rows_prefill),
                     ("idle", rows_idle)):
            if v:
                _MIXED_ROWS.labels(kind=k).inc(v)
        if mfu is not None:
            _STEP_MFU.labels(kind=kind).set(_sig(mfu))
        if hbm is not None:
            _STEP_HBM.labels(kind=kind).set(_sig(hbm))
        if self._log is not None:
            self._log.append(rec.to_dict())
        return rec

    # -- export -------------------------------------------------------------

    def dump(self, limit: Optional[int] = None) -> List[Dict]:
        """Records newest first (the GET /api/v1/steps body)."""
        with self._lock:
            recs = list(reversed(self._ring))
        if limit is not None:
            recs = recs[:max(0, int(limit))]
        return [r.to_dict() for r in recs]

    def records_for(self, rid: int) -> List[Dict]:
        """Ring records whose dispatched batch contained `rid`, oldest
        first — the per-request explain's step stream (bounded by the
        ring capacity, like every other dump)."""
        with self._lock:
            recs = [r for r in self._ring
                    if r.rids is not None and rid in r.rids]
        return [r.to_dict() for r in recs]

    def utilization(self, since_step: int = 0, *,
                    include_prefill: bool = False) -> Dict[str, float]:
        """Wall-time-weighted mean MFU / HBM utilization over the
        ring's decode-side records (decode / decode_scan / spec;
        prefill excluded — its utilization profile is a different
        question). include_prefill=True widens the aggregate to
        prefill records too: an A/B against mixed batching needs it,
        because a mixed record folds its chunk's prefill FLOPs in and
        the phase-split side must count the same work to compare
        occupancy rather than aggregation. Records whose dispatch
        compiled a new signature are excluded — their wall is XLA
        compile, not decode — and since_step drops everything up to a
        warmup boundary (pass the post-warmup
        `summary()["recorded_steps"]`). 0.0 when no remaining record
        carried cost info — a bench consumer always gets the keys."""
        kinds = _DECODE_KINDS + ("prefill",) if include_prefill \
            else _DECODE_KINDS
        with self._lock:
            recs = [r for r in self._ring
                    if r.kind in kinds and not r.compiled
                    and r.step > since_step]
        out = {"mfu": 0.0, "hbm_util": 0.0}
        for field in ("mfu", "hbm_util"):
            num = den = 0.0
            for r in recs:
                v = getattr(r, field)
                if v is not None and r.wall_s > 0:
                    num += v * r.wall_s
                    den += r.wall_s
            if den > 0:
                out[field] = _sig(num / den)
        return out

    def summary(self) -> Dict:
        """Aggregate view for /api/v1/steps and tools: per-kind counts,
        tokens, mean dispatch seconds, compile counts, plus the
        decode-side utilization means."""
        with self._lock:
            recs = list(self._ring)
            recorded = self._next - 1
        kinds: Dict[str, Dict] = {}
        for r in recs:
            k = kinds.setdefault(r.kind, {
                "count": 0, "tokens": 0, "compiles": 0,
                "dispatch_s_sum": 0.0})
            k["count"] += 1
            k["tokens"] += r.tokens
            k["compiles"] += 1 if r.compiled else 0
            k["dispatch_s_sum"] += r.dispatch_s
        for k in kinds.values():
            k["mean_dispatch_s"] = round(
                k.pop("dispatch_s_sum") / k["count"], 6)
        return {
            "recorded_steps": recorded,
            "ring": len(recs),
            "impl": self.impl,
            "kinds": kinds,
            **self.utilization(),
        }

    def close(self) -> None:
        if self._log is not None:
            self._log.close()


# -- on-demand profiler capture ----------------------------------------------


class ProfileBusyError(RuntimeError):
    """A capture is already running (the single-flight guard). The API
    layer maps this to HTTP 409."""


class ProfileCapture:
    """Single-flight jax.profiler capture from a live process.

    jax.profiler supports one active trace per process; a second
    concurrent capture would raise from deep inside the profiler (or
    corrupt the first artifact), so the guard rejects it up front with
    ProfileBusyError instead."""

    MAX_SECONDS = 120.0

    def __init__(self):
        self._lock = threading.Lock()

    @property
    def busy(self) -> bool:
        # advisory only (the real gate is the non-blocking acquire)
        return self._lock.locked()

    def capture(self, seconds: float,
                out_dir: Optional[str] = None) -> Dict:
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            raise ValueError("seconds must be a number")
        if not (0 < seconds <= self.MAX_SECONDS):
            raise ValueError(
                f"seconds must be in (0, {self.MAX_SECONDS:.0f}]")
        if not self._lock.acquire(blocking=False):
            raise ProfileBusyError(
                "a profiler capture is already in progress")
        try:
            from cake_tpu.utils.profiling import capture_trace
            return capture_trace(seconds, out_dir)
        finally:
            self._lock.release()


PROFILER = ProfileCapture()
