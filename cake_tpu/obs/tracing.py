"""Per-request lifecycle tracing for the serving engine.

Every request gets a `TraceRecord` with timestamped spans:

    admitted -> queued -> prefill -> first_token -> decode
                                                 -> retired | error | cancelled

and the derived latencies every capacity/regression question needs:
queue wait (admitted -> prefill), prefill seconds (prefill ->
first_token), TTFT (admitted -> first_token), per-token inter-arrival
stats, and e2e latency. Records live in a bounded ring (finished
requests; active ones are tracked until they finish) and are dumped by
`GET /api/v1/requests`. With an events path set (`--trace-events`),
every span is also appended as one JSON line — the replayable audit log
for offline analysis.

The tracer also feeds the metrics registry: finishing a request
observes the TTFT / e2e / queue-wait / prefill histograms and the
per-status request counter, so `/api/v1/metrics` latency distributions
populate with zero extra wiring in the engine. Tracer methods never
raise into the engine loop — a broken events file degrades to a logged
warning, not a failed generation.

Both engine flavors run through `serve.engine.InferenceEngine`
(single-device dense, paged, speculative, topology-pipelined, and the
sp / stage x sp / dp x sp step-fn paths), so instrumenting the engine's
submit/prefill/emit/retire seams covers every serving mode at once.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.jsonl import JsonlAppender

log = logging.getLogger(__name__)

# terminal statuses a record can finish with
TERMINAL = ("retired", "error", "cancelled")

REQUEST_TTFT = _m.histogram(
    "cake_request_ttft_seconds",
    "Time from admission to first generated token (includes queue wait)")
REQUEST_E2E = _m.histogram(
    "cake_request_e2e_seconds",
    "Time from admission to request retirement")
REQUEST_QUEUE_WAIT = _m.histogram(
    "cake_request_queue_wait_seconds",
    "Time from admission until a decode slot started prefilling")
REQUEST_PREFILL = _m.histogram(
    "cake_request_prefill_seconds",
    "Time from prefill dispatch to the first generated token")
REQUEST_INTER_TOKEN = _m.histogram(
    "cake_request_inter_token_seconds",
    "Gap between consecutive generated tokens of one request",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0))
REQUESTS_FINISHED = _m.counter(
    "cake_requests_finished_total",
    "Requests finished, by terminal status", labelnames=("status",))


@dataclass
class TraceRecord:
    """One request's lifecycle. Spans are (name, perf_counter ts);
    `wall_start` anchors them to wall-clock for export."""

    rid: int
    prompt_tokens: int = 0
    max_new_tokens: int = 0
    # admission class (cake_tpu/sched priority classes; "standard"
    # for engines without SLO scheduling)
    priority: str = "standard"
    spans: List[tuple] = field(default_factory=list)
    status: str = "active"
    error: Optional[str] = None
    output_tokens: int = 0
    # inter-token gap summary (seconds); full per-token lists would make
    # the ring's memory proportional to generated tokens
    itl_count: int = 0
    itl_sum: float = 0.0
    itl_max: float = 0.0
    # annotations (checkpoint resume, decode-budget truncation, ...)
    resumed: bool = False
    truncated: bool = False
    # engine config epoch at admission (cake_tpu/autotune): a live
    # config switch bumps the engine's epoch, so a trace whose spans
    # include a "reconfigured" event is attributable to both configs —
    # admitted under this epoch, finished under a later one
    config_epoch: int = 0
    # originating distributed-trace id (x-cake-trace, minted by the
    # front-door router or supplied by the client): the key the
    # router's federated timeline correlates this replica-local record
    # under. None when the request arrived without trace context.
    trace: Optional[str] = None
    wall_start: float = 0.0
    _last_token_t: float = 0.0

    def _t(self, name: str) -> Optional[float]:
        for n, t in self.spans:
            if n == name:
                return t
        return None

    def _t_last(self, name: str) -> Optional[float]:
        t = None
        for n, ts in self.spans:
            if n == name:
                t = ts
        return t

    @property
    def queue_wait_s(self) -> Optional[float]:
        a, p = self._t("admitted"), self._t_last("prefill")
        return (p - a) if a is not None and p is not None else None

    @property
    def prefill_s(self) -> Optional[float]:
        p, f = self._t_last("prefill"), self._t("first_token")
        return (f - p) if p is not None and f is not None else None

    @property
    def ttft_s(self) -> Optional[float]:
        a, f = self._t("admitted"), self._t("first_token")
        return (f - a) if a is not None and f is not None else None

    @property
    def e2e_s(self) -> Optional[float]:
        a = self._t("admitted")
        end = self._t(self.status) if self.status in TERMINAL else None
        return (end - a) if a is not None and end is not None else None

    def to_dict(self) -> Dict:
        t0 = self.spans[0][1] if self.spans else 0.0
        out = {
            "rid": self.rid,
            "status": self.status,
            "priority": self.priority,
            "config_epoch": self.config_epoch,
            "prompt_tokens": self.prompt_tokens,
            "max_new_tokens": self.max_new_tokens,
            "output_tokens": self.output_tokens,
            "submitted_at": round(self.wall_start, 6),
            "spans": [
                {"name": n, "t": round(self.wall_start + (ts - t0), 6),
                 "offset_s": round(ts - t0, 6)}
                for n, ts in self.spans
            ],
            "queue_wait_s": _r(self.queue_wait_s),
            "prefill_s": _r(self.prefill_s),
            "ttft_s": _r(self.ttft_s),
            "e2e_s": _r(self.e2e_s),
            "inter_token": {
                "count": self.itl_count,
                "mean_s": _r(self.itl_sum / self.itl_count
                             if self.itl_count else None),
                "max_s": _r(self.itl_max if self.itl_count else None),
            },
        }
        if self.error:
            out["error"] = self.error
        if self.trace:
            out["trace"] = self.trace
        if self.resumed:
            out["resumed"] = True
        if self.truncated:
            out["truncated"] = True
        return out


def _r(v: Optional[float]) -> Optional[float]:
    return round(v, 6) if v is not None else None


class RequestTracer:
    """Bounded-ring lifecycle recorder, safe from any thread.

    capacity bounds the FINISHED-record ring; active records are always
    retained (they are bounded by the engine's queue + slots). With
    `events_path`, each span appends one JSON line
    ``{"ts", "rid", "event", ...}`` through the shared obs/jsonl.py
    writer (append-only, lazily opened so a follower process that never
    serves requests never touches the file, fsync on close, fail-open
    on OSError; read it back with `obs.jsonl.read_jsonl`, which
    tolerates the torn tail a killed process leaves)."""

    # cakelint guards discipline: SLO accounting and the event bus are
    # optional attachments
    OPTIONAL_PLANES = ("_slo", "_events")

    def __init__(self, capacity: int = 256,
                 events_path: Optional[str] = None,
                 observe_metrics: bool = True,
                 slo=None):
        self._lock = threading.Lock()
        self._active: Dict[int, TraceRecord] = {}
        self._done: deque = deque(maxlen=max(1, int(capacity)))
        self._events = (JsonlAppender(events_path)
                        if events_path else None)
        self._observe = observe_metrics
        # obs/slo.SLOAccountant: finish() is THE retire seam every
        # path funnels through (normal emit, recovery's exhausted-
        # budget finish), so attainment/goodput accounting hooked here
        # sees each request exactly once, with latencies measured from
        # the ORIGINAL admission span (resubmits append spans to the
        # same record — the clock never resets on requeue)
        self._slo = slo

    # -- lifecycle hooks (called by the engine) ---------------------------

    def admit(self, rid: int, prompt_tokens: int,
              max_new_tokens: int, priority: str = "standard",
              config_epoch: int = 0,
              trace: Optional[str] = None) -> None:
        now = time.perf_counter()
        rec = TraceRecord(rid=rid, prompt_tokens=prompt_tokens,
                          max_new_tokens=max_new_tokens,
                          priority=priority,
                          config_epoch=config_epoch,
                          trace=trace,
                          wall_start=time.time())
        rec.spans.append(("admitted", now))
        rec.spans.append(("queued", now))
        with self._lock:
            self._active[rid] = rec
        self._event(rec, "admitted", prompt_tokens=prompt_tokens,
                    max_new_tokens=max_new_tokens, priority=priority)

    def drop(self, rid: int) -> None:
        """Un-admit a request whose submission was rejected (queue
        full): remove the active record without retiring it into the
        ring — it never entered the engine."""
        with self._lock:
            rec = self._active.pop(rid, None)
        if rec is not None:
            self._event(rec, "rejected")

    def span(self, rid: int, name: str, **fields) -> None:
        now = time.perf_counter()
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                return
            rec.spans.append((name, now))
        self._event(rec, name, **fields)

    def prefill_start(self, rid: int) -> None:
        self.span(rid, "prefill")

    def first_token(self, rid: int) -> None:
        now = time.perf_counter()
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                return
            rec.spans.append(("first_token", now))
            rec.spans.append(("decode", now))
            rec.output_tokens = 1
            rec._last_token_t = now
        self._event(rec, "first_token", ttft_s=_r(rec.ttft_s))

    def token(self, rid: int) -> None:
        """Per-token inter-arrival accounting (tokens after the first).
        Summary-only on the record; the distribution goes to the
        inter-token histogram."""
        now = time.perf_counter()
        gap = None
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                return
            if rec._last_token_t:
                gap = now - rec._last_token_t
                rec.itl_count += 1
                rec.itl_sum += gap
                rec.itl_max = max(rec.itl_max, gap)
            rec._last_token_t = now
            rec.output_tokens += 1
        if gap is not None and self._observe:
            REQUEST_INTER_TOKEN.observe(gap)

    def finish(self, rid: int, status: str = "retired",
               error: Optional[str] = None,
               output_tokens: Optional[int] = None) -> None:
        """Move a request to the finished ring (idempotent: only the
        first terminal transition records)."""
        if status not in TERMINAL:
            raise ValueError(f"not a terminal status: {status!r}")
        now = time.perf_counter()
        with self._lock:
            rec = self._active.pop(rid, None)
            if rec is None:
                return
            rec.status = status
            rec.error = error
            if output_tokens is not None:
                rec.output_tokens = output_tokens
            rec.spans.append((status, now))
            self._done.append(rec)
        if self._observe:
            REQUESTS_FINISHED.labels(status=status).inc()
            if status == "retired":
                for h, v in ((REQUEST_TTFT, rec.ttft_s),
                             (REQUEST_E2E, rec.e2e_s),
                             (REQUEST_QUEUE_WAIT, rec.queue_wait_s),
                             (REQUEST_PREFILL, rec.prefill_s)):
                    if v is not None:
                        h.observe(v)
        if self._slo is not None and status != "cancelled":
            # cancelled = the client went away; the server attained
            # nothing and missed nothing. Errors are unconditional
            # misses (slo="failed").
            self._slo.observe(rec.priority, rec.ttft_s, rec.e2e_s,
                              rec.output_tokens,
                              failed=(status == "error"))
        self._event(rec, status, error=error,
                    output_tokens=rec.output_tokens, e2e_s=_r(rec.e2e_s),
                    queue_wait_s=_r(rec.queue_wait_s))

    def annotate(self, rid: int, **fields) -> None:
        """Attach flags to a live record (resumed / truncated / ...).
        Unknown keys are ignored rather than raised — annotation is
        best-effort metadata, never control flow."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                # the request may have retired between submit and this
                # call (ultra-fast generation): annotate the ring record
                rec = next((r for r in self._done if r.rid == rid), None)
            if rec is None:
                return
            for k, v in fields.items():
                if hasattr(rec, k) and not k.startswith("_"):
                    setattr(rec, k, v)

    # -- export -----------------------------------------------------------

    def dump(self, limit: Optional[int] = None,
             rid: Optional[int] = None,
             cls: Optional[str] = None,
             since: Optional[int] = None) -> List[Dict]:
        """All records, newest first: active requests, then the finished
        ring. Filters compose (GET /api/v1/requests): rid= exact,
        cls= priority class, since= strictly-greater rid — rids are
        monotonic per engine, so `since=<response cursor>` is a cursor
        that reads only requests admitted after the previous poll.
        With since= the order flips to OLDEST-first and limit= keeps
        the first n (the page right after the cursor — newest-first
        truncation would skip the older records forever); without it,
        newest-first is the natural dashboard view."""
        with self._lock:
            recs = (sorted(self._active.values(),
                           key=lambda r: r.rid, reverse=True)
                    + list(reversed(self._done)))
        if rid is not None:
            recs = [r for r in recs if r.rid == rid]
        if cls is not None:
            recs = [r for r in recs if r.priority == cls]
        if since is not None:
            recs = sorted((r for r in recs if r.rid > since),
                          key=lambda r: r.rid)
        if limit is not None:
            recs = recs[:max(0, int(limit))]
        return [r.to_dict() for r in recs]

    def get(self, rid: int) -> Optional[Dict]:
        """One record by rid (active or finished), or None — the
        timeline endpoint's lookup."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                rec = next((r for r in self._done if r.rid == rid),
                           None)
            return rec.to_dict() if rec is not None else None

    def trace_for(self, rid: int) -> Optional[str]:
        """The distributed-trace id (x-cake-trace) the request was
        admitted under, or None — the EventBus's per-publish annotation
        resolver (one dict lookup; events are per-incident, never
        per-token, so this sits on no hot path)."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                rec = next((r for r in self._done if r.rid == rid),
                           None)
            return rec.trace if rec is not None else None

    def recent_ttfts(self, n: int = 32) -> List[float]:
        """TTFT seconds of the newest <= n finished-and-retired
        requests (the autotune controller's arrival-latency signal —
        cheap: one pass over the bounded ring's tail)."""
        out: List[float] = []
        with self._lock:
            recs = list(self._done)[-max(1, int(n)):]
        for r in recs:
            if r.status == "retired" and r.ttft_s is not None:
                out.append(r.ttft_s)
        return out

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def close(self) -> None:
        if self._events is not None:
            self._events.close()

    # -- JSONL event log ---------------------------------------------------

    def _event(self, rec: TraceRecord, event: str, **fields) -> None:
        if self._events is None:
            return
        line = {"ts": round(time.time(), 6), "rid": rec.rid,
                "event": event}
        if rec.trace:
            line["trace"] = rec.trace
        line.update({k: v for k, v in fields.items() if v is not None})
        self._events.append(line)
