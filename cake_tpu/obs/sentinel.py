"""Online performance-regression sentinel: rolling-window anomaly
detectors over the LIVE signal stream.

ROADMAP item 5's regression gate (tools/check_bench_round.py) catches
regressions one OFFLINE bench round late. The live telemetry the repo
already keeps — step records (obs/steps.py), SLO attainment
(obs/slo.py), the event bus (obs/events.py), the router's hop records
(cake_tpu/router/tracing.py) — is rich enough to detect the same
failure classes online, the way Sandwich (PAPERS.md #4) fits from live
signals: recompile storms, KV spill storms, shed storms, per-kind
step-time regressions against a self-calibrated baseline, per-class
attainment collapse, and router-tier per-replica TTFT / affinity
hit-rate skew.

Design rules:

  * **Detectors are pure and fake-clock testable.** A detector is fed
    (value, now) observations by `Sentinel.tick()` and answers with a
    fired/cleared transition or None; hysteresis (fire after N
    consecutive anomalous windows, clear after M consecutive clean
    ones) prevents flapping on a single noisy window. Tests drive
    `observe()` directly with synthetic windows.
  * **No new hot-path instrumentation.** Sources are closures over
    seams that ALREADY exist — the flight recorder ring, the event
    bus cursor, the SLO accountant's windows, the router's hop
    samples — read once per tick (seconds), never per token/step.
  * **Typed output.** A firing publishes one typed ``anomaly`` event
    (machine-readable cause + the evidence window) on the owning
    process's event bus, bumps ``cake_anomaly_total{kind}``, raises
    ``cake_anomaly_active{kind}``, and lands in the bounded anomaly
    ring served at ``GET /api/v1/anomalies`` (engine replicas AND the
    router front door). Clearing publishes the paired transition and
    drops the gauge.

Armed by ``--sentinel`` (args -> master -> engine; the router role
reads the same flag) with ``--sentinel-interval`` setting the tick
cadence; `attach_engine_sentinel` / `attach_router_sentinel` build the
standard detector sets from a live engine / RouterServer.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from cake_tpu.obs import metrics as _m

log = logging.getLogger(__name__)

ANOMALY_TOTAL = _m.counter(
    "cake_anomaly_total",
    "Anomalies fired by the online regression sentinel (--sentinel), "
    "by detector kind (obs/sentinel.py; each firing also publishes a "
    "typed 'anomaly' event carrying the machine-readable cause and "
    "evidence window)",
    labelnames=("kind",))
ANOMALY_ACTIVE = _m.gauge(
    "cake_anomaly_active",
    "1 while the named sentinel detector is in the fired state, 0 "
    "once its clear-hysteresis window passes clean",
    labelnames=("kind",))


@dataclass
class Observation:
    """One (value, time) sample a detector judged; the evidence
    window's unit."""

    t: float
    value: float
    anomalous: bool

    def to_dict(self) -> Dict:
        return {"t": round(self.t, 6),
                "value": round(float(self.value), 6),
                "anomalous": self.anomalous}


class Detector:
    """Hysteresis core shared by every detector flavor.

    `observe(value, now)` judges one windowed sample and returns a
    transition dict (`{"state": "fired"|"cleared", "cause": {...}}`)
    or None. Firing needs `fire_after` CONSECUTIVE anomalous samples;
    clearing needs `clear_after` consecutive clean ones — a single
    noisy window moves neither edge (the no-flap contract, pinned by
    unit test). Subclasses implement `judge(value) -> bool` and
    `describe() -> dict` (the machine-readable threshold block)."""

    def __init__(self, kind: str, *, fire_after: int = 2,
                 clear_after: int = 3, evidence: int = 32):
        if fire_after < 1 or clear_after < 1:
            raise ValueError("fire_after and clear_after must be >= 1")
        self.kind = kind
        self.fire_after = fire_after
        self.clear_after = clear_after
        self.active = False
        self._over = 0
        self._clean = 0
        self._evidence: deque = deque(maxlen=max(1, int(evidence)))

    # -- subclass surface --------------------------------------------------

    def judge(self, value: float) -> bool:
        raise NotImplementedError

    def describe(self) -> Dict:
        """Machine-readable threshold block ({"threshold": ...,
        "comparison": "above"|"below", ...})."""
        raise NotImplementedError

    # -- the one entry point ----------------------------------------------

    def observe(self, value: float, now: float) -> Optional[Dict]:
        anomalous = bool(self.judge(value))
        self._evidence.append(Observation(now, float(value), anomalous))
        if anomalous:
            self._over += 1
            self._clean = 0
        else:
            self._clean += 1
            self._over = 0
        if not self.active and self._over >= self.fire_after:
            self.active = True
            return {"state": "fired", "cause": self.cause(value)}
        if self.active and self._clean >= self.clear_after:
            self.active = False
            return {"state": "cleared", "cause": self.cause(value)}
        return None

    def cause(self, value: float) -> Dict:
        out = {"kind": self.kind, "value": round(float(value), 6)}
        out.update(self.describe())
        return out

    def evidence_window(self) -> List[Dict]:
        return [o.to_dict() for o in self._evidence]

    def state(self) -> Dict:
        return {"kind": self.kind, "active": self.active,
                "fire_after": self.fire_after,
                "clear_after": self.clear_after,
                **self.describe()}


class ThresholdDetector(Detector):
    """Fixed-threshold detector: anomalous when the windowed value
    crosses `threshold` in the `mode` direction (rates: recompiles /
    spills / sheds per window; fractions: attainment below target)."""

    def __init__(self, kind: str, threshold: float,
                 mode: str = "above", **kw):
        if mode not in ("above", "below"):
            raise ValueError(f"mode {mode!r} must be above or below")
        super().__init__(kind, **kw)
        self.threshold = float(threshold)
        self.mode = mode

    def judge(self, value: float) -> bool:
        return (value > self.threshold if self.mode == "above"
                else value < self.threshold)

    def describe(self) -> Dict:
        return {"threshold": self.threshold, "comparison": self.mode}


class BaselineDetector(Detector):
    """Self-calibrated detector: the first `calibrate_n` samples (never
    judged anomalous) fix a median baseline; afterwards a sample is
    anomalous when it exceeds `ratio x baseline` (mode "above" — e.g.
    step-time p95 regression) or falls below `ratio x baseline` (mode
    "below", ratio < 1 — e.g. affinity hit-rate collapse). min_baseline
    floors the calibrated value so microsecond-noise baselines cannot
    make every later sample read as a 3x regression."""

    def __init__(self, kind: str, ratio: float = 3.0,
                 calibrate_n: int = 6, mode: str = "above",
                 min_baseline: float = 0.0, **kw):
        if mode not in ("above", "below"):
            raise ValueError(f"mode {mode!r} must be above or below")
        if calibrate_n < 1:
            raise ValueError("calibrate_n must be >= 1")
        super().__init__(kind, **kw)
        self.ratio = float(ratio)
        self.calibrate_n = int(calibrate_n)
        self.mode = mode
        self.min_baseline = float(min_baseline)
        self.baseline: Optional[float] = None
        self._calib: List[float] = []

    def judge(self, value: float) -> bool:
        if self.baseline is None:
            self._calib.append(float(value))
            if len(self._calib) >= self.calibrate_n:
                xs = sorted(self._calib)
                mid = xs[len(xs) // 2] if len(xs) % 2 else (
                    (xs[len(xs) // 2 - 1] + xs[len(xs) // 2]) / 2.0)
                self.baseline = max(mid, self.min_baseline)
            return False
        bound = self.ratio * self.baseline
        return value > bound if self.mode == "above" else value < bound

    def describe(self) -> Dict:
        out = {"ratio": self.ratio, "comparison": self.mode,
               "calibrate_n": self.calibrate_n}
        if self.baseline is not None:
            out["baseline"] = round(self.baseline, 6)
            out["threshold"] = round(self.ratio * self.baseline, 6)
        else:
            out["calibrating"] = True
        return out


@dataclass
class Anomaly:
    """One fired detector transition held in the bounded ring."""

    kind: str
    fired_at: float                # wall clock
    cause: Dict
    evidence: List[Dict] = field(default_factory=list)
    cleared_at: Optional[float] = None

    def to_dict(self) -> Dict:
        out = {"kind": self.kind,
               "fired_at": round(self.fired_at, 6),
               "active": self.cleared_at is None,
               "cause": self.cause,
               "evidence": self.evidence}
        if self.cleared_at is not None:
            out["cleared_at"] = round(self.cleared_at, 6)
        return out


class Sentinel:
    """Detector orchestrator: one tick reads every registered source,
    feeds its detector, and turns transitions into anomaly records,
    metrics and typed bus events.

    `tick(now=None)` is the synchronous, fake-clock-friendly seam
    (bench and tests drive it directly); `start()` runs it on a daemon
    thread every `interval_s`. Sources are zero-arg callables returning
    the windowed value or None (no data this window — the detector is
    NOT fed: absence of traffic is not evidence either way). A source
    that raises is logged and skipped — the sentinel must never take
    serving down."""

    # cakelint guards discipline: the event bus is an optional plane
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, *, interval_s: float = 2.0, events=None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 capacity: int = 256, observe_metrics: bool = True):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.interval_s = float(interval_s)
        self._events = events
        self._clock = clock
        self._wall = wall
        self._observe = observe_metrics
        self._mu = threading.Lock()
        self._sources: List[tuple] = []
        self._active: Dict[str, Anomaly] = {}
        self._history: deque = deque(maxlen=max(1, int(capacity)))
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # transition listeners (obs/actions.py actuators): called as
        # fn(kind, state, cause) on the ticking thread AFTER the
        # anomaly ring / metrics / bus publish — a listener that raises
        # is logged and skipped (the sentinel never takes serving down)
        self._listeners: List[Callable] = []

    def add_listener(self, fn: Callable[[str, str, Dict], None]
                     ) -> "Sentinel":
        with self._mu:
            self._listeners.append(fn)
        return self

    def add(self, detector: Detector,
            source: Callable[[], Optional[float]]) -> "Sentinel":
        with self._mu:
            if any(d.kind == detector.kind for d, _ in self._sources):
                raise ValueError(
                    f"duplicate detector kind {detector.kind!r}")
            self._sources.append((detector, source))
        return self

    # -- the tick ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[Dict]:
        """Run every detector once; returns this tick's transitions
        (the bench smoke's assertion surface)."""
        now = self._clock() if now is None else now
        with self._mu:
            sources = list(self._sources)
            self._ticks += 1
        out: List[Dict] = []
        for det, src in sources:
            try:
                value = src()
            except Exception:  # noqa: BLE001 — telemetry never fails serving
                log.debug("sentinel source %s failed", det.kind,
                          exc_info=True)
                continue
            if value is None:
                continue
            tr = det.observe(float(value), now)
            if tr is not None:
                self._transition(det, tr)
                out.append({"kind": det.kind, **tr})
        return out

    def _transition(self, det: Detector, tr: Dict) -> None:
        wall_now = self._wall()
        if tr["state"] == "fired":
            rec = Anomaly(kind=det.kind, fired_at=wall_now,
                          cause=tr["cause"],
                          evidence=det.evidence_window())
            with self._mu:
                self._active[det.kind] = rec
                self._history.append(rec)
            if self._observe:
                ANOMALY_TOTAL.labels(kind=det.kind).inc()
                ANOMALY_ACTIVE.labels(kind=det.kind).set(1)
            log.warning("sentinel: anomaly fired: %s", tr["cause"])
        else:
            with self._mu:
                rec = self._active.pop(det.kind, None)
            if rec is not None:
                rec.cleared_at = wall_now
            if self._observe:
                ANOMALY_ACTIVE.labels(kind=det.kind).set(0)
            log.info("sentinel: anomaly cleared: %s", det.kind)
        if self._events is not None:
            self._events.publish("anomaly", state=tr["state"],
                                 **tr["cause"])
        with self._mu:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(det.kind, tr["state"], dict(tr["cause"]))
            except Exception:  # noqa: BLE001 — actuators never take us down
                log.warning("sentinel listener failed on %s %s",
                            det.kind, tr["state"], exc_info=True)

    # -- export (GET /api/v1/anomalies) -----------------------------------

    def state(self, limit: Optional[int] = None) -> Dict:
        with self._mu:
            active = [a.to_dict() for a in self._active.values()]
            hist = [a.to_dict() for a in reversed(self._history)]
            dets = [d.state() for d, _ in self._sources]
            ticks = self._ticks
        if limit is not None:
            hist = hist[:max(0, int(limit))]
        return {"active": active, "anomalies": hist,
                "detectors": dets, "ticks": ticks,
                "interval_s": self.interval_s}

    # -- baseline persistence (checkpoint snapshot, ISSUE 16) --------------

    def export_baselines(self) -> Dict[str, Dict]:
        """Calibrated BaselineDetector state, keyed by kind — the
        checkpoint snapshot carries this (informationally, outside the
        fingerprint) so a graceful restart does not spend calibrate_n
        windows re-learning what normal looks like. Only CALIBRATED
        detectors export; a mid-calibration sample list is not a
        baseline."""
        out: Dict[str, Dict] = {}
        with self._mu:
            sources = list(self._sources)
        for det, _ in sources:
            if (isinstance(det, BaselineDetector)
                    and det.baseline is not None):
                out[det.kind] = {"baseline": round(det.baseline, 9),
                                 "ratio": det.ratio, "mode": det.mode}
        return out

    def restore_baselines(self, baselines: Optional[Dict[str, Dict]]
                          ) -> int:
        """Adopt previously exported baselines into this sentinel's
        still-calibrating BaselineDetectors (matched by kind; a
        detector that already calibrated keeps its own — live evidence
        beats a snapshot). Mismatched mode or a non-positive value is
        skipped: a stale snapshot must never plant a baseline an
        empty-baseline firing would be judged against. Returns the
        number of detectors restored."""
        if not baselines:
            return 0
        restored = 0
        with self._mu:
            sources = list(self._sources)
        for det, _ in sources:
            if not isinstance(det, BaselineDetector):
                continue
            saved = baselines.get(det.kind)
            if not isinstance(saved, dict) or det.baseline is not None:
                continue
            try:
                value = float(saved["baseline"])
            except (KeyError, TypeError, ValueError):
                continue
            if value <= 0 or saved.get("mode", det.mode) != det.mode:
                continue
            det.baseline = max(value, det.min_baseline)
            det._calib = []
            restored += 1
        if restored:
            log.info("sentinel: restored %d calibrated baseline(s) "
                     "from snapshot", restored)
        return restored

    @property
    def active_count(self) -> int:
        with self._mu:
            return len(self._active)

    @property
    def fired_total(self) -> int:
        """Firings THIS sentinel saw (ring-bounded; bench phases read
        this per-instance view — cake_anomaly_total is process-global
        across sentinels)."""
        with self._mu:
            return len(self._history)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Sentinel":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="cake-sentinel")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — keep ticking
                log.debug("sentinel tick failed", exc_info=True)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- standard detector sets ---------------------------------------------------

# step kinds the per-kind step-time regression detectors watch: the
# decode-side kinds carry the throughput, prefill carries admission
# latency — a bounded set (obs/steps.py's vocabulary), so the
# cake_anomaly_* {kind} label stays bounded too
STEP_KINDS = ("decode", "decode_scan", "mixed", "spec", "prefill")


def _event_count_source(bus, type: str) -> Callable[[], Optional[float]]:
    """Events of `type` published since the previous tick (cursor-paged
    off the bus — the existing seam, no publisher changes)."""
    state = {"cursor": bus.cursor}

    def src() -> Optional[float]:
        evs, cursor = bus.snapshot(type=type, since=state["cursor"])
        state["cursor"] = cursor
        return float(len(evs))
    return src


class _FlightWindow:
    """ONE flight-ring snapshot per tick, shared by every flight-fed
    source (5 step kinds + recompile would otherwise each copy the
    whole ring every tick). The cursor starts at the ring's newest
    step AT ATTACH TIME, so a sentinel attached to an already-warm
    engine never counts pre-attach history as its first window (the
    event sources start at the bus cursor for the same reason).
    Sentinel.tick calls every registered source each tick, so the
    refresh cycles exactly once per `consumers` reads."""

    def __init__(self, flight):
        self._flight = flight
        recs = flight.dump(limit=1)
        self._cursor = recs[0]["step"] if recs else 0
        self._recs: List[Dict] = []
        self._reads = 0
        self.consumers = 1     # set after registration

    def _window(self) -> List[Dict]:
        if self._reads == 0:
            recs = self._flight.dump()
            newest = recs[0]["step"] if recs else self._cursor
            self._recs = [r for r in recs
                          if r["step"] > self._cursor]
            self._cursor = newest
        self._reads += 1
        if self._reads >= self.consumers:
            self._reads = 0
        return self._recs

    def p95_source(self, kind: str, min_samples: int = 5
                   ) -> Callable[[], Optional[float]]:
        """p95 dispatch-wall seconds of `kind` steps in this window,
        compiled dispatches excluded (their wall is XLA compile — the
        recompile detector owns those)."""
        def src() -> Optional[float]:
            walls = sorted(r["wall_s"] for r in self._window()
                           if r["kind"] == kind and not r["compiled"])
            if len(walls) < min_samples:
                return None
            return walls[min(len(walls) - 1, int(0.95 * len(walls)))]
        return src

    def recompile_source(self) -> Callable[[], Optional[float]]:
        """New-jit-signature dispatches in this window (the flight
        recorder's compiled flag — works with the event bus disabled
        too)."""
        def src() -> Optional[float]:
            return float(sum(1 for r in self._window()
                             if r["compiled"]))
        return src


def attach_engine_sentinel(engine, *, interval_s: float = 2.0,
                           step_ratio: float = 3.0,
                           recompile_threshold: float = 2.0,
                           spill_threshold: float = 16.0,
                           shed_threshold: float = 4.0,
                           attainment_floor: float = 0.5,
                           fire_after: int = 2,
                           clear_after: int = 3) -> Sentinel:
    """The engine-side standard detector set, fed entirely from
    existing seams (flight recorder, event bus, SLO accountant):

      * ``step_time:{kind}`` — per-kind step p95 vs a self-calibrated
        baseline (> step_ratio x baseline fires);
      * ``recompile_storm`` — new jit signatures per tick window
        (steady-state serving compiles nothing; a rise is a shape
        leak);
      * ``kv_spill_storm`` / ``shed_storm`` — kv_spill / shed events
        per tick window (needs the event bus);
      * ``attainment:{class}`` — rolling-1m SLO attainment below
        attainment_floor.
    """
    sen = Sentinel(interval_s=interval_s, events=engine.events)
    window = _FlightWindow(engine.flight)
    for kind in STEP_KINDS:
        sen.add(BaselineDetector(f"step_time:{kind}", ratio=step_ratio,
                                 min_baseline=1e-4,
                                 fire_after=fire_after,
                                 clear_after=clear_after),
                window.p95_source(kind))
    sen.add(ThresholdDetector("recompile_storm", recompile_threshold,
                              fire_after=fire_after,
                              clear_after=clear_after),
            window.recompile_source())
    window.consumers = len(STEP_KINDS) + 1
    if engine.events is not None:
        sen.add(ThresholdDetector("kv_spill_storm", spill_threshold,
                                  fire_after=fire_after,
                                  clear_after=clear_after),
                _event_count_source(engine.events, "kv_spill"))
        sen.add(ThresholdDetector("shed_storm", shed_threshold,
                                  fire_after=fire_after,
                                  clear_after=clear_after),
                _event_count_source(engine.events, "shed"))
    from cake_tpu.sched.classes import PRIORITY_CLASSES

    def _attainment_source(cls: str):
        def src() -> Optional[float]:
            return engine.slo.attainment_by_class("1m").get(cls)
        return src
    for cls in PRIORITY_CLASSES:
        sen.add(ThresholdDetector(f"attainment:{cls}",
                                  attainment_floor, mode="below",
                                  fire_after=fire_after,
                                  clear_after=clear_after),
                _attainment_source(cls))
    return sen


def attach_router_sentinel(router, *, interval_s: float = 2.0,
                           window_s: float = 30.0,
                           ttft_skew_ratio: float = 4.0,
                           hit_collapse_ratio: float = 0.5,
                           shed_threshold: float = 4.0,
                           min_samples: int = 4,
                           fire_after: int = 2,
                           clear_after: int = 3) -> Optional[Sentinel]:
    """The router-side standard detector set, fed from the hop
    tracer's rolling samples and the router event ring:

      * ``replica_ttft_skew`` — slowest replica's median first-byte
        latency over the fastest's (> ttft_skew_ratio fires): one
        degraded replica in an otherwise healthy fleet;
      * ``affinity_collapse`` — fleet affinity hit fraction vs its
        self-calibrated baseline (< hit_collapse_ratio x baseline
        fires): ring churn / a hot tenant overwhelming its home;
      * ``router_shed_storm`` — shed_by_router events per tick window.

    None when the hop tracer is disabled (trace_ring=0) — every
    detector here reads it."""
    if router.hops is None:
        log.warning("router sentinel disabled: the hop tracer is off "
                    "(trace_ring=0) and every router detector reads "
                    "its samples")
        return None
    hops = router.hops
    sen = Sentinel(interval_s=interval_s, events=router.events)

    def ttft_skew() -> Optional[float]:
        by_rep = hops.ttft_by_replica(window_s)
        meds = []
        for ttfts in by_rep.values():
            if len(ttfts) >= min_samples:
                xs = sorted(ttfts)
                meds.append(xs[len(xs) // 2])
        if len(meds) < 2 or min(meds) <= 0:
            return None
        return max(meds) / min(meds)

    def hit_fraction() -> Optional[float]:
        counts = hops.outcome_counts(window_s)
        denom = counts.get("hit", 0) + counts.get("spill", 0)
        if denom < min_samples:
            return None
        return counts.get("hit", 0) / denom

    sen.add(ThresholdDetector("replica_ttft_skew", ttft_skew_ratio,
                              fire_after=fire_after,
                              clear_after=clear_after), ttft_skew)
    sen.add(BaselineDetector("affinity_collapse",
                             ratio=hit_collapse_ratio, mode="below",
                             fire_after=fire_after,
                             clear_after=clear_after), hit_fraction)
    if router.events is not None:
        sen.add(ThresholdDetector("router_shed_storm", shed_threshold,
                                  fire_after=fire_after,
                                  clear_after=clear_after),
                _event_count_source(router.events, "shed_by_router"))
    return sen
