"""Fleet-scope telemetry federation: cross-host metric/event shipping.

Every subsystem PR 1-10 built measures ONE process. Under multi-host
serving the coordinator runs the API and publishes the op stream, but
the follower hosts — which dispatch every SPMD step, hold their own
slice of HBM, and can fail independently — were observability black
holes: no metrics, no events, no answer to "what was host B doing when
this request stalled". This module closes the process boundary:

  * **TelemetryExporter** (one per non-coordinator process): every
    ``interval_s`` it batches the process's LOCAL telemetry — the full
    obs/metrics registry (structured family export, histograms as
    cumulative buckets), the typed event-bus events published since the
    last frame (cursor-tracked, resent on a failed send), the step
    flight-recorder summary, the follower's last-APPLIED control-op
    seq, and a health snapshot — into one length-prefixed JSON frame
    and ships it over TCP to the coordinator. Same wire discipline as
    the control channel (serve/control.py): ints/floats/strings only,
    no pickle, and a token-gated hello so a rogue peer on the serving
    network can neither pose as a host nor read another host's frames.

  * **TelemetryCollector** (coordinator side): accepts exporter
    connections, validates the shared token within a bounded window,
    and ingests frames into per-host namespaced views. Every frame
    carries a ``(t_mono, t_wall)`` clock sample from the exporter; the
    collector keeps ``min(rx_wall - t_wall)`` over frames as the
    per-host clock offset (skew + the smallest observed transit time),
    uses the mono sample to DETECT remote wall-clock steps (the
    exporter's ``t_wall - t_mono`` is constant unless NTP stepped its
    clock — a step resets the stale min-offset), and adjusts remote
    event timestamps by the offset on read — so a merged request
    timeline (obs/timeline.py) stays wall-clock-ordered across hosts
    whose clocks disagree. The adjustment is bounded by the tightest
    frame's transit time: sub-transit orderings between hosts are not
    resolvable from this channel (README documents the caveat).

  * The collector feeds three consumer surfaces: ``GET /api/v1/fleet``
    (per-host liveness, last-export age, applied seq + lag vs the
    control server's published seq, device HBM gauges, health state),
    ``GET /api/v1/events?host=`` (a remote host's event stream), and
    ``render_federated()`` — remote metric families appended to the
    coordinator's /metrics exposition with a ``host`` label (families
    the coordinator also owns reuse its HELP/TYPE block; remote-only
    families bring their own).

Cost discipline: the exporter is one daemon thread with a bounded
frame cadence; a dead collector degrades to counted send errors and
reconnects — telemetry must never fail serving. The collector caps the
number of hosts at topology scale (``max_hosts``) so a misbehaving
peer cannot grow per-host state without bound.
"""

from __future__ import annotations

import hmac
import json
import logging
import math
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.metrics import _escape_label_value, _format_value
from cake_tpu.utils import wire as _wire

log = logging.getLogger(__name__)

# shared length-prefix framing (cake_tpu/utils/wire.py): ONE copy of
# the wire discipline for the control AND telemetry planes
_LEN = _wire.LEN
_send_frame = _wire.send_msg
FRAME_VERSION = 1
MAX_FRAME_BYTES = 32 << 20   # a full registry dump is ~100s of KB
MAX_HELLO_BYTES = 4096

# -- wire-plane metrics (exporter side) --------------------------------------
_EXPORTED_FRAMES = _m.counter(
    "cake_telemetry_exported_frames_total",
    "Telemetry frames this process shipped to the fleet collector "
    "(obs/federation.py TelemetryExporter)")
_EXPORT_ERRORS = _m.counter(
    "cake_telemetry_export_errors_total",
    "Telemetry frames that failed to ship (collector unreachable or "
    "send error) — the exporter reconnects and resends undelivered "
    "events on the next frame")
_TEL_BYTES = _m.counter(
    "cake_telemetry_bytes_total",
    "Telemetry federation wire bytes incl. the length prefix, by "
    "direction (tx = exporter frames out, rx = collector frames in)",
    labelnames=("dir",))
# -- wire-plane metrics (collector side) -------------------------------------
_INGESTED_FRAMES = _m.counter(
    "cake_telemetry_frames_total",
    "Telemetry frames ingested by the fleet collector, by origin host",
    labelnames=("host",))
_INGEST_LAG = _m.histogram(
    "cake_telemetry_ingest_lag_seconds",
    "Per-frame ingest lag: collector receipt time minus the frame's "
    "clock-offset-corrected build time (transit + queueing on the "
    "telemetry channel)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 5.0))
_FLEET_UP = _m.gauge(
    "cake_fleet_host_up",
    "1 when the host's last telemetry export is within the staleness "
    "window, 0 when it has gone quiet (GET /api/v1/fleet liveness)",
    labelnames=("host",))
_FLEET_AGE = _m.gauge(
    "cake_fleet_last_export_age_seconds",
    "Seconds since the host's last ingested telemetry frame",
    labelnames=("host",))
_FLEET_APPLIED = _m.gauge(
    "cake_fleet_applied_seq",
    "Last control-op seq the host reported as APPLIED in its telemetry "
    "frame (pair with cake_control_follower_lag_ops for the lag)",
    labelnames=("host",))
_FLEET_OFFSET = _m.gauge(
    "cake_fleet_clock_offset_seconds",
    "Estimated per-host wall-clock offset (min over frames of receipt "
    "time minus frame build time: skew + smallest observed transit) — "
    "the correction applied to remote event timestamps before merging "
    "timelines",
    labelnames=("host",))


def dump_registry(registry: Optional[_m.Registry] = None,
                  prefixes: Optional[Tuple[str, ...]] = None
                  ) -> List[Dict]:
    """Structured snapshot of every family in `registry` (default: the
    process-global REGISTRY) — the ``metrics`` section of a telemetry
    frame. `prefixes` optionally restricts to matching family names."""
    reg = registry if registry is not None else _m.REGISTRY
    out: List[Dict] = []
    for fam in reg.families():
        if prefixes and not fam.name.startswith(tuple(prefixes)):
            continue
        try:
            out.append(fam.export())
        except Exception:  # noqa: BLE001 — telemetry must never raise
            log.debug("family export failed: %s", fam.name,
                      exc_info=True)
    return out


class TelemetryExporter:
    """Non-coordinator side: ship this process's telemetry to the
    coordinator's TelemetryCollector as periodic JSON frames.

    address: "host:port" of the collector. host: this process's fleet
    id (proc1, ...). token: the shared control-channel secret (the
    collector rejects hellos without it). All content callables are
    best-effort — a raising supplier drops its section from the frame,
    never the frame. ``clock``/``mono`` are injectable for tests that
    simulate clock skew; the clock MUST be the same source the event
    bus stamps its events with, or the collector's offset correction
    would corrupt remote event ordering instead of fixing it."""

    # cakelint guards discipline: the event bus is optional (an
    # engine-less follower exports metrics/health only)
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, address: str, host: str,
                 token: Optional[str] = None,
                 interval_s: float = 2.0, *,
                 registry: Optional[_m.Registry] = None,
                 metric_prefixes: Optional[Tuple[str, ...]] = None,
                 events=None, flight=None,
                 applied_seq: Optional[Callable[[], int]] = None,
                 health_snapshot: Optional[Callable[[], Dict]] = None,
                 clock: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic,
                 connect_timeout_s: float = 30.0,
                 start: bool = True):
        peer_host, port = address.rsplit(":", 1)
        self._addr = (peer_host, int(port))
        self.host = str(host)
        self._token = token
        self._interval = max(0.01, float(interval_s))
        self._registry = registry
        self._prefixes = tuple(metric_prefixes) if metric_prefixes \
            else None
        self._events = events
        self._events_cursor = 0
        self._flight = flight
        self._applied = applied_seq
        self._health = health_snapshot
        self._clock = clock
        self._mono = mono
        self._connect_timeout = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._frame = 0
        self.frames_sent = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> "TelemetryExporter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"cake-telemetry-{self.host}")
            self._thread.start()
        return self

    # -- wire ---------------------------------------------------------------

    def _connect(self, timeout_s: Optional[float] = None,
                 ignore_stop: bool = False) -> bool:
        budget = (self._connect_timeout if timeout_s is None
                  else timeout_s)
        t0 = time.monotonic()
        last: Optional[Exception] = None
        while (time.monotonic() - t0 < budget
               and (ignore_stop or not self._stop.is_set())):
            try:
                s = socket.create_connection(self._addr, timeout=10.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                hello = json.dumps({
                    "v": FRAME_VERSION, "host": self.host,
                    "token": self._token or "",
                }).encode()
                _send_frame(s, hello)
                self._sock = s
                return True
            except OSError as e:
                last = e
                self._stop.wait(0.2)
        log.warning("telemetry exporter %s: collector unreachable at "
                    "%s:%s (%s)", self.host, *self._addr, last)
        return False

    def _call(self, fn):
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — drop the section, not the frame
            log.debug("telemetry supplier failed", exc_info=True)
            return None

    def _build_frame(self) -> Tuple[Dict, int]:
        """(frame, post-send events cursor). The cursor only advances
        after a SUCCESSFUL send, so events are resent, not dropped,
        across a collector blip."""
        evs: List[Dict] = []
        cursor = self._events_cursor
        if self._events is not None:
            try:
                evs, cursor = self._events.snapshot(
                    since=self._events_cursor)
            except Exception:  # noqa: BLE001
                log.debug("event snapshot failed", exc_info=True)
        try:
            # scrape-fresh device HBM gauges ride the registry dump, so
            # the coordinator's fleet view shows real follower memory
            from cake_tpu.obs.steps import refresh_device_gauges
            refresh_device_gauges()
        except Exception:  # noqa: BLE001
            pass
        frame = {
            "v": FRAME_VERSION,
            "host": self.host,
            "frame": self._frame + 1,
            "t_mono": self._mono(),
            "t_wall": self._clock(),
            "applied_seq": self._call(self._applied),
            "events": evs,
            "metrics": dump_registry(self._registry, self._prefixes),
            "steps": (self._flight.summary()
                      if self._flight is not None else None),
            "health": self._call(self._health),
        }
        return frame, cursor

    def flush(self, connect_timeout_s: Optional[float] = None,
              _ignore_stop: bool = False) -> bool:
        """Build and ship one frame NOW (synchronous; also the body of
        the periodic thread). False = the frame did not go out (the
        events cursor is kept, so nothing is lost)."""
        with self._send_lock:
            if self._sock is None and not self._connect(
                    connect_timeout_s, ignore_stop=_ignore_stop):
                _EXPORT_ERRORS.inc()
                return False
            frame, cursor = self._build_frame()
            payload = json.dumps(frame).encode()
            try:
                _send_frame(self._sock, payload)
            except OSError:
                _EXPORT_ERRORS.inc()
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                return False
            self._frame += 1
            self.frames_sent += 1
            self._events_cursor = cursor
            _EXPORTED_FRAMES.inc()
            _TEL_BYTES.labels(dir="tx").inc(_LEN.size + len(payload))
            return True

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("telemetry flush failed")

    def close(self, flush: bool = True) -> None:
        """Stop the export thread; by default ship one final frame so
        the collector sees the terminal applied seq (lag drains to 0
        on a clean shutdown). _stop is set FIRST: an in-flight
        periodic flush stuck in its connect-retry loop exits within
        one retry step instead of holding _send_lock for the full
        connect budget, and the terminal flush itself runs under a
        short bounded budget — teardown of a follower whose
        coordinator is already gone must not stall for a minute."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if flush:
            try:
                self.flush(connect_timeout_s=2.0, _ignore_stop=True)
            except Exception:  # noqa: BLE001
                pass
        with self._send_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class _HostView:
    """One exporter host's namespaced state on the collector."""

    __slots__ = ("host", "frames", "last_rx_mono", "last_rx_wall",
                 "offset", "wall_minus_mono", "applied_seq", "metrics",
                 "steps", "health", "events", "lags", "peer")

    def __init__(self, host: str, event_ring: int, peer: str):
        self.host = host
        self.frames = 0
        self.last_rx_mono = 0.0
        self.last_rx_wall = 0.0
        self.offset: Optional[float] = None
        # exporter-side (t_wall - t_mono): constant for a given remote
        # process unless its WALL clock steps (NTP) — the step detector
        # that invalidates a stale min-offset
        self.wall_minus_mono: Optional[float] = None
        self.applied_seq: Optional[int] = None
        self.metrics: List[Dict] = []
        self.steps: Optional[Dict] = None
        self.health: Optional[Dict] = None
        self.events: deque = deque(maxlen=max(1, int(event_ring)))
        self.lags: deque = deque(maxlen=512)
        self.peer = peer


class TelemetryCollector:
    """Coordinator side: accept exporter connections (token-gated, the
    ControlServer hello discipline: bounded hello size AND wall time),
    ingest frames into per-host views, and serve them to the fleet API
    + federated /metrics + cross-host timelines.

    control: an attached serve.control.ControlServer — applied seqs
    from telemetry frames feed its note_ack (the per-follower lag
    gauge + post-mortem acks), and its published_seq is the lag
    reference in fleet()."""

    def __init__(self, host: str = "", port: int = 0,
                 token: Optional[str] = None, *,
                 control=None, local_host: str = "proc0",
                 stale_after_s: float = 10.0, event_ring: int = 2048,
                 max_hosts: int = 64, hello_timeout_s: float = 10.0):
        self.token = token
        self._control = control
        self.local_host = local_host
        self._stale_after = float(stale_after_s)
        self._event_ring = int(event_ring)
        self._max_hosts = int(max_hosts)
        self._hello_timeout = float(hello_timeout_s)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((host, port))
            self._sock.listen(8)
        except OSError:
            self._sock.close()
            raise
        self._sock.settimeout(0.5)
        self._lock = threading.Lock()
        self._views: Dict[str, _HostView] = {}
        self._conns: List[socket.socket] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._serve, daemon=True,
            name="cake-telemetry-collector")
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    # -- accept/ingest ------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return   # closed
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._handle_conn, args=(conn, peer),
                daemon=True, name="cake-telemetry-conn").start()

    def _handle_conn(self, conn: socket.socket, peer) -> None:
        """_handle plus guaranteed cleanup: whatever path the handler
        exits through (rejected hello, EOF, oversized frame), the
        socket is closed AND removed from _conns — a flaky exporter
        reconnecting every few seconds must not grow the list for the
        life of the process."""
        try:
            self._handle(conn, peer)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _recv_hello(self, conn: socket.socket) -> Optional[Dict]:
        """Bounded hello read (cake_tpu/utils/wire.py: size-capped —
        an attacker-controlled multi-GiB length must not allocate —
        and deadline-capped — byte trickling must not hold a handler
        thread hostage)."""
        payload = _wire.recv_bounded_msg(
            conn, MAX_HELLO_BYTES,
            time.monotonic() + self._hello_timeout)
        if payload is None:
            return None
        try:
            hello = json.loads(payload)
        except ValueError:
            return None
        return hello if isinstance(hello, dict) else None

    def _handle(self, conn: socket.socket, peer) -> None:
        peer_s = "%s:%s" % peer[:2]
        hello = self._recv_hello(conn)
        host = str(hello.get("host") or "") if hello else ""
        if hello is None or not host or (
                self.token is not None and not hmac.compare_digest(
                    str(hello.get("token", "")).encode(),
                    self.token.encode())):
            log.warning("telemetry: rejected exporter %s (bad hello/"
                        "token)", peer_s)
            conn.close()
            return
        with self._lock:
            if host not in self._views:
                if len(self._views) >= self._max_hosts:
                    # topology-sized cap: per-host state (views, host-
                    # labeled series) must not grow unboundedly from a
                    # misbehaving peer inventing host names
                    log.warning(
                        "telemetry: rejecting host %r from %s — "
                        "max_hosts=%d reached", host, peer_s,
                        self._max_hosts)
                    conn.close()
                    return
                self._views[host] = _HostView(host, self._event_ring,
                                              peer_s)
            else:
                self._views[host].peer = peer_s
        log.info("telemetry: exporter %r connected from %s", host,
                 peer_s)
        conn.settimeout(1.0)
        rbuf = b""
        while not self._stop.is_set():
            try:
                part = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not part:
                break
            rbuf += part
            while len(rbuf) >= _LEN.size:
                (n,) = _LEN.unpack(rbuf[:_LEN.size])
                if n > MAX_FRAME_BYTES:
                    log.error("telemetry: oversized frame (%d bytes) "
                              "from %r; disconnecting", n, host)
                    conn.close()
                    return
                if len(rbuf) < _LEN.size + n:
                    break
                payload = rbuf[_LEN.size:_LEN.size + n]
                rbuf = rbuf[_LEN.size + n:]
                try:
                    self._ingest(host, payload)
                except Exception:  # noqa: BLE001 — one bad frame must
                    log.exception("telemetry ingest failed")  # not kill
        conn.close()
        log.info("telemetry: exporter %r disconnected", host)

    def _ingest(self, host: str, payload: bytes) -> None:
        rx_wall = time.time()
        rx_mono = time.monotonic()
        try:
            frame = json.loads(payload)
        except ValueError:
            log.warning("telemetry: unparseable frame from %r", host)
            return
        if not isinstance(frame, dict):
            return
        t_wall = frame.get("t_wall")
        applied = frame.get("applied_seq")
        with self._lock:
            view = self._views[host]
            view.frames += 1
            view.last_rx_mono = rx_mono
            view.last_rx_wall = rx_wall
            t_mono = frame.get("t_mono")
            if isinstance(t_wall, (int, float)):
                if isinstance(t_mono, (int, float)):
                    # (t_wall - t_mono) is constant for the remote
                    # process unless its wall clock STEPPED (NTP): on
                    # a >1s step, discard the stale min-offset so the
                    # estimate re-converges on the new epoch instead
                    # of pinning every future event to the old one
                    wmm = float(t_wall) - float(t_mono)
                    if (view.wall_minus_mono is not None
                            and abs(wmm - view.wall_minus_mono) > 1.0):
                        log.warning(
                            "telemetry: host %r wall clock stepped by "
                            "%.1fs; resetting its clock offset", host,
                            wmm - view.wall_minus_mono)
                        view.offset = None
                    view.wall_minus_mono = wmm
                delta = rx_wall - float(t_wall)
                # min over frames = skew + the smallest observed
                # transit: the tightest offset bound this channel can
                # produce (see the module docstring's caveat)
                view.offset = (delta if view.offset is None
                               else min(view.offset, delta))
            if isinstance(applied, int):
                view.applied_seq = applied
            if isinstance(frame.get("metrics"), list):
                view.metrics = frame["metrics"]
            if isinstance(frame.get("steps"), dict):
                view.steps = frame["steps"]
            if isinstance(frame.get("health"), dict):
                view.health = frame["health"]
            for ev in frame.get("events") or ():
                if isinstance(ev, dict):
                    view.events.append(dict(ev))
            offset = view.offset or 0.0
        _INGESTED_FRAMES.labels(host=host).inc()
        _TEL_BYTES.labels(dir="rx").inc(_LEN.size + len(payload))
        if isinstance(t_wall, (int, float)):
            lag = max(0.0, rx_wall - (float(t_wall) + offset))
            _INGEST_LAG.observe(lag)
            view.lags.append(lag)
            _FLEET_OFFSET.labels(host=host).set(round(offset, 6))
        if isinstance(applied, int):
            _FLEET_APPLIED.labels(host=host).set(applied)
            if self._control is not None:
                try:
                    self._control.note_ack(host, applied)
                except Exception:  # noqa: BLE001
                    log.debug("note_ack failed", exc_info=True)

    # -- read surfaces ------------------------------------------------------

    def hosts(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def ingest_lags(self, host: str) -> List[float]:
        """Recent per-frame ingest lags (seconds) for one host — the
        bench tier's p50/p99 source."""
        with self._lock:
            view = self._views.get(host)
            return list(view.lags) if view is not None else []

    def events_for(self, rid: Optional[int] = None,
                   host: Optional[str] = None,
                   type: Optional[str] = None,
                   since: Optional[int] = None,
                   limit: Optional[int] = None) -> List[Dict]:
        """Collector-held remote events, each tagged with its origin
        ``host`` and its ``ts`` corrected by that host's clock offset,
        merged in corrected wall-clock order. Filters: rid/type exact,
        host exact, since = strictly-greater per-host seq."""
        with self._lock:
            views = ([self._views[host]] if host in self._views
                     else [] if host is not None
                     else list(self._views.values()))
            items = [(v.host, v.offset or 0.0, list(v.events))
                     for v in views]
        out: List[Dict] = []
        for hname, off, evs in items:
            for ev in evs:
                if rid is not None and ev.get("rid") != rid:
                    continue
                if type is not None and ev.get("type") != type:
                    continue
                if since is not None and (ev.get("seq") or 0) <= since:
                    continue
                e = dict(ev)
                e["host"] = hname
                if isinstance(e.get("ts"), (int, float)):
                    e["ts"] = round(float(e["ts"]) + off, 6)
                out.append(e)
        out.sort(key=lambda e: (e.get("ts") or 0.0, e.get("seq") or 0))
        if limit is not None:
            out = out[:max(0, int(limit))]
        return out

    def events_page(self, host: str, rid: Optional[int] = None,
                    type: Optional[str] = None,
                    since: Optional[int] = None,
                    limit: Optional[int] = None):
        """(events, cursor) for ONE host's stream under the local
        EventBus.snapshot contract (obs/events.py): limit keeps the
        FIRST n matches — the page right after `since` — and a
        truncated page's cursor is the last RETURNED seq, so a
        ?since=cursor poll resumes where the page ended instead of
        skipping the truncated remainder forever; an un-truncated
        page's cursor is the host's newest held seq. The cursor-
        pagination invariant lives HERE, next to the data, not in the
        API layer."""
        evs = self.events_for(rid=rid, type=type, host=host,
                              since=since)
        truncated = limit is not None and len(evs) > max(0, int(limit))
        if limit is not None:
            evs = evs[:max(0, int(limit))]
        if not truncated:
            cursor = self.host_cursor(host)
        elif evs:
            cursor = max(e.get("seq") or 0 for e in evs)
        else:                      # limit=0: no progress was made
            cursor = since if since is not None else 0
        return evs, cursor

    def host_cursor(self, host: str) -> int:
        """Newest event seq held for `host` (0 = none) — the ?host=
        events endpoint's response cursor."""
        with self._lock:
            view = self._views.get(host)
            if view is None or not view.events:
                return 0
            return max((ev.get("seq") or 0) for ev in view.events)

    def published_seq(self) -> Optional[int]:
        if self._control is None:
            return None
        try:
            return self._control.published_seq
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _hbm_from_metrics(metrics: List[Dict]) -> Dict[str, Dict]:
        """Per-device HBM gauge values lifted out of a host's shipped
        metric dump — the fleet view's memory column."""
        fields = {
            "cake_device_hbm_bytes_in_use": "bytes_in_use",
            "cake_device_hbm_peak_bytes": "peak_bytes",
            "cake_device_hbm_bytes_limit": "bytes_limit",
        }
        out: Dict[str, Dict] = {}
        for fam in metrics:
            key = fields.get(fam.get("name"))
            if key is None:
                continue
            try:
                idx = list(fam.get("labels") or ()).index("device")
            except ValueError:
                continue
            for values, v in fam.get("samples") or ():
                dev = str(values[idx])
                out.setdefault(dev, {})[key] = v
        return out

    def refresh_gauges(self) -> None:
        """Scrape-time refresh of the per-host liveness/age gauges
        (api/server.py calls this before rendering /metrics)."""
        now = time.monotonic()
        with self._lock:
            views = list(self._views.values())
        for v in views:
            age = now - v.last_rx_mono if v.frames else float("inf")
            _FLEET_UP.labels(host=v.host).set(
                1 if age < self._stale_after else 0)
            if math.isfinite(age):
                _FLEET_AGE.labels(host=v.host).set(round(age, 3))

    def fleet(self) -> Dict:
        """The GET /api/v1/fleet body's remote half: per-host liveness,
        export age, applied seq + lag, clock offset, ingest lag, HBM
        gauges, health and step summaries."""
        self.refresh_gauges()
        now = time.monotonic()
        pub = self.published_seq()
        hosts: Dict[str, Dict] = {}
        with self._lock:
            views = list(self._views.values())
        for v in views:
            age = now - v.last_rx_mono if v.frames else None
            lag = None
            if pub is not None and isinstance(v.applied_seq, int):
                lag = max(0, pub - v.applied_seq)
            lags = sorted(v.lags)
            entry = {
                "role": "exporter",
                "peer": v.peer,
                "live": (age is not None
                         and age < self._stale_after),
                "frames": v.frames,
                "last_export_age_s": (round(age, 3)
                                      if age is not None else None),
                "applied_seq": v.applied_seq,
                "lag_ops": lag,
                "clock_offset_s": (round(v.offset, 6)
                                   if v.offset is not None else None),
                "events_held": len(v.events),
                "hbm": self._hbm_from_metrics(v.metrics),
            }
            if lags:
                entry["ingest_lag_p50_ms"] = round(
                    lags[len(lags) // 2] * 1e3, 3)
                entry["ingest_lag_p99_ms"] = round(
                    lags[min(len(lags) - 1,
                             int(len(lags) * 0.99))] * 1e3, 3)
            if v.health is not None:
                entry["health"] = v.health
            if v.steps is not None:
                entry["steps"] = {
                    k: v.steps.get(k)
                    for k in ("recorded_steps", "impl", "mfu",
                              "hbm_util") if k in v.steps}
            hosts[v.host] = entry
        return {"published_seq": pub,
                "stale_after_s": self._stale_after,
                "hosts": hosts}

    # -- federated /metrics --------------------------------------------------

    def render_federated(self, local_families=()) -> str:
        """Remote hosts' metric families as exposition text with a
        ``host`` label on every sample, appended after the local
        render. Families the coordinator also exposes locally reuse
        the local HELP/TYPE block (emitting a second one would be a
        duplicate-family violation); remote-only families bring their
        own. Returns "" when nothing is held."""
        local = set(local_families)
        # family name -> (type, help, [(host, fam_dict)]) — grouped so
        # a family exported by several hosts gets ONE HELP/TYPE block
        fams: Dict[str, List] = {}
        with self._lock:
            views = sorted(self._views.values(), key=lambda v: v.host)
            per_host = [(v.host, list(v.metrics)) for v in views]
        for hname, metrics in per_host:
            for fam in metrics:
                name = fam.get("name")
                typ = fam.get("type")
                if (not isinstance(name, str)
                        or not _m._NAME_RE.match(name)
                        or typ not in ("counter", "gauge", "histogram",
                                       "untyped")):
                    continue
                fams.setdefault(name, [typ, str(fam.get("help") or
                                                name), []])
                if fams[name][0] != typ:
                    # two hosts disagreeing on a family's type cannot
                    # be rendered under one TYPE line; keep the first
                    continue
                fams[name][2].append((hname, fam))
        lines: List[str] = []
        for name in sorted(fams):
            typ, help_, rows = fams[name]
            if name not in local:
                lines.append("# HELP %s %s"
                             % (name, help_.replace("\n", " ")))
                lines.append(f"# TYPE {name} {typ}")
            for hname, fam in rows:
                labels = [str(x) for x in (fam.get("labels") or ())]
                if typ == "histogram":
                    for child in fam.get("hist") or ():
                        self._render_hist(lines, name, labels, hname,
                                          child)
                else:
                    for values, v in fam.get("samples") or ():
                        suffix = self._suffix(labels, values, hname)
                        if isinstance(v, (int, float)):
                            lines.append(
                                f"{name}{suffix} {_format_value(v)}")
        return "\n".join(lines) + "\n" if lines else ""

    @staticmethod
    def _suffix(labels: List[str], values, host: str,
                extra: Tuple = ()) -> str:
        pairs = list(zip(labels, [str(v) for v in values]))
        pairs.append(("host", host))
        pairs.extend(extra)
        body = ",".join('%s="%s"' % (k, _escape_label_value(v))
                        for k, v in pairs)
        return "{" + body + "}"

    @classmethod
    def _render_hist(cls, lines: List[str], name: str,
                     labels: List[str], host: str,
                     child: Dict) -> None:
        """One histogram child as bucket/sum/count lines. A child with
        any malformed piece is dropped WHOLE — a partial bucket series
        (no +Inf, no _sum) would fail the exposition lint."""
        values = child.get("values") or ()
        buckets = child.get("buckets") or ()
        s, n = child.get("sum"), child.get("count")
        if not buckets or not (isinstance(s, (int, float))
                               and isinstance(n, (int, float))):
            return
        out: List[str] = []
        for pair in buckets:
            try:
                le, cum = pair
            except (TypeError, ValueError):
                return
            if not isinstance(cum, (int, float)):
                return
            suffix = cls._suffix(
                labels, values, host,
                extra=(("le", _format_value(
                    float(le) if le is not None else math.inf)),))
            out.append(f"{name}_bucket{suffix} {_format_value(cum)}")
        base = cls._suffix(labels, values, host)
        out.append(f"{name}_sum{base} {_format_value(s)}")
        out.append(f"{name}_count{base} {_format_value(n)}")
        lines.extend(out)

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=5.0)
