"""Shared append-only JSONL plumbing for the observability event logs.

One writer class serves both `--trace-events` (request-lifecycle spans,
obs/tracing.py) and `--step-log` (per-step flight records, obs/steps.py)
so the two logs cannot drift in durability semantics:

  * append-only, one `json.dumps` line per record, flushed per line (a
    crash loses at most the line being written);
  * fsync on close, so a clean shutdown's records are durable;
  * fail-open: the first OSError (full disk, revoked path) logs ONE
    warning and disables the writer — serving must never trade a token
    emit for a logging exception.

`read_jsonl` is the matching corrupt-tail-tolerant reader: a process
killed mid-write leaves a torn final line (or, after power loss, a
garbage tail), and resume-time parsing must shrug that off instead of
wedging on a JSONDecodeError. Undecodable lines are skipped, complete
records are returned.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

log = logging.getLogger(__name__)


class JsonlAppender:
    """Thread-safe append-only JSONL writer (lazy open, fail-open).

    The file is opened on the first append, so a process that never
    writes (e.g. a multi-host follower) never touches the path.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._file = None
        self._failed = False
        self._warned_unserializable = False

    @property
    def failed(self) -> bool:
        return self._failed

    def append(self, obj: Dict) -> int:
        """Write one record as one line. Returns the bytes written
        (line + newline — callers accounting journal growth need it
        without re-serializing), or 0 when the writer is disabled (a
        previous failure) or this write failed — so boolean tests
        keep working."""
        if self._failed:
            return 0
        try:
            line = json.dumps(obj)
        except (TypeError, ValueError):
            # warn ONCE per appender, like the OSError path: a non-JSON
            # field leaking into every record must not turn each append
            # (the token-emit hot path) into a logged warning
            if not self._warned_unserializable:
                self._warned_unserializable = True
                log.warning("jsonl: unserializable record(s) dropped "
                            "(%s); further drops are silent", self.path)
            return 0
        try:
            with self._lock:
                if self._file is None:
                    self._file = open(self.path, "a")
                self._file.write(line + "\n")
                self._file.flush()
            return len(line) + 1
        except OSError:
            # one warning, then disable: a full disk must not turn every
            # record into a logged exception
            self._failed = True
            log.warning("jsonl log disabled: cannot write %s", self.path,
                        exc_info=True)
            return 0

    def sync(self) -> bool:
        """fsync the open file (no-op before the first append, or after
        a failure). The durability knob behind the request journal's
        --journal-fsync batch/always modes (serve/journal.py): append()
        alone flushes to the OS, sync() makes it power-loss durable.
        Returns False when the writer is disabled or the fsync failed."""
        if self._failed:
            return False
        try:
            with self._lock:
                if self._file is None:
                    return True
                os.fsync(self._file.fileno())
            return True
        except OSError:
            self._failed = True
            log.warning("jsonl log disabled: cannot fsync %s", self.path,
                        exc_info=True)
            return False

    def close(self) -> None:
        """Flush + fsync + close: records written before a clean
        shutdown survive a power loss right after it."""
        with self._lock:
            f, self._file = self._file, None
        if f is None:
            return
        try:
            f.flush()
            os.fsync(f.fileno())
        except OSError:
            pass
        try:
            f.close()
        except OSError:
            pass


def read_jsonl(path: str, limit: Optional[int] = None) -> List[Dict]:
    """Read a JSONL log tolerantly: a torn tail (killed writer) or any
    other undecodable line is skipped, never raised — so log parsing at
    resume time cannot be wedged by the crash that made resume
    necessary. Returns complete records in file order (the last `limit`
    when set); a missing file reads as empty."""
    out: List[Dict] = []
    try:
        fh = open(path, "r", errors="replace")
    except OSError:
        return out
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue   # torn/corrupt line: skip, keep reading
            if isinstance(rec, dict):
                out.append(rec)
    if limit is not None:
        limit = int(limit)
        out = out[-limit:] if limit > 0 else []
    return out
