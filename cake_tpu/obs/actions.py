"""Closed-loop anomaly actions: typed, rate-bounded actuation with a
full audit trail, plus black-box postmortem bundles.

PR 15's sentinel (obs/sentinel.py) detects recompile storms, step-time
regressions, attainment collapse and replica TTFT skew — but only
*reports* them (ROADMAP item 5 named the fusion as the open gap). This
module closes the loop:

  * **ActionPlane** — the audit trail every actuator shares: a bounded
    action-history ring served through ``GET /api/v1/anomalies``, one
    typed ``anomaly_action`` event per action on the owning process's
    bus, the ``cake_anomaly_actions_total{kind,action,outcome}``
    counter, and a sliding-window rate bound so a flapping detector can
    never thrash configs or placement faster than
    ``max_per_min`` state changes a minute.
  * **EngineAnomalyActuator** — replica side: sentinel transitions
    become first-class AutotuneController signals
    (``note_anomaly``): a recompile storm or step-time regression
    HOLDS new policy switches while active (the window's signals are
    garbage), and — when the post-switch rollback guard is armed —
    pins the rollback verdict immediately from anomaly evidence
    instead of waiting out the timer window. The actual reconfigure
    still happens on the engine thread through the existing
    ``reconfigure()`` seam at the next autotune tick.
  * **RouterAnomalyActuator** — router side: TTFT-skew / shed-storm /
    affinity-collapse anomalies DE-WEIGHT the offending replica in
    RoutingPolicy placement (its effective load is divided by the
    weight, so traffic spills away) and automatically re-weight it
    when the anomaly clears. A de-weighted replica stays eligible —
    never ejected on a stale window — and a re-weighted replica gets a
    per-replica cooldown before it can be de-weighted again.
  * **PostmortemSink** — black-box forensics: on breaker-stop, poison,
    failed recovery or SIGTERM, dump one JSON bundle (recent step
    records, event ring, trace spans, anomaly + action history,
    metrics snapshot, journal tail) to ``--postmortem-dir``;
    ``tools/postmortem.py`` renders a bundle into a wall-clock-ordered
    narrative. Dumps are best-effort and interval-bounded — the sink
    runs on failure paths and must never take the process down (or
    write one bundle per poisoned request in a cascade).

Actuation is opt-in (``--sentinel-act``, ``--router-anomaly-weighting``,
``--postmortem-dir``): with the flags off nothing here is constructed
and behavior is byte-identical to PR 15 report-only (pinned by test).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from cake_tpu.obs import metrics as _m

log = logging.getLogger(__name__)

ACTIONS_TOTAL = _m.counter(
    "cake_anomaly_actions_total",
    "Closed-loop actions taken (or declined) in response to sentinel "
    "anomalies, by detector kind, action (hold / rollback / resume / "
    "deweight / reweight) and outcome (applied / noop / skipped / "
    "rate_limited) — obs/actions.py; armed by --sentinel-act / "
    "--router-anomaly-weighting, zero series in report-only mode",
    labelnames=("kind", "action", "outcome"))
POSTMORTEM_BUNDLES = _m.counter(
    "cake_postmortem_bundles_total",
    "Black-box postmortem bundles written to --postmortem-dir, by "
    "trigger (breaker_stop / reset_failed / poison / sigterm / "
    "engine_stop); tools/postmortem.py renders a bundle into a "
    "wall-clock narrative",
    labelnames=("trigger",))
POSTMORTEM_ERRORS = _m.counter(
    "cake_postmortem_errors_total",
    "Postmortem bundle writes that failed (the dump path never takes "
    "serving down — a failure is logged and counted, never raised)")

# actions that CHANGE state (a config switch, a placement weight) and
# therefore spend the ActionPlane's rate budget; holds, resumes and
# recovery re-weights are always free — the budget must never strand a
# de-weighted replica or let the controller keep switching on garbage
RATE_BOUND_ACTIONS = ("rollback", "deweight")


class ActionPlane:
    """Bounded audit trail + rate limiter shared by every anomaly
    actuator in one process. Thread-safe: actuators run on the sentinel
    thread, `history()`/`state()` on API handler threads."""

    # cakelint guards discipline: the event bus is an optional plane
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, *, events=None, capacity: int = 256,
                 max_per_min: int = 6,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 observe_metrics: bool = True):
        if max_per_min < 1:
            raise ValueError("max_per_min must be >= 1")
        self._events = events
        self._clock = clock
        self._wall = wall
        self._observe = observe_metrics
        self.max_per_min = int(max_per_min)
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._spent: deque = deque()   # monotonic stamps of rate-bound applies
        self._total = 0
        self._applied = 0

    def allow(self, now: Optional[float] = None) -> bool:
        """True while another rate-bound actuation fits the sliding
        one-minute budget (the bound ISSUE 16 promises: a flapping
        detector can propose, but cannot actuate, unboundedly)."""
        now = self._clock() if now is None else now
        with self._mu:
            while self._spent and now - self._spent[0] > 60.0:
                self._spent.popleft()
            return len(self._spent) < self.max_per_min

    def record(self, kind: str, action: str, outcome: str,
               **detail) -> Dict:
        """Append one action to the audit trail: ring + typed
        ``anomaly_action`` bus event + metrics. None-valued detail is
        dropped (callers pass optional context unconditionally)."""
        rec = {"t": round(self._wall(), 6), "kind": kind,
               "action": action, "outcome": outcome}
        rec.update({k: v for k, v in detail.items() if v is not None})
        with self._mu:
            self._ring.append(rec)
            self._total += 1
            if outcome == "applied":
                self._applied += 1
                if action in RATE_BOUND_ACTIONS:
                    self._spent.append(self._clock())
        if self._observe:
            ACTIONS_TOTAL.labels(kind=kind, action=action,
                                 outcome=outcome).inc()
        if self._events is not None:
            # only scalar detail rides the event (evidence dicts stay
            # in the ring — the bus is the timeline's merge feed)
            scal = {k: v for k, v in detail.items()
                    if isinstance(v, (str, int, float, bool))}
            self._events.publish("anomaly_action", kind=kind,
                                 action=action, outcome=outcome, **scal)
        return rec

    # -- export (GET /api/v1/anomalies "actions") -------------------------

    def history(self, limit: Optional[int] = None) -> List[Dict]:
        """Newest-first action records."""
        with self._mu:
            out = [dict(r) for r in reversed(self._ring)]
        if limit is not None:
            out = out[:max(0, int(limit))]
        return out

    @property
    def total(self) -> int:
        with self._mu:
            return self._total

    @property
    def applied_total(self) -> int:
        with self._mu:
            return self._applied

    def state(self, limit: Optional[int] = None) -> Dict:
        with self._mu:
            total, applied = self._total, self._applied
        return {"actions": self.history(limit), "total": total,
                "applied": applied, "max_per_min": self.max_per_min}


def _scalar_cause(cause: Dict) -> Dict:
    """The scalar slice of a detector cause (threshold/value/baseline),
    prefixed so action records never collide with their own keys."""
    out = {}
    for k in ("value", "threshold", "baseline", "ratio", "comparison"):
        v = cause.get(k)
        if isinstance(v, (str, int, float, bool)):
            out[f"cause_{k}"] = v
    return out


class EngineAnomalyActuator:
    """Replica-side closed loop: sentinel transitions -> autotune
    controller signals (--sentinel-act).

    Runs on the sentinel thread; `AutotuneController.note_anomaly` is
    thread-safe and only flips host-side intent — the resulting
    hold/rollback is consumed by `decide()` on the engine thread at the
    next autotune tick, so the reconfigure itself stays on the existing
    engine-thread `reconfigure()` seam."""

    def __init__(self, engine, plane: ActionPlane):
        self._engine = engine
        self._plane = plane

    def attach(self, sentinel) -> "EngineAnomalyActuator":
        sentinel.add_listener(self.on_transition)
        return self

    @staticmethod
    def actionable(kind: str) -> bool:
        """Config-plane evidence: a recompile storm or a step-time
        regression indicts the CURRENT config for the live shape mix;
        spill/shed/attainment anomalies have their own actuators
        (shedding, the host tier) and propose nothing here."""
        return kind == "recompile_storm" or kind.startswith("step_time:")

    def on_transition(self, kind: str, state: str, cause: Dict) -> None:
        if not self.actionable(kind):
            return
        at = getattr(self._engine, "_autotuner", None)
        if at is None:
            self._plane.record(kind, "hold" if state == "fired"
                               else "resume", "skipped",
                               reason="autotune disabled")
            return
        if state == "cleared":
            proposal = at.note_anomaly(kind, "cleared", cause)
            if proposal is not None:
                self._plane.record(kind, proposal, "applied",
                                   **_scalar_cause(cause))
            return
        # fired: a rollback (guard armed) is a config switch and spends
        # the rate budget; over budget it degrades to a plain hold —
        # holds are free (they PREVENT switches, never cause them)
        wants_switch = at.guard_armed
        allowed = self._plane.allow() if wants_switch else True
        proposal = at.note_anomaly(kind, "fired", cause,
                                   allow_switch=allowed)
        outcome = ("rate_limited"
                   if wants_switch and not allowed else "applied")
        self._plane.record(kind, proposal, outcome,
                           **_scalar_cause(cause))


# router anomaly kinds that indict one replica's placement weight
ROUTER_ACTION_KINDS = ("replica_ttft_skew", "affinity_collapse",
                       "router_shed_storm")


class RouterAnomalyActuator:
    """Router-side closed loop: sentinel transitions -> placement
    de-weighting (--router-anomaly-weighting).

    On fire, the offending replica's RoutingPolicy weight drops to
    `factor` (its effective load is divided by the weight, so affinity
    targets spill away and least-loaded stops picking it) — it stays
    ELIGIBLE, never ejected, so a stale window can at worst misplace
    load, not strand it. On clear, the weight is restored and the
    replica enters a `cooldown_s` window during which it cannot be
    de-weighted again (anti-flap, on top of the detectors' own
    fire/clear hysteresis)."""

    def __init__(self, router, plane: ActionPlane, *,
                 factor: float = 0.25, cooldown_s: float = 30.0,
                 window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < factor < 1.0:
            raise ValueError(f"factor {factor} must be in (0, 1)")
        self._router = router
        self._plane = plane
        self.factor = float(factor)
        self.cooldown_s = float(cooldown_s)
        self.window_s = float(window_s)
        self._clock = clock
        self._mu = threading.Lock()
        self._deweighted: Dict[str, str] = {}      # kind -> replica
        self._cooldown_until: Dict[str, float] = {}  # replica -> t

    def attach(self, sentinel) -> "RouterAnomalyActuator":
        sentinel.add_listener(self.on_transition)
        return self

    def _offender(self, kind: str) -> Optional[str]:
        """The replica this anomaly indicts. TTFT skew: the slowest
        median in the hop tracer's window. Shed storm / affinity
        collapse carry no replica in their cause — blame the most
        loaded admitting replica. None when the fleet has fewer than
        two admitting replicas: de-weighting the only destination just
        misroutes the accounting."""
        if kind == "replica_ttft_skew":
            hops = self._router.hops
            if hops is None:
                return None
            meds: Dict[str, float] = {}
            for name, ttfts in hops.ttft_by_replica(
                    self.window_s).items():
                if ttfts:
                    xs = sorted(ttfts)
                    meds[name] = xs[len(xs) // 2]
            if len(meds) < 2:
                return None
            return max(sorted(meds), key=lambda n: meds[n])
        states = self._router.tracker.admitting()
        if len(states) < 2:
            return None
        return max(states, key=lambda s: (s.load, s.name)).name

    def on_transition(self, kind: str, state: str, cause: Dict) -> None:
        if kind not in ROUTER_ACTION_KINDS:
            return
        policy = self._router.policy
        now = self._clock()
        if state == "fired":
            name = self._offender(kind)
            if name is None:
                self._plane.record(kind, "deweight", "noop",
                                   reason="no offender "
                                          "(need >= 2 admitting replicas)",
                                   **_scalar_cause(cause))
                return
            with self._mu:
                cooling = now < self._cooldown_until.get(
                    name, float("-inf"))
            if cooling:
                self._plane.record(kind, "deweight", "skipped",
                                   replica=name, reason="cooldown",
                                   **_scalar_cause(cause))
                return
            if not self._plane.allow(now):
                self._plane.record(kind, "deweight", "rate_limited",
                                   replica=name, **_scalar_cause(cause))
                return
            policy.set_weight(name, self.factor)
            with self._mu:
                self._deweighted[kind] = name
            self._plane.record(kind, "deweight", "applied",
                               replica=name, weight=self.factor,
                               **_scalar_cause(cause))
            return
        # cleared: restore the weight unless another active anomaly
        # still holds this replica down
        with self._mu:
            name = self._deweighted.pop(kind, None)
            held = name is not None and name in self._deweighted.values()
        if name is None:
            return
        if held:
            self._plane.record(kind, "reweight", "noop", replica=name,
                               reason="held by another anomaly")
            return
        policy.set_weight(name, 1.0)
        with self._mu:
            self._cooldown_until[name] = now + self.cooldown_s
        self._plane.record(kind, "reweight", "applied", replica=name,
                           weight=1.0, cooldown_s=self.cooldown_s)


def _best_effort(fn: Callable, what: str):
    """Collector wrapper for the postmortem path: a broken telemetry
    read costs one log line, never the bundle."""
    try:
        return fn()
    except Exception:  # noqa: BLE001 — forensics never raise
        log.debug("postmortem: %s collector failed", what,
                  exc_info=True)
        return None


def _journal_tail(path: Optional[str], n: int = 200) -> Optional[list]:
    if not path:
        return None
    try:
        with open(path, "rb") as f:
            # bounded read from the end: journals can be huge
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 256 * 1024))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return None
    out = []
    for ln in lines[-n:]:
        try:
            out.append(json.loads(ln))
        except ValueError:
            out.append({"raw": ln})
    return out


class PostmortemSink:
    """Black-box bundle writer (--postmortem-dir): one JSON file per
    terminal incident, holding every in-memory ring that explains WHY.
    Interval-bounded (one poison cascade writes one bundle, not
    hundreds) and best-effort end to end."""

    def __init__(self, dir_path: str, *, min_interval_s: float = 5.0,
                 wall: Callable[[], float] = time.time,
                 clock: Callable[[], float] = time.monotonic):
        self.dir = dir_path
        self.min_interval_s = float(min_interval_s)
        self._wall = wall
        self._clock = clock
        self._mu = threading.Lock()
        self._last_t: Optional[float] = None
        self._seq = 0
        try:
            os.makedirs(dir_path, exist_ok=True)
        except OSError:
            log.warning("postmortem: cannot create %s", dir_path,
                        exc_info=True)

    def dump(self, trigger: str, *, engine=None, router=None,
             reason: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write one bundle; returns its path, or None (interval-bound
        hit, or the write failed). `force=True` bypasses the interval
        bound — terminal triggers (breaker stop, SIGTERM) always leave
        a bundle even right after a poison dump."""
        now = self._clock()
        with self._mu:
            if (not force and self._last_t is not None
                    and now - self._last_t < self.min_interval_s):
                return None
            self._last_t = now
            self._seq += 1
            seq = self._seq
        try:
            bundle = self._collect(trigger, engine=engine,
                                   router=router, reason=reason)
            name = (f"postmortem-{int(bundle['wall_time'] * 1000)}"
                    f"-{seq:03d}-{trigger}.json")
            path = os.path.join(self.dir, name)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1, default=str)
                f.write("\n")
            os.replace(tmp, path)
            POSTMORTEM_BUNDLES.labels(trigger=trigger).inc()
            log.warning("postmortem: wrote %s (%s)", path, trigger)
            return path
        except Exception:  # noqa: BLE001 — forensics never raise
            POSTMORTEM_ERRORS.inc()
            log.warning("postmortem: bundle write failed (%s)", trigger,
                        exc_info=True)
            return None

    def _collect(self, trigger: str, *, engine=None, router=None,
                 reason: Optional[str]) -> Dict:
        bundle: Dict = {"version": 1, "trigger": trigger,
                        "wall_time": round(self._wall(), 6)}
        if reason is not None:
            bundle["reason"] = str(reason)
        src = engine if engine is not None else router
        if src is None:
            return bundle
        flight = getattr(src, "flight", None)
        if flight is not None:
            bundle["steps"] = _best_effort(
                lambda: flight.dump(limit=256), "flight")
        events = getattr(src, "events", None)
        if events is not None:
            bundle["events"] = _best_effort(
                lambda: events.dump(limit=512), "events")
        tracer = getattr(src, "tracer", None)
        if tracer is not None:
            bundle["traces"] = _best_effort(
                lambda: tracer.dump(limit=64), "tracer")
        hops = getattr(src, "hops", None)
        if hops is not None:
            bundle["hops"] = _best_effort(
                lambda: hops.dump(limit=64), "hops")
        sentinel = getattr(src, "sentinel", None)
        if sentinel is not None:
            bundle["anomalies"] = _best_effort(
                lambda: sentinel.state(limit=64), "sentinel")
        actions = (getattr(src, "_actions", None)
                   or getattr(src, "actions", None))
        if actions is not None:
            bundle["actions"] = _best_effort(
                lambda: actions.history(limit=128), "actions")
        stats = getattr(src, "stats", None)
        if stats is not None and dataclasses.is_dataclass(stats):
            bundle["stats"] = _best_effort(
                lambda: dataclasses.asdict(stats), "stats")
        journal = getattr(src, "_journal", None)
        if journal is not None:
            bundle["journal_tail"] = _best_effort(
                lambda: _journal_tail(getattr(journal, "path", None)),
                "journal")
        bundle["metrics"] = _best_effort(_m.REGISTRY.render, "metrics")
        return bundle
