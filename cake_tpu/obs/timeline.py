"""Per-request explain: one merged, time-ordered request timeline.

``GET /api/v1/requests/{rid}/timeline`` answers "why was this
request's TTFT 400ms?" from one call, by stitching the three telemetry
streams the repo already keeps into a single chronology:

  * the tracer's lifecycle spans (obs/tracing.py: admitted, queued,
    prefill, first_token, decode, preempted, requeued, kv_restored,
    crash_recovered, reconfigured, replayed — a cold-restart
    journal/checkpoint resume re-seeded this stream's history —
    retired/error/cancelled) — the request's own state machine;
  * the event bus (obs/events.py: preempted, kv_spill, kv_restore,
    prefix_hit, recovered, poisoned, reconfigured, shed, ...) — what
    the other subsystems DID to it, with their context fields;
  * the step flight recorder (obs/steps.py): the engine steps whose
    dispatched batch contained the request (records carry the rids of
    their rows), so stalls between spans are attributable to what the
    device was actually running — or compiling (``compiled: true``).

Everything here is a pure function over the three dumps, so tests
drive it on synthetic records; the engine method
(serve/engine.request_timeline) only gathers the inputs.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# event types that explain latency (the "causes" summary counts these
# between admission and first token — the TTFT attribution — and over
# the whole life for the e2e view)
ROUTER_CAUSE_TYPES = ("affinity_miss", "spill_to_secondary",
                      "failover_resume", "shed_by_router")
CAUSE_TYPES = ("preempted", "resident_spilled", "kv_spill",
               "kv_restore", "prefix_hit", "recovered", "poisoned",
               "reconfigured", "shed", "fault_injected",
               "recompile") + ROUTER_CAUSE_TYPES


def build_timeline(trace: Dict, events: List[Dict],
                   steps: Optional[List[Dict]] = None,
                   local_host: Optional[str] = None) -> Dict:
    """Merge one request's trace record (RequestTracer dump entry),
    its bus events (EventBus.dump(rid=...), plus any collector-held
    REMOTE events — obs/federation.py tags those with their origin
    ``host`` and corrects their timestamps by the per-host clock
    offset) and the step records whose batch contained it
    (StepTelemetry.records_for(rid)) into one time-ordered view with a
    cause summary.

    All inputs carry wall-clock timestamps (the tracer's spans are
    exported anchored to wall time; remote events arrive offset-
    corrected), so a plain sort merges them — one chronology even when
    the request's events span hosts; ties break trace-first (a span
    and the event it caused share a timestamp, and the state change
    reads better first). local_host names this process in the
    ``hosts`` summary when remote-origin events are present."""
    entries: List[Dict] = []
    for sp in trace.get("spans", ()):
        entries.append({"t": sp["t"], "source": "trace",
                        "event": sp["name"],
                        "offset_s": sp.get("offset_s")})
    for ev in events:
        e = {"t": ev.get("ts"), "source": "events",
             "event": ev.get("type")}
        e.update({k: v for k, v in ev.items()
                  if k not in ("ts", "type", "rid", "seq")})
        entries.append(e)
    for rec in steps or ():
        entries.append({
            "t": rec.get("ts"), "source": "steps",
            "event": f"step:{rec.get('kind')}",
            "step": rec.get("step"),
            "rows": rec.get("rows"),
            "wall_s": rec.get("wall_s"),
            "compiled": rec.get("compiled", False),
        })
    order = {"trace": 0, "events": 1, "steps": 2}
    entries.sort(key=lambda e: (e.get("t") or 0.0,
                                order.get(e["source"], 3)))

    first_token_t = next((sp["t"] for sp in trace.get("spans", ())
                          if sp["name"] == "first_token"), None)
    causes: Dict[str, int] = {}
    ttft_causes: Dict[str, int] = {}
    for ev in events:
        t = ev.get("type")
        if t not in CAUSE_TYPES:
            continue
        causes[t] = causes.get(t, 0) + 1
        if first_token_t is None or (ev.get("ts") or 0.0) <= first_token_t:
            ttft_causes[t] = ttft_causes.get(t, 0) + 1
    compile_steps = sum(1 for rec in steps or ()
                        if rec.get("compiled"))
    if compile_steps:
        causes["compiled_steps"] = compile_steps

    # fleet-scope requests: name every host that contributed events —
    # the local process first, then remote origins in name order
    remote_hosts = sorted({ev["host"] for ev in events
                           if ev.get("host")})
    hosts = None
    if remote_hosts:
        hosts = ([local_host] if local_host
                 and local_host not in remote_hosts else [])
        hosts += remote_hosts

    return {
        "rid": trace.get("rid"),
        "status": trace.get("status"),
        "priority": trace.get("priority"),
        "config_epoch": trace.get("config_epoch"),
        "summary": {
            "prompt_tokens": trace.get("prompt_tokens"),
            "output_tokens": trace.get("output_tokens"),
            "queue_wait_s": trace.get("queue_wait_s"),
            "ttft_s": trace.get("ttft_s"),
            "e2e_s": trace.get("e2e_s"),
            # what happened to this request, total and inside the
            # TTFT window — the one-glance attribution ("preempted
            # twice, prefix spilled then restored, folded by a
            # config switch")
            "causes": causes,
            "ttft_causes": ttft_causes,
            **({"hosts": hosts} if hosts else {}),
        },
        "timeline": entries,
    }


def merge_router_timeline(hop: Dict, router_events: List[Dict],
                          replicas: List[tuple]) -> Dict:
    """Merge one request's ROUTER-tier view — the front door's hop
    record (router/tracing.HopTracer dump entry: admit, pick + affinity
    verdict, connect, first byte, failover resume, retire) and its
    router event-ring events (selected by trace id) — with the owning
    replica(s)' merged timelines into ONE wall-clock-ordered chronology.

    `replicas` is [(name, clock_offset_s, rid, timeline_doc_or_None)]:
    one entry per replica that admitted this trace (BOTH replicas after
    a drain/kill failover). Each replica entry's timestamps are
    corrected by that replica's clock offset (the PR 11 federation
    rule: offset = min over health polls of receive-wall minus the
    replica's reported wall — skew plus the smallest observed transit)
    and tagged with its replica name. A replica whose timeline fetch
    failed (e.g. the killed home of a failover) contributes no spans
    but is still NAMED, with unreachable=true — the router hops cover
    its attempt either way.

    Pure function over dumps, like build_timeline: tests drive it on
    synthetic records; RouterServer.request_timeline only gathers the
    inputs."""
    entries: List[Dict] = []
    for sp in hop.get("spans", ()):
        e = {"t": sp.get("t"), "source": "router",
             "event": sp.get("name")}
        e.update({k: v for k, v in sp.items()
                  if k not in ("t", "name")})
        entries.append(e)
    for ev in router_events:
        e = {"t": ev.get("ts"), "source": "router-events",
             "event": ev.get("type")}
        e.update({k: v for k, v in ev.items()
                  if k not in ("ts", "type", "rid", "seq")})
        entries.append(e)

    causes: Dict[str, int] = {}
    replica_rows = []
    for name, offset_s, rid, doc in replicas:
        row: Dict = {"replica": name, "rid": rid,
                     "clock_offset_s": (round(offset_s, 6)
                                        if offset_s else 0.0)}
        if doc is None:
            # the replica is gone (killed home) or refused the fetch:
            # its attempt still reads from the router hops above
            row["unreachable"] = True
            replica_rows.append(row)
            continue
        row["status"] = doc.get("status")
        replica_rows.append(row)
        for e in doc.get("timeline", ()):
            e2 = dict(e)
            if e2.get("t") is not None:
                e2["t"] = e2["t"] + (offset_s or 0.0)
            e2["replica"] = name
            entries.append(e2)
        for k, v in (doc.get("summary", {}).get("causes") or {}).items():
            causes[k] = causes.get(k, 0) + int(v)
    for ev in router_events:
        t = ev.get("type")
        if t in ROUTER_CAUSE_TYPES:
            causes[t] = causes.get(t, 0) + 1

    # one chronology: wall-clock order; ties read router-first (the
    # front door observed the request before any replica did)
    order = {"router": 0, "router-events": 1, "trace": 2, "events": 3,
             "steps": 4}
    entries.sort(key=lambda e: (e.get("t") or 0.0,
                                order.get(e.get("source"), 5)))
    return {
        "trace": hop.get("trace"),
        "status": hop.get("status"),
        "priority": hop.get("class"),
        "hop": hop.get("hop"),
        "replicas": replica_rows,
        "summary": {
            "causes": causes,
            "attempts": len(hop.get("attempts", ()) or ()),
        },
        "timeline": entries,
    }
