"""Per-request explain: one merged, time-ordered request timeline.

``GET /api/v1/requests/{rid}/timeline`` answers "why was this
request's TTFT 400ms?" from one call, by stitching the three telemetry
streams the repo already keeps into a single chronology:

  * the tracer's lifecycle spans (obs/tracing.py: admitted, queued,
    prefill, first_token, decode, preempted, requeued, kv_restored,
    crash_recovered, reconfigured, replayed — a cold-restart
    journal/checkpoint resume re-seeded this stream's history —
    retired/error/cancelled) — the request's own state machine;
  * the event bus (obs/events.py: preempted, kv_spill, kv_restore,
    prefix_hit, recovered, poisoned, reconfigured, shed, ...) — what
    the other subsystems DID to it, with their context fields;
  * the step flight recorder (obs/steps.py): the engine steps whose
    dispatched batch contained the request (records carry the rids of
    their rows), so stalls between spans are attributable to what the
    device was actually running — or compiling (``compiled: true``).

Everything here is a pure function over the three dumps, so tests
drive it on synthetic records; the engine method
(serve/engine.request_timeline) only gathers the inputs.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# event types that explain latency (the "causes" summary counts these
# between admission and first token — the TTFT attribution — and over
# the whole life for the e2e view)
CAUSE_TYPES = ("preempted", "kv_spill", "kv_restore", "prefix_hit",
               "recovered", "poisoned", "reconfigured", "shed",
               "fault_injected", "recompile")


def build_timeline(trace: Dict, events: List[Dict],
                   steps: Optional[List[Dict]] = None,
                   local_host: Optional[str] = None) -> Dict:
    """Merge one request's trace record (RequestTracer dump entry),
    its bus events (EventBus.dump(rid=...), plus any collector-held
    REMOTE events — obs/federation.py tags those with their origin
    ``host`` and corrects their timestamps by the per-host clock
    offset) and the step records whose batch contained it
    (StepTelemetry.records_for(rid)) into one time-ordered view with a
    cause summary.

    All inputs carry wall-clock timestamps (the tracer's spans are
    exported anchored to wall time; remote events arrive offset-
    corrected), so a plain sort merges them — one chronology even when
    the request's events span hosts; ties break trace-first (a span
    and the event it caused share a timestamp, and the state change
    reads better first). local_host names this process in the
    ``hosts`` summary when remote-origin events are present."""
    entries: List[Dict] = []
    for sp in trace.get("spans", ()):
        entries.append({"t": sp["t"], "source": "trace",
                        "event": sp["name"],
                        "offset_s": sp.get("offset_s")})
    for ev in events:
        e = {"t": ev.get("ts"), "source": "events",
             "event": ev.get("type")}
        e.update({k: v for k, v in ev.items()
                  if k not in ("ts", "type", "rid", "seq")})
        entries.append(e)
    for rec in steps or ():
        entries.append({
            "t": rec.get("ts"), "source": "steps",
            "event": f"step:{rec.get('kind')}",
            "step": rec.get("step"),
            "rows": rec.get("rows"),
            "wall_s": rec.get("wall_s"),
            "compiled": rec.get("compiled", False),
        })
    order = {"trace": 0, "events": 1, "steps": 2}
    entries.sort(key=lambda e: (e.get("t") or 0.0,
                                order.get(e["source"], 3)))

    first_token_t = next((sp["t"] for sp in trace.get("spans", ())
                          if sp["name"] == "first_token"), None)
    causes: Dict[str, int] = {}
    ttft_causes: Dict[str, int] = {}
    for ev in events:
        t = ev.get("type")
        if t not in CAUSE_TYPES:
            continue
        causes[t] = causes.get(t, 0) + 1
        if first_token_t is None or (ev.get("ts") or 0.0) <= first_token_t:
            ttft_causes[t] = ttft_causes.get(t, 0) + 1
    compile_steps = sum(1 for rec in steps or ()
                        if rec.get("compiled"))
    if compile_steps:
        causes["compiled_steps"] = compile_steps

    # fleet-scope requests: name every host that contributed events —
    # the local process first, then remote origins in name order
    remote_hosts = sorted({ev["host"] for ev in events
                           if ev.get("host")})
    hosts = None
    if remote_hosts:
        hosts = ([local_host] if local_host
                 and local_host not in remote_hosts else [])
        hosts += remote_hosts

    return {
        "rid": trace.get("rid"),
        "status": trace.get("status"),
        "priority": trace.get("priority"),
        "config_epoch": trace.get("config_epoch"),
        "summary": {
            "prompt_tokens": trace.get("prompt_tokens"),
            "output_tokens": trace.get("output_tokens"),
            "queue_wait_s": trace.get("queue_wait_s"),
            "ttft_s": trace.get("ttft_s"),
            "e2e_s": trace.get("e2e_s"),
            # what happened to this request, total and inside the
            # TTFT window — the one-glance attribution ("preempted
            # twice, prefix spilled then restored, folded by a
            # config switch")
            "causes": causes,
            "ttft_causes": ttft_causes,
            **({"hosts": hosts} if hosts else {}),
        },
        "timeline": entries,
    }
