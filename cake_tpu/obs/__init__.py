"""Observability: metrics registry + request-lifecycle tracing.

The reference's only observability is a windowed worker ops/s log line
(worker.rs:254-283, SURVEY §5). This package gives the serving stack a
real measurement substrate, dependency-free:

  * `obs.metrics` — a Prometheus-style registry (`Counter`, `Gauge`,
    `Histogram`, all with label support) rendering the text exposition
    format; `ApiServer.metrics()` serves it at `/api/v1/metrics` and
    `/metrics`.
  * `obs.tracing` — per-request lifecycle traces: timestamped spans
    (admitted → queued → prefill → first_token → decode → retired /
    error / cancelled) with queue-wait, prefill seconds, TTFT,
    inter-token gaps and e2e latency, kept in a bounded ring, dumpable
    via `GET /api/v1/requests`, optionally streamed to a JSONL event
    log (`--trace-events PATH`).
  * `obs.steps` — step-level performance telemetry: a bounded step
    flight recorder (`GET /api/v1/steps`, `--step-log PATH` JSONL),
    XLA cost-analysis MFU / HBM-utilization accounting, jit-recompile
    counters, per-device HBM gauges, and the single-flight live
    profiler capture behind `POST /api/v1/profile`.
  * `obs.events` — the cross-subsystem event bus: typed,
    request-linked events (preempted, kv_spill/kv_restore, prefix_hit,
    recovered/poisoned, reconfigured, shed, fault_injected, recompile)
    in a bounded ring at `GET /api/v1/events` with an optional
    `--event-log` JSONL sink.
  * `obs.timeline` — the per-request explain: one merged time-ordered
    view of a request's trace spans, bus events and step records
    (`GET /api/v1/requests/{rid}/timeline`).
  * `obs.slo` — SLO attainment + goodput accounting (`--slo-targets`):
    rolling per-class attainment gauges, burn-rate counters, and
    goodput (tokens from requests that met their class SLO) feeding
    the autotune controller's quality signals.
  * `obs.sentinel` — the online performance-regression sentinel
    (`--sentinel`): rolling-window anomaly detectors with hysteresis
    over the live signal stream (step-time p95 vs self-calibrated
    baseline, recompile/spill/shed storms, attainment collapse,
    router replica skew), emitting typed `anomaly` events,
    `cake_anomaly_*` metrics and `GET /api/v1/anomalies`.
  * `obs.jsonl` — the shared append-only JSONL writer (fsync on close)
    and corrupt-tail-tolerant reader all three event logs use.
  * `obs.federation` — fleet-scope telemetry federation: each
    non-coordinator process runs a `TelemetryExporter` shipping its
    metrics/events/step summaries/applied control seq to the
    coordinator's `TelemetryCollector` (token-gated length-prefixed
    JSON frames with a clock sample), powering `GET /api/v1/fleet`,
    `?host=` event filters, host-labeled federated `/metrics`
    families, and cross-host request timelines.
"""

from cake_tpu.obs.events import EVENT_TYPES, Event, EventBus  # noqa: F401
from cake_tpu.obs.federation import (  # noqa: F401
    TelemetryCollector, TelemetryExporter,
)
from cake_tpu.obs.jsonl import JsonlAppender, read_jsonl  # noqa: F401
from cake_tpu.obs.metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, Registry, counter, gauge,
    histogram,
)
from cake_tpu.obs.sentinel import (  # noqa: F401
    BaselineDetector, Sentinel, ThresholdDetector,
    attach_engine_sentinel, attach_router_sentinel,
)
from cake_tpu.obs.slo import (  # noqa: F401
    DEFAULT_TARGETS, SLOAccountant, SLOTarget, parse_slo_targets,
)
from cake_tpu.obs.timeline import (  # noqa: F401
    build_timeline, merge_router_timeline,
)
from cake_tpu.obs.tracing import RequestTracer, TraceRecord  # noqa: F401
