"""Dependency-free Prometheus-style metrics registry.

Design contract (what tools/lint_metrics.py enforces on the output):

  * every metric family renders one `# HELP` and one `# TYPE` line
    followed by its samples;
  * metric names match ``[a-zA-Z_:][a-zA-Z0-9_:]*``, label names match
    ``[a-zA-Z_][a-zA-Z0-9_]*``; label values are escaped (backslash,
    double quote, newline);
  * histograms expose cumulative ``_bucket{le="..."}`` series ending in
    ``le="+Inf"``, plus ``_sum`` and ``_count``, with the +Inf bucket
    equal to ``_count`` — the standard scrape contract, so any
    Prometheus/Grafana stack ingests it unchanged.

Everything is thread-safe: handler threads, the engine thread and the
health sweeper all write concurrently. Metrics live in a process-global
default registry (`REGISTRY`) so the engine, the API layer and the
health monitor need no plumbing to share one exposition; the
``counter()``/``gauge()``/``histogram()`` helpers are get-or-create, so
repeated construction (tests, engine restarts) reuses the same family
instead of colliding.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# latency buckets (seconds) sized for LLM serving: sub-ms host work up
# through multi-minute long-context prefills
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _labels_suffix(labelnames: Tuple[str, ...],
                   labelvalues: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(labelnames, labelvalues)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


class _Child:
    """One label set's value cell. The parent holds the lock — children
    of one family share it, so cross-label reads (render) see a
    consistent snapshot."""

    def __init__(self, parent: "MetricFamily",
                 labelvalues: Tuple[str, ...]):
        self._parent = parent
        self._labelvalues = labelvalues
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    # -- counter ----------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0 and self._parent.typ == "counter":
            raise ValueError("counters only go up; use a Gauge")
        with self._parent._lock:
            self._value += amount

    def set_total(self, value: float) -> None:
        """Mirror an externally-maintained monotonic total (e.g.
        EngineStats counters synced at scrape time). Never moves the
        value backwards — a restarted engine's smaller total would
        otherwise break every rate() over the series."""
        with self._parent._lock:
            if value > self._value:
                self._value = float(value)

    # -- gauge ------------------------------------------------------------
    def set(self, value: float) -> None:
        with self._parent._lock:
            self._value = float(value)
            self._fn = None

    def dec(self, amount: float = 1.0) -> None:
        with self._parent._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate fn() at render time instead of storing a value
        (e.g. heartbeat staleness = now - last_seen)."""
        with self._parent._lock:
            self._fn = fn

    # -- shared ------------------------------------------------------------
    @property
    def value(self) -> float:
        with self._parent._lock:
            return self._read()

    def _read(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must never fail
                return float("nan")
        return self._value


class _HistogramChild:
    def __init__(self, parent: "Histogram",
                 labelvalues: Tuple[str, ...]):
        self._parent = parent
        self._labelvalues = labelvalues
        self._counts = [0] * (len(parent.buckets) + 1)  # +Inf last
        self._sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._parent._lock:
            self._sum += v
            for i, ub in enumerate(self._parent.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._parent._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._parent._lock:
            return self._sum


class MetricFamily:
    """Base: a named metric with optional labels. Unlabeled families
    proxy value methods to their single anonymous child."""

    typ = "untyped"
    _child_cls = _Child

    def __init__(self, name: str, help: str = "",
                 labelnames: Iterable[str] = (),
                 registry: Optional["Registry"] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help or name
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._child_cls(self, ())
        (registry if registry is not None else REGISTRY).register(self)

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(str(kw[ln]) for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}")
            if len(kw) != len(self.labelnames):
                raise ValueError(
                    f"unexpected labels for {self.name}: "
                    f"{sorted(set(kw) - set(self.labelnames))}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._child_cls(
                    self, values)
        return child

    def samples(self) -> Dict[Tuple[str, ...], float]:
        """Snapshot of label-values -> current value for every child
        (counters/gauges; histogram children, which have no scalar
        value, are omitted). The public read path for tools that walk a
        family's children without poking registry internals."""
        with self._lock:
            children = list(self._children.items())
        out: Dict[Tuple[str, ...], float] = {}
        for labelvalues, child in children:
            value = getattr(child, "value", None)
            if value is not None:
                out[labelvalues] = value
        return out

    def _single(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                ".labels(...) first")
        return self._children[()]

    def export(self) -> Dict:
        """Structured snapshot of this family — the telemetry
        federation wire shape (obs/federation.py): name/type/help/
        labelnames plus per-child samples. Counters and gauges ship
        `samples: [[labelvalues], value]`; histograms override this to
        ship cumulative buckets + sum + count, so a remote collector
        can re-render the family (with a host label) exactly as the
        local renderer would."""
        return {
            "name": self.name, "type": self.typ, "help": self.help,
            "labels": list(self.labelnames),
            "samples": [[list(lv), v]
                        for lv, v in self.samples().items()],
        }

    def collect(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.typ}"]
        with self._lock:
            children = list(self._children.items())
        for labelvalues, child in children:
            lines.extend(self._render_child(labelvalues, child))
        return lines

    def _render_child(self, labelvalues, child) -> List[str]:
        suffix = _labels_suffix(self.labelnames, labelvalues)
        return [f"{self.name}{suffix} {_format_value(child.value)}"]


class Counter(MetricFamily):
    typ = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._single().inc(amount)

    def set_total(self, value: float) -> None:
        self._single().set_total(value)

    @property
    def value(self) -> float:
        return self._single().value


class Gauge(MetricFamily):
    typ = "gauge"

    def set(self, value: float) -> None:
        self._single().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._single().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._single().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._single().set_function(fn)

    @property
    def value(self) -> float:
        return self._single().value


class Histogram(MetricFamily):
    typ = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name: str, help: str = "",
                 labelnames: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
                 registry: Optional["Registry"] = None):
        b = sorted(float(x) for x in buckets)
        if not b or b != sorted(set(b)):
            raise ValueError("buckets must be distinct and non-empty")
        if b and b[-1] == math.inf:
            b = b[:-1]           # +Inf is implicit
        self.buckets: Tuple[float, ...] = tuple(b)
        super().__init__(name, help, labelnames, registry)

    def observe(self, value: float) -> None:
        self._single().observe(value)

    @property
    def count(self) -> int:
        return self._single().count

    @property
    def sum(self) -> float:
        return self._single().sum

    def child_samples(self) -> Dict[Tuple[str, ...], Dict]:
        """{labelvalues: {"buckets": [(le, cumulative), ..., (inf, n)],
        "sum": s, "count": n}} — the histogram half of export():
        cumulative counts in increasing le order ending at +Inf, the
        exact series the text renderer emits."""
        with self._lock:
            children = list(self._children.items())
        out: Dict[Tuple[str, ...], Dict] = {}
        for lv, child in children:
            with self._lock:
                counts, s = list(child._counts), child._sum
            cum = 0
            buckets = []
            for ub, c in zip(self.buckets, counts):
                cum += c
                buckets.append((ub, cum))
            cum += counts[-1]
            buckets.append((math.inf, cum))
            out[lv] = {"buckets": buckets, "sum": s, "count": cum}
        return out

    def export(self) -> Dict:
        return {
            "name": self.name, "type": self.typ, "help": self.help,
            "labels": list(self.labelnames),
            "hist": [{"values": list(lv), **hs}
                     for lv, hs in self.child_samples().items()],
        }

    def _render_child(self, labelvalues, child) -> List[str]:
        lines = []
        with self._lock:
            counts, total_sum = list(child._counts), child._sum
        cum = 0
        for ub, c in zip(self.buckets, counts):
            cum += c
            suffix = _labels_suffix(self.labelnames, labelvalues,
                                    extra=(("le", _format_value(ub)),))
            lines.append(f"{self.name}_bucket{suffix} {cum}")
        cum += counts[-1]
        suffix = _labels_suffix(self.labelnames, labelvalues,
                                extra=(("le", "+Inf"),))
        lines.append(f"{self.name}_bucket{suffix} {cum}")
        base = _labels_suffix(self.labelnames, labelvalues)
        lines.append(f"{self.name}_sum{base} {_format_value(total_sum)}")
        lines.append(f"{self.name}_count{base} {cum}")
        return lines


class Registry:
    """Thread-safe metric family registry rendering the text exposition.
    Registration order is preserved (stable scrapes diff cleanly)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, MetricFamily] = {}

    def register(self, metric: MetricFamily) -> MetricFamily:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(
                    f"metric {metric.name!r} already registered; use "
                    "the counter()/gauge()/histogram() helpers for "
                    "get-or-create semantics")
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def families(self) -> List[MetricFamily]:
        """Registered families in registration order — the public walk
        for exporters (obs/federation.py ships every family's
        export()) and for callers that need the local family-name set
        without parsing the text exposition."""
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        with self._lock:
            families = list(self._metrics.values())
        lines: List[str] = []
        for fam in families:
            lines.extend(fam.collect())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


def _get_or_create(cls, name: str, help: str, labelnames, registry,
                   **kw):
    reg = registry if registry is not None else REGISTRY
    existing = reg.get(name)
    if existing is not None:
        if not isinstance(existing, cls):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{existing.typ}, not {cls.typ}")
        if existing.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{existing.labelnames}, not {tuple(labelnames)}")
        return existing
    return cls(name, help, labelnames=labelnames, registry=reg, **kw)


def counter(name: str, help: str = "", labelnames: Iterable[str] = (),
            registry: Optional[Registry] = None) -> Counter:
    return _get_or_create(Counter, name, help, tuple(labelnames),
                          registry)


def gauge(name: str, help: str = "", labelnames: Iterable[str] = (),
          registry: Optional[Registry] = None) -> Gauge:
    return _get_or_create(Gauge, name, help, tuple(labelnames), registry)


def histogram(name: str, help: str = "",
              labelnames: Iterable[str] = (),
              buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
              registry: Optional[Registry] = None) -> Histogram:
    return _get_or_create(Histogram, name, help, tuple(labelnames),
                          registry, buckets=buckets)
