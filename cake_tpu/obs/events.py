"""Cross-subsystem event bus: typed, request-linked serving events.

Five subsystems can delay or rewrite a request mid-flight — preemption
(cake_tpu/sched), KV spill/restore (cake_tpu/kv), crash recovery and
config hot-switches (serve/engine), and load shedding — but until this
module their telemetry was siloed per metric family: a counter says
*how many* requests were preempted, never *which* ones, so "why was
this request's TTFT 400ms?" was unanswerable from the API. The bus is
the request-linked complement: every subsystem publishes one typed
event per incident, carrying the rid where one exists, into a bounded
thread-safe ring served at ``GET /api/v1/events`` (filterable by
``?rid= / ?type= / ?since=`` cursor) and optionally appended as JSONL
(``--event-log``, the shared obs/jsonl.py writer). The per-request
explain endpoint (obs/timeline.py) stitches these events with the
tracer's lifecycle spans and the flight recorder's step records into
one time-ordered view.

Event vocabulary (typed: an unknown type raises at the publish site,
because a misspelled type would silently vanish from every ``?type=``
filter):

    preempted       a decoding slot was reclaimed for a higher class
    kv_spill        KV pages moved device -> host RAM
    kv_restore      KV pages streamed back host -> device
    prefix_hit      an admission reused a registered prefix's KV
    resident_spilled  a decode-RESIDENT stream was parked in the host
                    tier under admission pressure (pool
                    oversubscription; its page moves also publish
                    kv_spill)
    recovered       a crashed request was resubmitted via the fold
    poisoned        a request was quarantined as crash-implicated
    reconfigured    a live config switch folded/requeued the request
                    (one summary event with rid=None carries from/to)
    shed            admission rejected by per-class load shedding
    fault_injected  the --fault-plan chaos plane fired at a site
    recompile       a step fn compiled a new jit signature
    anomaly_action  the closed-loop action plane (obs/actions.py)
                    responded to a sentinel anomaly — carries the
                    detector kind, the action (hold / rollback /
                    deweight / reweight / resume) and its outcome

Cost discipline (the --fault-plan injector pattern): publishers hold
``events = None`` when the bus is disabled (``--event-ring 0``) and
every call site guards ``if <bus> is not None`` — the disabled plane
costs exactly one attribute test per site, pinned by a source-scan
test. Metrics stay rid-free by design: the bus carries rids, the
``cake_events_total{type}`` counter carries only the type (a rid-valued
label would grow one series per request — tools/lint_metrics.py bans
the label outright).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.jsonl import JsonlAppender

# the typed vocabulary — every publisher names one of these. The
# router-tier types (cake_tpu/router: the front-door process publishes
# into its OWN bus instance) and the sentinel's "anomaly"
# (obs/sentinel.py) share the vocabulary so ?type= filters and the
# timeline cause summary treat every tier identically; router events
# carry a `trace` field (the x-cake-trace id) instead of a rid — the
# router never knows the replica-local rid until admission.
EVENT_TYPES = (
    "preempted", "kv_spill", "kv_restore", "prefix_hit", "recovered",
    "poisoned", "reconfigured", "shed", "fault_injected", "recompile",
    # decode-resident spill under pool oversubscription
    # (serve/engine._spill_resident_stream)
    "resident_spilled",
    # router tier (cake_tpu/router/server.py)
    "affinity_miss", "spill_to_secondary", "failover_resume",
    "shed_by_router",
    # regression sentinel (obs/sentinel.py): fired/cleared transitions
    "anomaly",
    # closed-loop action plane (obs/actions.py): one typed audit event
    # per action taken (or declined) in response to an anomaly
    "anomaly_action",
    # fleet discovery (cake_tpu/router/discovery.py): replica
    # membership churn at the front door — a replica's first announce
    # frame registered it (replica_joined), its departure notice began
    # the drain-then-forget sequence (replica_departed), or its
    # announce stream went quiet and placement fell back to the poll
    # path (replica_stale)
    "replica_joined", "replica_departed", "replica_stale",
    # disaggregated prefill/decode (cake_tpu/kv/transfer.py): a
    # prefill host shipped a prefix's pool pages (kv_shipped), the
    # decode host adopted them into its own pool (kv_adopted), or the
    # shipment failed/expired and the request degraded to whole-prompt
    # prefill on the decode host (kv_ship_degraded)
    "kv_shipped", "kv_adopted", "kv_ship_degraded",
    # paged speculative decoding (cake_tpu/spec): one batched
    # draft+verify round's aggregate acceptance (spec_round, rid-less;
    # fault=True marks an injected spec.verify round), and the degrade
    # actions of the closed loop (spec_degraded: action="disabled"
    # carries the stream's rid + reason, action="shrink_gamma" is the
    # engine-wide tuner move)
    "spec_round", "spec_degraded",
)

EVENTS_TOTAL = _m.counter(
    "cake_events_total",
    "Serving events published on the cross-subsystem event bus, by "
    "event type (obs/events.py; rids ride the events themselves, "
    "never a metric label)",
    labelnames=("type",))
EVENTS_DROPPED = _m.counter(
    "cake_events_dropped_total",
    "Events evicted from the bounded in-memory event ring before being "
    "read (raise --event-ring, or attach --event-log for a lossless "
    "JSONL sink)")


@dataclass
class Event:
    """One published event. ``seq`` is the ring-wide monotonic cursor
    (GET /api/v1/events?since= pagination); ``ts`` is wall-clock so
    the timeline stitcher can merge events with tracer spans."""

    seq: int
    ts: float
    type: str
    rid: Optional[int] = None
    fields: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        out = {"seq": self.seq, "ts": round(self.ts, 6),
               "type": self.type}
        if self.rid is not None:
            out["rid"] = self.rid
        out.update(self.fields)
        return out


class EventBus:
    """Bounded, thread-safe ring of typed request-linked events.

    capacity bounds the in-memory ring (evictions count into
    cake_events_dropped_total); log_path additionally appends every
    event as one JSON line through the shared obs/jsonl.py writer
    (lazily opened, fsync on close, fail-open on OSError — a broken
    log file degrades to a logged warning, never a failed publish)."""

    # cakelint guards discipline: the JSONL appender and the trace-id
    # resolver are both optional attachments
    OPTIONAL_PLANES = ("_log", "trace_of")

    def __init__(self, capacity: int = 1024,
                 log_path: Optional[str] = None,
                 observe_metrics: bool = True):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._next_seq = 1
        self._log = JsonlAppender(log_path) if log_path else None
        self._observe = observe_metrics
        # optional rid -> trace-id resolver (RequestTracer.trace_for):
        # when the serving process sits behind the front-door router,
        # events published with a rid are annotated with the
        # originating x-cake-trace id so the router's federated
        # timeline can select them without knowing replica-local rids
        self.trace_of = None

    def publish(self, type: str, rid: Optional[int] = None,
                **fields) -> Event:
        """Append one event. Unknown types raise ValueError — a typo'd
        type would silently vanish from every ?type= filter, so the
        vocabulary is closed. None-valued fields are dropped (callers
        pass optional context unconditionally)."""
        if type not in EVENT_TYPES:
            raise ValueError(
                f"unknown event type {type!r} (obs/events.EVENT_TYPES)")
        ev = Event(seq=0, ts=time.time(), type=type,
                   rid=int(rid) if rid is not None else None,
                   fields={k: v for k, v in fields.items()
                           if v is not None})
        if (rid is not None and self.trace_of is not None
                and "trace" not in ev.fields):
            t = self.trace_of(int(rid))
            if t:
                ev.fields["trace"] = t
        with self._lock:
            ev.seq = self._next_seq
            self._next_seq += 1
            dropped = len(self._ring) == self._ring.maxlen
            self._ring.append(ev)
        if self._observe:
            EVENTS_TOTAL.labels(type=type).inc()
            if dropped:
                EVENTS_DROPPED.inc()
        if self._log is not None:
            self._log.append(ev.to_dict())
        return ev

    # -- export -----------------------------------------------------------

    def dump(self, rid: Optional[int] = None,
             type: Optional[str] = None,
             since: Optional[int] = None,
             limit: Optional[int] = None) -> List[Dict]:
        """Events in publish order (ascending seq); see snapshot()."""
        return self.snapshot(rid=rid, type=type, since=since,
                             limit=limit)[0]

    def snapshot(self, rid: Optional[int] = None,
                 type: Optional[str] = None,
                 since: Optional[int] = None,
                 limit: Optional[int] = None):
        """(events, cursor) in publish order (ascending seq). Filters
        compose: rid= exact, type= exact, since= strictly-greater seq.
        limit= keeps the FIRST n matches — the page right after
        `since`; keeping the newest would make a limited cursor poll
        skip the truncated older events forever. The cursor is safe to
        pass back as `since`: the last RETURNED seq when the page was
        truncated, else the ring's newest seq AT THE SNAPSHOT (events
        published after the snapshot stay strictly above it — nothing
        is ever skipped)."""
        with self._lock:
            evs = list(self._ring)
            snap_cursor = self._next_seq - 1
        out = []
        for ev in evs:
            if rid is not None and ev.rid != rid:
                continue
            if type is not None and ev.type != type:
                continue
            if since is not None and ev.seq <= since:
                continue
            out.append(ev.to_dict())
        truncated = limit is not None and len(out) > max(0, int(limit))
        if limit is not None:
            out = out[:max(0, int(limit))]
        if not truncated:
            cursor = snap_cursor
        elif out:
            cursor = out[-1]["seq"]
        else:                      # limit=0: no progress was made
            cursor = since if since is not None else 0
        return out, cursor

    @property
    def cursor(self) -> int:
        """Highest seq published so far (0 = nothing yet)."""
        with self._lock:
            return self._next_seq - 1

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
