"""CLI argument surface — capability parity with the reference's clap structs.

Reference: `Args` (cake-core/src/lib.rs:21-88), `SDArgs` (lib.rs:90-127),
`ImageGenerationArgs` (lib.rs:145-200), `ModelType` (lib.rs:14-19).

Defaults match the reference where sensible; the dtype default is **bfloat16**
instead of f16 (cake/mod.rs:54-60) because bf16 is the native TPU matmul type.
`ImageGenerationArgs` doubles as the REST image-request body, like the
reference's parallel clap/serde attributes (lib.rs:145-200, api/image.rs:15-18).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field, fields, asdict
from enum import Enum
from typing import Optional

# one source of truth for the quantized-KV-with-speculation config
# error: Args.validate raises it on the CLI path, master.make_engine
# raises it for programmatically-built Args that skipped validate()
INT8_KV_SPEC_ERROR = (
    "--kv-dtype int8/int4 is unavailable with --draft-model:"
    " the speculative engine is gated off the paged "
    "pool, so there are no KV pages to quantize")

# the quantized paged-pool storage names ("int8" = 1 byte/value,
# "int4" = two nibble-packed values/byte; cake_tpu/kv/quantized_pool)
QUANTIZED_KV_DTYPES = ("int8", "int4")


class ModelType(str, Enum):
    TEXT = "text"
    IMAGE = "image"


def parse_replicas(spec: str) -> list:
    """Validate + split a --replicas list: comma-separated host:port
    entries, no duplicates. One source of truth for Args.validate and
    the router builder (cli._serve_router)."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, sep, port = entry.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"--replicas entry {entry!r} must be host:port")
        try:
            p = int(port)
        except ValueError:
            raise ValueError(
                f"--replicas entry {entry!r}: port {port!r} is not an "
                "integer")
        if not 0 < p < 65536:
            raise ValueError(
                f"--replicas entry {entry!r}: port {p} out of range")
        out.append(entry)
    if not out:
        raise ValueError(f"--replicas {spec!r} names no replicas")
    if len(set(out)) != len(out):
        raise ValueError(f"--replicas {spec!r} has duplicate entries")
    return out


class SDVersion(str, Enum):
    V1_5 = "v1-5"
    V2_1 = "v2-1"
    XL = "xl"
    TURBO = "turbo"


@dataclass
class Args:
    """Process-wide configuration (reference lib.rs:21-88)."""

    model: str = ""                     # path to model directory
    model_type: ModelType = ModelType.TEXT
    mode: str = "master"                # master | worker (compat; TPU runs SPMD)
    name: str = ""                      # node name within the topology
    address: str = "127.0.0.1:10128"    # serving bind address
    api: Optional[str] = None           # REST bind address; None = one-shot CLI
    topology: Optional[str] = None      # topology.yml path
    prompt: str = "Why is the sky blue?"
    system_prompt: str = "You are a helpful AI assistant."
    seed: int = 299792458               # reference default (lib.rs)
    sample_len: int = 100
    temperature: float = 1.0
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    # None = "not set": resolves to the reference default 1.1
    # (llama.rs:311-320) for normal serving, and to 1.0 for speculative
    # serving (whose parallel verify cannot replay a penalty ring) — an
    # EXPLICIT value is honored (or rejected) everywhere
    repeat_penalty: Optional[float] = None
    repeat_last_n: int = 128
    dtype: str = "bf16"                 # f16 | bf16 | f32 (TPU default bf16)
    # KV-cache storage dtype; fp8 halves KV HBM traffic/footprint (values
    # upcast into the attention matmul on read). "int8"/"int4" select
    # the QUANTIZED paged pool (cake_tpu/kv): int8 or nibble-packed
    # int4 KV pages + per-page per-kv-head f32 scales, ~4x / ~8x the
    # resident decode streams per pool byte vs f32 — both require
    # --kv-pages (the page is the quantization unit; int4 additionally
    # needs an even --kv-page-size) and are a loud config error with
    # --draft-model (the spec engine is gated off the paged pool).
    # None = same as dtype.
    kv_dtype: Optional[str] = None      # + f8_e4m3 | f8_e5m2 | int8 | int4
    cpu: bool = False
    device_idx: int = 0
    max_seq_len: int = 4096             # reference hard constant (config.rs:6); tunable here
    batch_size: int = 1
    max_slots: int = 8                  # continuous-batching decode slots (API serving)
    # parallelism knobs (TPU additions; reference has PP only)
    tp: int = 1                         # tensor-parallel degree
    dp: int = 1                         # data-parallel degree
    sp: int = 1                         # sequence/context-parallel degree
    microbatches: int = 1               # GPipe microbatches per pipeline step
                                        # (1 = reference depth-1 behavior)
    # prefill prompts in fixed windows of N tokens (one compiled program
    # for every prompt length; cache-aware flash attention per chunk);
    # None = whole-prompt prefill with bucketed shapes. Applies to the
    # paged (--kv-pages) engine too: windows scatter into the slot's
    # pages at any offset (models/llama/paged.prefill_slot_paged_chunk)
    prefill_chunk: Optional[int] = None
    # engine: when no request is queued, decode N tokens per host
    # round-trip as one on-device scan (amortizes dispatch latency);
    # 1 = step-by-step
    decode_scan: int = 1
    # Pallas flash attention for LLM prefill; None = auto (on when the
    # backend is a real TPU, off on CPU where interpret mode is slow)
    flash_attention: Optional[bool] = None
    # profile generation to this directory (jax.profiler; view in
    # TensorBoard or ui.perfetto.dev) — LLM-path analog of --sd-tracing
    tracing: Optional[str] = None
    # engine checkpoint file: restore in-flight requests on startup, save
    # on shutdown (serve/checkpoint.py; the reference has no runtime
    # checkpointing, SURVEY.md §5)
    checkpoint: Optional[str] = None
    # weight quantization (ops/quant.py): "int8" halves decode HBM traffic
    # (weight-only per-channel), "int4" quarters it (group-wise, dense
    # models only); "none" keeps args.dtype weights
    quant: str = "none"
    # speculative decoding (models/llama/speculative.py): path to a small
    # draft model sharing the target's tokenizer; each target pass then
    # verifies spec_gamma drafted tokens at once. Batch-1, single-device.
    draft_model: Optional[str] = None
    spec_gamma: int = 4
    # PAGED speculative decoding (cake_tpu/spec): path to a small draft
    # model whose KV rides a second paged pool behind the engine's one
    # page allocator — spec becomes a row KIND of the mixed ragged step
    # (many streams speculate concurrently) instead of the dense
    # batch-engine above. Requires --kv-pages + f32/bf16 KV; shares
    # --spec-gamma. Mutually exclusive with --draft-model.
    spec_draft: Optional[str] = None
    # batch-1 CLI speculation: propose-verify rounds chained on device
    # per host fetch (spec_scan); the engine path batches across slots
    # instead and ignores this
    spec_rounds: int = 4
    # serving watchdog: fail (recoverably) when the engine makes no
    # progress for this many seconds with active requests; must exceed
    # the worst-case first-request compile time (parallel/health.py)
    stall_timeout: float = 600.0
    # multi-host serving: fail when a follower's heartbeat lapses this
    # many seconds (parallel/health.HeartbeatMonitor stale window) —
    # pre-fail snapshot + 503s instead of a wedged collective
    heartbeat_timeout: float = 15.0
    # --auto-prefix: the API engine KV-caches each distinct system
    # prompt's rendered head once (serve/engine.register_prefix), so
    # conversations sharing it prefill only their own turns. On the
    # paged (--kv-pages) engine the head is rounded down to a page
    # boundary and its pages are mapped READ-ONLY into every matching
    # slot's table row (page-granular prefix sharing: one copy in the
    # pool, refcounted, however many slots share it)
    auto_prefix: bool = False
    # --kv-pages N: paged KV for the serving engine — KV lives in a pool
    # of N pages of --kv-page-size tokens; slot admission is gated by
    # free pages, so resident KV is bounded by the pool instead of
    # max_slots x max_seq_len (models/llama/paged.py). Composes with
    # --auto-prefix (shared prefix pages) and --prefill-chunk (windowed
    # paged prefill)
    kv_pages: Optional[int] = None
    kv_page_size: int = 128
    # --paged-attn: attention impl for the paged (--kv-pages) engine —
    # "pallas" = the ragged paged-attention TPU kernel
    # (ops/ragged_paged_attention.py), "fold" = the XLA online-softmax
    # fold over all pages (the reference semantics; use for debugging
    # or non-TPU backends); "auto" = pallas on TPU, fold elsewhere
    paged_attn: str = "auto"
    # --mixed-batch: token-level continuous batching for the paged
    # (--kv-pages) engine — ONE jitted mixed step processes decode rows
    # and prefill-chunk rows together (per-row query-length metadata in
    # the ragged paged-attention kernel), so a new request's chunks
    # join the very next step instead of waiting for a decode pause.
    # "auto" = on for paged serving, off elsewhere; "on" without
    # --kv-pages is a config error; "off" keeps the phase-split loop
    mixed_batch: str = "auto"
    # --kv-host-pages N: host-RAM spill tier for the paged pool
    # (cake_tpu/kv/host_tier.py) — preemption victims' pages and cold
    # shared-prefix pages spill to pinned host memory (LRU, capacity N
    # pages) and stream back on demand, so a resumed victim decodes
    # from where it stopped instead of re-prefilling and a cold prefix
    # re-maps instead of recomputing. Applies to --kv-pages serving
    # only (the page is the spill unit)
    kv_host_pages: Optional[int] = None
    # --trace-events PATH: append every request-lifecycle span as one
    # JSON line (obs/tracing.py) — the replayable audit log behind the
    # in-memory ring served at GET /api/v1/requests
    trace_events: Optional[str] = None
    # --trace-ring N: finished request traces retained in memory for
    # GET /api/v1/requests
    trace_ring: int = 256
    # --step-log PATH: append one JSON line per engine step (the
    # obs/steps.py flight recorder: kind, occupancy, tokens, dispatch
    # wall, MFU/HBM utilization, page-pool state) — the step-level
    # audit log behind GET /api/v1/steps
    step_log: Optional[str] = None
    # --step-ring N: step flight-recorder records retained in memory
    # for GET /api/v1/steps
    step_ring: int = 512
    # --event-log PATH: append every cross-subsystem serving event
    # (preempted, kv_spill/kv_restore, prefix_hit, recovered/poisoned,
    # reconfigured, shed, fault_injected, recompile — obs/events.py)
    # as one JSON line; the lossless sink behind the bounded ring at
    # GET /api/v1/events
    event_log: Optional[str] = None
    # --event-ring N: events retained in memory for GET /api/v1/events;
    # 0 disables the event bus entirely (every publish site is then one
    # attribute test, the --fault-plan discipline)
    event_ring: int = 1024
    # --slo-targets SPEC: per-class latency SLOs for attainment +
    # goodput accounting (obs/slo.py) —
    # "interactive=ttft:0.1,e2e:2;standard=ttft:1,e2e:30;..." names a
    # class's TTFT / e2e targets in seconds; unnamed classes keep the
    # defaults. Drives cake_slo_attainment{class,window},
    # cake_slo_*_total burn-rate counters and
    # cake_goodput_tokens_total{class}, and the autotuner's
    # quality-aware policy lookup
    slo_targets: Optional[str] = None
    # --profile-dir DIR: where POST /api/v1/profile writes its
    # jax.profiler capture; None = a fresh temp dir per capture
    profile_dir: Optional[str] = None
    # --priority-classes: SLO-aware scheduling (cake_tpu/sched/) for
    # the serving engine — requests carry a class (interactive |
    # standard | batch, via the request-body "priority" field or the
    # x-cake-priority header) and plan() admits by class with
    # anti-starvation aging instead of FIFO arrival order
    priority_classes: bool = False
    # --preemption / --no-preemption: recompute-style preemption
    # (requires --priority-classes): when a higher class is slot- or
    # page-starved, the youngest lowest-class decoding slot is
    # preempted — its generated tokens fold into its prompt (the
    # checkpoint-resume fold), its kv pages release through the
    # refcounted allocator, and it requeues to re-prefill later, with a
    # per-request preemption budget guaranteeing progress. None = auto
    # (on whenever --priority-classes is on and the engine flavor
    # supports the fold)
    preemption: Optional[bool] = None
    # --shed: per-class load shedding — admission probability derived
    # from the measured service rate and queue depth; rejected requests
    # surface as HTTP 429 with an honest computed Retry-After
    # (cake_tpu/sched/shed.py)
    shed: bool = False
    # --fault-plan SPEC: deterministic fault injection (cake_tpu/faults)
    # — "seed=N;site:trigger:error[:opts];..." names where/when/what
    # the serving stack should fail (e.g.
    # "seed=7;engine.decode:nth=12:transient"), so every chaos
    # experiment is reproducible from its command line. Sites cover
    # engine step dispatch, the control channel, the host KV tier and
    # the page allocator; unset = the plane is a no-op.
    fault_plan: Optional[str] = None
    # --recovery / --no-recovery: crash recovery for the serving
    # engine — on a step failure, reset and RESUBMIT in-flight
    # requests via the checkpoint fold-tokens-into-prompt path
    # instead of failing them all; repeatedly-implicated requests are
    # quarantined as poison, and a reset storm trips a breaker
    # (snapshot + clean stop). None = auto: on wherever the fold works
    # (off for speculative and windowed serving)
    recovery: Optional[bool] = None
    # --autotune {off,manual,auto}: live engine-config hot-switching
    # (cake_tpu/autotune). "manual" arms POST /api/v1/autotune (an
    # operator switches slots/decode-scan/kv-pages/kv-dtype/
    # mixed-batch/paged-attn under load: in-flight streams fold their
    # generated tokens into their prompts — the checkpoint-resume fold
    # — and requeue with seniority/class preserved, token-identical at
    # f32 KV); "auto" additionally runs the policy controller: an
    # offered-load regime -> config table (--autotune-policy, fitted
    # offline by tools/autotune_fit.py) consulted over sliding-window
    # signals with hysteresis, cooldown and a one-shot rollback guard
    autotune: str = "off"
    # --autotune-policy PATH: the piecewise policy table for --autotune
    # auto (JSON: {"version": 1, "regimes": [{"max_offered_rps": ...,
    # "config": {...}}, ...]}; cake_tpu/autotune/search.py)
    autotune_policy: Optional[str] = None
    # --journal PATH: write-ahead request journal (serve/journal.py) —
    # one record per admission, one per emitted-token batch, retire
    # tombstones. On startup the journal (plus the --checkpoint base
    # when both are set) replays every non-retired request through the
    # fold-tokens-into-prompt path, so a hard process death (SIGKILL,
    # OOM-kill, power) between snapshots loses no stream; greedy
    # continuations are token-identical at f32 KV. Composes with
    # idempotency keys + SSE Last-Event-ID resume so clients re-attach
    # across the restart.
    journal: Optional[str] = None
    # --journal-fsync {never,batch,always}: journal durability —
    # "never" flushes per line (process death loses nothing, machine
    # death may lose recent records), "batch" (default) fsyncs once
    # per engine iteration, "always" fsyncs every append
    journal_fsync: str = "batch"
    # --telemetry-export / --no-telemetry-export: fleet telemetry
    # federation (obs/federation.py) — every non-coordinator process
    # ships its metrics / event-bus events / step summaries / applied
    # control-op seq to a coordinator-side collector, powering
    # GET /api/v1/fleet, ?host= event filters, host-labeled federated
    # /metrics families and cross-host request timelines. None = auto
    # (on for multi-host serving, where followers would otherwise be
    # observability black holes); True on a single host is a one-shot
    # warning (there are no followers to federate)
    telemetry_export: Optional[bool] = None
    # --telemetry-interval S: exporter frame cadence in seconds (each
    # frame batches everything new since the last one; the event
    # cursor advances only on a successful send, so a collector blip
    # delays events rather than dropping them)
    telemetry_interval: float = 2.0
    # --router: run THIS process as the front-door router
    # (cake_tpu/router) over N independent engine replicas instead of
    # loading a model — prefix-affinity consistent-hash routing, lite
    # health polling with staleness ejection, drain-aware failover,
    # verbatim Retry-After propagation. Binds --api (or --address).
    # With --model pointing at a directory holding tokenizer.json the
    # affinity keys are page-aligned token fingerprints (the
    # register_prefix rounding rule); without one they degrade to
    # system-prompt text fingerprints.
    router: bool = False
    # --replicas host:port,host:port,...: the engine replicas the
    # router fronts (each an independent `--api` serving process).
    # With --router-announce this becomes an OPTIONAL static seed —
    # announced replicas join the same fleet.
    replicas: Optional[str] = None
    # --router-announce host:port — dual-role flag for fleet discovery
    # (cake_tpu/router/discovery.py):
    #   * on the --router role: BIND the token-gated announce listener
    #     there (port 0 = ephemeral); replicas self-register, pushed
    #     frames supersede polling while fresh, departures
    #     drain-then-forget, pushed headroom/attainment feed placement
    #   * on a replica (--api) role: ANNOUNCE to the router's listener
    #     at that address (lite-health-superset frames + an explicit
    #     departure notice at shutdown)
    # The shared token comes from $CAKE_ANNOUNCE_TOKEN on both sides.
    router_announce: Optional[str] = None
    # --announce-interval S: replica announce-frame cadence; also the
    # router side's warm-up Retry-After bound and (x3) its
    # fallback-to-poll staleness window
    announce_interval: float = 2.0
    # --router-watermark N: bounded-load spill threshold — the
    # affinity target takes the request only under this queue+active
    # load; over it, the request spills to the next ring node
    router_watermark: int = 8
    # --router-poll S: lite-health poll cadence per replica
    # (GET /api/v1/health?lite=1)
    router_poll: float = 0.25
    # --router-policy {affinity,round_robin}: round_robin is the
    # bench strawman (no prefix affinity; per-request rotation)
    router_policy: str = "affinity"
    # --sentinel: arm the online performance-regression sentinel
    # (obs/sentinel.py) — rolling-window anomaly detectors over the
    # LIVE signal stream (per-kind step-time p95 vs a self-calibrated
    # baseline, jit-recompile rate, kv spill rate, shed rate,
    # per-class SLO attainment; on the --router role: per-replica
    # TTFT skew, affinity collapse, router shed storms), emitting
    # typed `anomaly` events, cake_anomaly_total{kind} /
    # cake_anomaly_active{kind} metrics and GET /api/v1/anomalies.
    # Fed entirely from existing seams — zero hot-path work.
    sentinel: bool = False
    # --sentinel-interval S: detector tick cadence in seconds (each
    # tick reads one rolling window per detector)
    sentinel_interval: float = 2.0
    # --sentinel-act: CLOSE the loop on the engine replica
    # (obs/actions.py): recompile-storm / step-time anomalies become
    # first-class autotune signals — hold new policy switches while
    # active, pin the post-switch rollback verdict from anomaly
    # evidence — every action typed on the bus, rate-bounded, counted
    # in cake_anomaly_actions_total and listed by GET
    # /api/v1/anomalies. Off = PR 15 report-only, byte-identical.
    sentinel_act: bool = False
    # --router-anomaly-weighting: the router-role closed loop — TTFT
    # skew / shed storm / affinity collapse de-weight the offending
    # replica's placement (never ejecting it), re-weighting on
    # recovery with a per-replica cooldown
    router_anomaly_weighting: bool = False
    # --postmortem-dir DIR: black-box forensics — breaker stops,
    # poison quarantines, failed recoveries and SIGTERM each dump one
    # JSON bundle (step records, event ring, traces, anomaly + action
    # history, metrics snapshot, journal tail) here;
    # tools/postmortem.py renders a bundle into a wall-clock narrative
    postmortem_dir: Optional[str] = None
    # --disagg {prefill,decode}: disaggregated prefill/decode serving
    # (cake_tpu/kv/transfer.py) — this engine takes ONE phase of the
    # pair. "decode" is the front door: it forwards each admission's
    # prompt to the prefill peer, installs the shipped KV pages via
    # the refcounted allocator and serves SSE from the first decoded
    # token; "prefill" admits forwarded prompts, runs chunked prefill
    # into pool pages and ships the pages + a handoff record. Requires
    # --kv-pages (pages are the transfer unit) and the shared channel
    # token in $CAKE_DISAGG_TOKEN on both engines. Any channel failure
    # degrades the decode host to whole-prompt local prefill — never a
    # wedged stream.
    disagg: Optional[str] = None
    # --disagg-peer host:port: the transfer channel address — the
    # PREFILL engine binds it (port 0 = ephemeral), the DECODE engine
    # connects to it (retrying with backoff, so start order is free)
    disagg_peer: Optional[str] = None
    # --disagg-timeout S: decode-host wait per forwarded prefill
    # before degrading that request to local prefill
    disagg_timeout: float = 30.0

    def validate(self) -> "Args":
        if self.dtype not in ("f16", "bf16", "f32"):
            raise ValueError(f"unsupported dtype '{self.dtype}'")
        if self.quant not in ("none", "int8", "int4"):
            raise ValueError(f"unsupported quant '{self.quant}'")
        if self.paged_attn not in ("auto", "fold", "pallas"):
            raise ValueError(
                f"unsupported paged_attn '{self.paged_attn}' "
                "(choose auto, fold or pallas)")
        if self.mixed_batch not in ("auto", "on", "off"):
            raise ValueError(
                f"unsupported mixed_batch '{self.mixed_batch}' "
                "(choose auto, on or off)")
        if self.kv_dtype in QUANTIZED_KV_DTYPES:
            # quantized KV is page-granular (per-page scales live in
            # the paged pool); without --kv-pages there is nothing to
            # quantize — loud error, not a silent no-op
            if not self.kv_pages:
                raise ValueError(
                    f"--kv-dtype {self.kv_dtype} requires --kv-pages: "
                    "quantized KV pages live in the paged pool "
                    "(cake_tpu/kv)")
            if self.kv_dtype == "int4" and self.kv_page_size % 2:
                # two int4 values nibble-pack into one byte along the
                # page's token axis, so a page must split evenly
                raise ValueError(
                    f"--kv-dtype int4 requires an even --kv-page-size "
                    f"(got {self.kv_page_size}): pages nibble-pack "
                    "token pairs (cake_tpu/kv/quantized_pool)")
            if self.draft_model is not None:
                raise ValueError(INT8_KV_SPEC_ERROR)
        elif self.kv_dtype is not None:
            # single source of truth for storage dtypes
            from cake_tpu.utils.devices import resolve_kv_dtype
            resolve_kv_dtype(self.kv_dtype)
        if self.spec_draft is not None:
            # paged speculative decoding (cake_tpu/spec): loud startup
            # errors mirroring the engine's constructor checks, so a
            # bad flag combination fails before the model loads
            if self.draft_model is not None:
                raise ValueError(
                    "--spec-draft (paged spec rows) and --draft-model "
                    "(the dense spec engine) are mutually exclusive")
            if not self.kv_pages:
                raise ValueError(
                    "--spec-draft requires --kv-pages: paged "
                    "speculative decoding shares the page allocator "
                    "(use --draft-model for the dense spec engine)")
            if self.kv_dtype in ("int8", "int4"):
                raise ValueError(
                    f"--spec-draft requires f32/bf16 KV pages, got "
                    f"--kv-dtype {self.kv_dtype}: the draft pool has "
                    "no quantized flavor yet (ROADMAP item 3)")
            if self.spec_gamma < 1:
                raise ValueError(
                    f"--spec-gamma {self.spec_gamma} must be >= 1")
            if self.disagg is not None:
                raise ValueError(
                    "--spec-draft is not supported with --disagg yet: "
                    "a shipped prefill carries no draft-pool KV (the "
                    "decode host would re-prefill every draft)")
            if self.mixed_batch == "off":
                raise ValueError(
                    "--spec-draft requires the mixed ragged step "
                    "(--mixed-batch auto/on): spec rows are a row "
                    "kind of that step")
        if self.kv_host_pages is not None and self.kv_host_pages < 1:
            raise ValueError(
                f"--kv-host-pages {self.kv_host_pages} must be >= 1")
        if self.autotune not in ("off", "manual", "auto"):
            raise ValueError(
                f"unsupported autotune '{self.autotune}' "
                "(choose off, manual or auto)")
        if self.autotune == "auto":
            if not self.autotune_policy:
                raise ValueError(
                    "--autotune auto requires --autotune-policy "
                    "(fit one with tools/autotune_fit.py)")
            # parse NOW so a malformed/missing policy is a loud startup
            # error, not a crash after the model loaded (the
            # --fault-plan precedent)
            from cake_tpu.autotune import PolicyTable
            PolicyTable.load(self.autotune_policy)
        if self.fault_plan:
            # parse NOW so a malformed plan is a loud startup error,
            # not a crash after the model loaded (a chaos run that
            # silently injects nothing is worse than no chaos run)
            from cake_tpu.faults import FaultPlan
            FaultPlan.parse(self.fault_plan)
        if self.journal_fsync not in ("never", "batch", "always"):
            raise ValueError(
                f"unsupported journal_fsync '{self.journal_fsync}' "
                "(choose never, batch or always)")
        if self.slo_targets:
            # same discipline as --fault-plan: a malformed SLO spec is
            # a loud startup error, not a serving run silently
            # accounting against the defaults
            from cake_tpu.obs.slo import parse_slo_targets
            parse_slo_targets(self.slo_targets)
        if self.event_ring < 0:
            raise ValueError(
                f"--event-ring {self.event_ring} must be >= 0 "
                "(0 disables the event bus)")
        if not self.telemetry_interval > 0:
            raise ValueError(
                f"--telemetry-interval {self.telemetry_interval} must "
                "be > 0 seconds")
        if self.router_policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"unsupported router_policy '{self.router_policy}' "
                "(choose affinity or round_robin)")
        if self.router_watermark < 1:
            raise ValueError(
                f"--router-watermark {self.router_watermark} must be "
                ">= 1")
        if not self.router_poll > 0:
            raise ValueError(
                f"--router-poll {self.router_poll} must be > 0 "
                "seconds")
        if not self.sentinel_interval > 0:
            raise ValueError(
                f"--sentinel-interval {self.sentinel_interval} must "
                "be > 0 seconds")
        if self.sentinel_act and not self.sentinel:
            raise ValueError(
                "--sentinel-act requires --sentinel (nothing to act "
                "on without the anomaly sentinel)")
        if self.router_anomaly_weighting and not self.sentinel:
            raise ValueError(
                "--router-anomaly-weighting requires --sentinel (the "
                "router-side detectors drive the de-weighting)")
        if not self.announce_interval > 0:
            raise ValueError(
                f"--announce-interval {self.announce_interval} must "
                "be > 0 seconds")
        if self.router_announce is not None:
            # same shape discipline as a --replicas entry: the value
            # must be a bindable/dialable host:port
            host, sep, port = self.router_announce.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise ValueError(
                    f"--router-announce {self.router_announce!r} must "
                    "be host:port (port 0 binds an ephemeral announce "
                    "listener on the router role)")
            if not 0 <= int(port) <= 65535:
                raise ValueError(
                    f"--router-announce port {port} out of range "
                    "(0-65535)")
        if self.router:
            # parse NOW so a malformed replica list is a loud startup
            # error (the --fault-plan discipline). With discovery
            # armed the static seed may be empty; without it an empty
            # fleet could never serve — keep the loud error.
            if not self.replicas and self.router_announce is None:
                raise ValueError(
                    "--router requires --replicas host:port,... (the "
                    "engine replicas the front door routes over) or "
                    "--router-announce host:port (fleet discovery: "
                    "replicas self-register)")
            if self.replicas:
                parse_replicas(self.replicas)
        if self.disagg is not None:
            if self.disagg not in ("prefill", "decode"):
                raise ValueError(
                    f"unsupported disagg '{self.disagg}' (choose "
                    "prefill or decode)")
            if not self.kv_pages:
                raise ValueError(
                    "--disagg requires --kv-pages: KV pool pages are "
                    "the transfer unit (cake_tpu/kv/transfer.py)")
            if not self.disagg_peer:
                raise ValueError(
                    "--disagg requires --disagg-peer host:port (the "
                    "prefill engine binds it; the decode engine "
                    "connects to it)")
            host, sep, port = self.disagg_peer.rpartition(":")
            if not sep or not host or not port.isdigit() \
                    or not 0 <= int(port) <= 65535:
                raise ValueError(
                    f"--disagg-peer {self.disagg_peer!r} must be "
                    "host:port (port 0 binds ephemeral on the prefill "
                    "role)")
            import os as _os
            if not _os.environ.get("CAKE_DISAGG_TOKEN"):
                # loud NOW, not a dead channel after the model loaded
                # (the $CAKE_ANNOUNCE_TOKEN discipline)
                raise ValueError(
                    "--disagg needs the shared channel token in "
                    "$CAKE_DISAGG_TOKEN on both engines")
        if not self.disagg_timeout > 0:
            raise ValueError(
                f"--disagg-timeout {self.disagg_timeout} must be > 0 "
                "seconds")
        if self.mode not in ("master", "worker"):
            raise ValueError(f"unsupported mode '{self.mode}'")
        for knob in ("tp", "dp", "sp", "microbatches", "batch_size",
                     "max_slots", "decode_scan", "spec_gamma",
                     "spec_rounds", "trace_ring", "step_ring"):
            if getattr(self, knob) < 1:
                raise ValueError(f"--{knob.replace('_', '-')} must be >= 1")
        return self


@dataclass
class SDArgs:
    """Stable-Diffusion model options (reference lib.rs:90-127)."""

    sd_version: SDVersion = SDVersion.V1_5
    sd_tokenizer: Optional[str] = None
    sd_tokenizer_2: Optional[str] = None
    sd_use_f16: bool = True
    sd_width: Optional[int] = None
    sd_height: Optional[int] = None
    sd_sliced_attention_size: Optional[int] = None
    sd_clip: Optional[str] = None
    sd_clip2: Optional[str] = None
    sd_vae: Optional[str] = None
    sd_unet: Optional[str] = None
    sd_flash_attention: bool = False


@dataclass
class ImageGenerationArgs:
    """Per-request image generation parameters (reference lib.rs:145-200).

    Serves as both CLI flags and the JSON body of POST /api/v1/image
    (reference api/image.rs:15-18).
    """

    image_prompt: str = "A very realistic photo of a rusty robot walking on a sandy beach"
    image_uncond_prompt: str = ""
    sd_tracing: bool = False
    sd_img2img: Optional[str] = None
    sd_img2img_strength: float = 0.8
    sd_n_steps: Optional[int] = None
    sd_num_samples: int = 1
    sd_bsize: int = 1
    sd_intermediary_images: bool = False
    sd_guidance_scale: Optional[float] = None
    sd_seed: Optional[int] = None

    @classmethod
    def from_json(cls, body: dict) -> "ImageGenerationArgs":
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in body.items() if k in known})

    def to_json(self) -> dict:
        return asdict(self)


def _add_dataclass_args(parser: argparse.ArgumentParser, dc_type) -> None:
    for f in fields(dc_type):
        name = "--" + f.name.replace("_", "-")
        default = f.default
        if isinstance(default, Enum):
            parser.add_argument(name, type=str, default=default.value,
                                dest=f.name)
        elif isinstance(default, bool):
            # --flag / --no-flag so True defaults (e.g. sd_use_f16) can be
            # disabled from the CLI
            parser.add_argument(name, action=argparse.BooleanOptionalAction,
                                default=default, dest=f.name)
        elif default is None and f.type == "Optional[bool]":
            parser.add_argument(name, action=argparse.BooleanOptionalAction,
                                default=None, dest=f.name)
        elif default is None:
            parser.add_argument(name, default=None, dest=f.name)
        else:
            parser.add_argument(name, type=type(default), default=default,
                                dest=f.name)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cake-tpu",
        description="TPU-native distributed LLM + diffusion inference",
    )
    _add_dataclass_args(parser, Args)
    _add_dataclass_args(parser, SDArgs)
    _add_dataclass_args(parser, ImageGenerationArgs)
    return parser


def parse_args(argv=None):
    """Parse argv into (Args, SDArgs, ImageGenerationArgs)."""
    ns = build_parser().parse_args(argv)
    d = vars(ns)

    def pick(dc_type):
        kwargs = {}
        for f in fields(dc_type):
            v = d[f.name]
            if isinstance(f.default, Enum) and not isinstance(v, Enum):
                v = type(f.default)(v)
            if f.type in ("int", "Optional[int]") and isinstance(v, str):
                v = int(v)
            if f.type in ("float", "Optional[float]") and isinstance(v, str):
                v = float(v)
            kwargs[f.name] = v
        return dc_type(**kwargs)

    return pick(Args).validate(), pick(SDArgs), pick(ImageGenerationArgs)
