"""Runtime half of the fault plane: a seeded, thread-safe injector.

`FaultInjector` evaluates a parsed `FaultPlan` at the named sites
threaded through the serving stack (see plan.SITES). Call sites do

    if self._faults is not None:
        self._faults.check("engine.decode", step=self.stats.steps)

so a disabled plane (no ``--fault-plan``) costs exactly one attribute
test per site — the injector object does not even exist. Determinism:
every rule owns its OWN ``random.Random`` seeded from (plan seed, rule
index), so probabilistic rules fire on the same matching-call indices
regardless of what other sites or rules do around them — same plan +
same seed + same per-site call sequence => same injections, which is
what makes a chaos run reproducible from its command line.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cake_tpu.faults.plan import (
    ABORT_EXIT_CODE, FaultPlan, FaultRule, InjectedOOM,
    InjectedTransient, InjectedWedge,
)
from cake_tpu.obs import metrics as obs_metrics

_INJECTIONS = obs_metrics.counter(
    "cake_fault_injections_total",
    "Faults injected by the --fault-plan chaos plane, by site "
    "(cake_tpu/faults; zero without a plan)",
    labelnames=("site",))

# bounded per-injector injection log (site, kind, matching-call index):
# enough for a bench tier or health dump to show what fired, without an
# unbounded list on a long-lived p= rule
_LOG_CAP = 256


@dataclass
class _RuleState:
    """Mutable runtime state for one plan rule."""

    rule: FaultRule
    rng: random.Random
    calls: int = 0      # matching calls seen (post match_len filter)
    fired: int = 0      # injections performed (capped at rule.times)


@dataclass
class InjectionRecord:
    site: str
    kind: str
    call: int           # 1-based matching-call index that fired
    step: Optional[int] = None


@dataclass
class FaultInjector:
    """Evaluates a FaultPlan at the serving stack's named sites."""

    plan: FaultPlan
    records: List[InjectionRecord] = field(default_factory=list)
    # obs/events.EventBus (None = no bus attached — same one-attribute-
    # test discipline as the call sites' own `_faults is not None`):
    # every firing publishes a fault_injected event so chaos shows up
    # on the same timeline as what it broke
    events: Optional[object] = None
    # cakelint guards discipline for the optional bus above
    OPTIONAL_PLANES = ("events",)

    def __post_init__(self):
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[_RuleState]] = {}
        for i, rule in enumerate(self.plan.rules):
            st = _RuleState(
                rule=rule,
                # independent stream per rule: other rules/sites never
                # consume from it, so p= firings are reproducible
                rng=random.Random((self.plan.seed << 20) ^ (i + 1)))
            self._by_site.setdefault(rule.site, []).append(st)
        self.total = 0
        self.by_site: Dict[str, int] = {}

    def check(self, site: str, *, step: Optional[int] = None,
              n_tokens: Optional[int] = None) -> None:
        """Raise the planned fault if a rule for `site` fires now.

        step: the engine's step counter (for step= triggers);
        n_tokens: call context for match_len= filtering (e.g. the
        token count of the prefill being dispatched)."""
        states = self._by_site.get(site)
        if not states:
            return
        fire: Optional[_RuleState] = None
        call = 0
        with self._lock:
            for st in states:
                r = st.rule
                if st.fired >= r.times:
                    continue
                if r.match_len is not None and n_tokens != r.match_len:
                    continue
                # EVERY active rule counts every matching call — even
                # when an earlier rule already claimed this one — so a
                # second nth= rule at the same site still fires on the
                # call its spec names, and p= streams stay indexed by
                # matching-call number. Only the first hit (plan
                # order) raises; a later rule whose trigger hits the
                # same call simply does not fire it.
                st.calls += 1
                if r.trigger == "always":
                    hit = True
                elif r.trigger == "nth":
                    hit = st.calls == int(r.value)
                elif r.trigger == "step":
                    hit = step is not None and step >= int(r.value)
                else:  # p
                    hit = st.rng.random() < r.value
                if hit and fire is None:
                    st.fired += 1
                    fire, call = st, st.calls
            if fire is not None:
                self.total += 1
                self.by_site[site] = self.by_site.get(site, 0) + 1
                if len(self.records) < _LOG_CAP:
                    self.records.append(InjectionRecord(
                        site=site, kind=fire.rule.error, call=call,
                        step=step))
        if fire is None:
            return
        _INJECTIONS.labels(site=site).inc()
        if self.events is not None:
            self.events.publish("fault_injected", site=site,
                                kind=fire.rule.error, call=call,
                                step=step)
        kind = fire.rule.error
        if kind == "abort":
            # staged kill -9: die NOW, with no atexit/flush courtesy —
            # only bytes already written to the OS survive, which is
            # exactly the state a crash drill must recover from. The
            # event/metric above may be lost with the process; the log
            # line below is best-effort evidence for the drill driver.
            import logging
            import os
            logging.getLogger(__name__).error(
                "injected abort at %s (call %d, step %s): os._exit(%d)",
                site, call, step, ABORT_EXIT_CODE)
            os._exit(ABORT_EXIT_CODE)
        if kind == "oom":
            raise InjectedOOM(site)
        if kind == "wedge":
            # the compressed form of a hung device/tunnel: hold the
            # calling thread (outside the lock — other sites must keep
            # evaluating), then surface as a failure
            time.sleep(fire.rule.secs)
            raise InjectedWedge(site, f"held {fire.rule.secs:g}s")
        raise InjectedTransient(site)

    def describe(self) -> dict:
        """Health-endpoint view of the plane (plan + what fired)."""
        with self._lock:
            return {
                "plan": self.plan.describe(),
                "injections_total": self.total,
                "injections_by_site": dict(self.by_site),
            }


def build_injector(spec) -> Optional[FaultInjector]:
    """--fault-plan string (or a pre-parsed FaultPlan) -> injector;
    None/empty spec -> None, and every call site's `is not None` guard
    keeps the disabled plane at zero per-step work."""
    if spec is None:
        return None
    plan = spec if isinstance(spec, FaultPlan) else FaultPlan.parse(spec)
    if plan is None:
        return None
    return FaultInjector(plan)
