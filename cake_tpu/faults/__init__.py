"""Deterministic fault injection for the serving stack (cake_tpu/faults).

A ``--fault-plan`` spec names WHERE (sites threaded through the hot
paths), WHEN (nth call / step count / seeded probability / always) and
WHAT (transient, simulated OOM, simulated wedge) should fail — so every
chaos experiment is reproducible from its command line and no test ever
monkeypatches engine internals to simulate a crash. Disabled (no plan)
the plane is a single ``is not None`` test per site.

See plan.py for the spec grammar and injector.py for runtime semantics.
"""

from cake_tpu.faults.injector import FaultInjector, build_injector
from cake_tpu.faults.plan import (
    ABORT_EXIT_CODE, ERRORS, SITES, TRIGGERS, FaultPlan, FaultRule,
    InjectedFault, InjectedOOM, InjectedTransient, InjectedWedge,
)

__all__ = [
    "ABORT_EXIT_CODE", "ERRORS", "SITES", "TRIGGERS",
    "FaultInjector", "FaultPlan", "FaultRule",
    "InjectedFault", "InjectedOOM", "InjectedTransient", "InjectedWedge",
    "build_injector",
]
