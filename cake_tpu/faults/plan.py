"""Fault-plan spec: named sites, seeded triggers, typed injected errors.

A fault plan is a deterministic description of *where* and *when* the
serving stack should fail, written as a compact spec string
(``--fault-plan``) so every chaos experiment is reproducible from its
command line — no monkeypatching of engine internals:

    seed=42;engine.decode:nth=12:transient;control.publish:p=0.01:oom

Grammar (rules separated by ``;``, fields inside a rule by ``:``)::

    plan  := [ 'seed=N' ';' ] rule ( ';' rule )*
    rule  := site ':' field ( ':' field )*
    field := trigger | error | option
    trigger := 'nth=N'       fire on the Nth matching call to the site
             | 'step=N'      fire once the engine step counter reaches N
             | 'p=F'         fire each matching call with probability F
                             (seeded — same plan+seed => same firings)
             | 'always'      fire on every matching call
    error  := 'transient'    a generic retryable step failure (XLA-ish)
             | 'oom'         a simulated RESOURCE_EXHAUSTED
             | 'wedge'       hold the calling thread for `secs`, then
                             raise (a hung device/tunnel, compressed)
             | 'abort'       hard process death via os._exit
                             (ABORT_EXIT_CODE) — a staged kill -9 for
                             restart/journal-replay crash drills
    option := 'times=N'      total injections this rule may perform (1)
             | 'match_len=N' only calls whose context carries
                             n_tokens == N match (content-keyed faults:
                             a specific request's prefill)
             | 'secs=F'      wedge hold seconds (default 2.0)

Each rule needs exactly one trigger and one error type. Sites are the
fixed names threaded through the hot paths (``SITES`` below); an
unknown site is a loud plan error, not a silent no-op.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

# the injection points threaded through the serving stack; keep in sync
# with the call sites (engine step dispatch, control channel, host KV
# tier, page allocator) and the README "Fault tolerance" table
SITES = frozenset({
    "engine.step",        # top of every engine iteration
    "engine.prefill",     # one admission's prefill (ctx: n_tokens)
    "engine.decode",      # a ragged decode / scan / spec dispatch
    "engine.mixed",       # a mixed (decode+prefill-chunk) dispatch
    "control.publish",    # coordinator -> follower op publish
    "control.recv",       # follower op receive
    "host_tier.fetch",    # device -> host KV page spill
    "host_tier.install",  # host -> device KV page restore
    "pager.alloc",        # page-pool allocation
    "journal.append",     # write-ahead journal record append
    "journal.fsync",      # journal durability barrier (fsync)
    "journal.replay",     # startup journal replay (serve/journal.py)
    "kv.ship",            # disagg prefill host: page-shipment capture
    "kv.adopt",           # disagg decode host: shipped-page adoption
    "spec.verify",        # paged speculative verify round (absorbed:
                          # rows degrade to plain decode, never wedge)
})

TRIGGERS = ("nth", "step", "p", "always")
ERRORS = ("transient", "oom", "wedge", "abort")

# `abort` kills the PROCESS (os._exit — no atexit, no flushes beyond
# what already hit the OS): the in-tree way to stage a kill -9 crash
# drill. The distinctive exit code lets a drill driver (bench.py
# --restart, tests) tell a planned abort from an organic death.
ABORT_EXIT_CODE = 86

# context each call site actually supplies. A rule keyed on context
# its site never passes would parse cleanly and then never fire — a
# silently-inert chaos plan, the exact failure mode the loud-parse
# contract exists to prevent — so parsing rejects the combination.
NO_STEP_SITES = frozenset({"control.publish", "control.recv",
                           "journal.append", "journal.fsync",
                           "journal.replay"})
MATCH_LEN_SITES = frozenset({"engine.prefill"})


class InjectedFault(RuntimeError):
    """Base class for plan-injected failures (site + kind attached so
    logs and classifiers can tell injected chaos from organic faults)."""

    kind = "fault"

    def __init__(self, site: str, detail: str = ""):
        super().__init__(
            f"injected {self.kind} at {site}" + (f": {detail}" if detail
                                                 else ""))
        self.site = site


class InjectedTransient(InjectedFault):
    """A generic retryable step failure (the XLA-error shape)."""

    kind = "transient"


class InjectedOOM(InjectedFault):
    """A simulated RESOURCE_EXHAUSTED allocation failure."""

    kind = "oom"

    def __init__(self, site: str):
        super().__init__(site, "RESOURCE_EXHAUSTED: out of memory "
                               "(simulated)")


class InjectedWedge(InjectedFault):
    """Raised after a wedge rule's hold expires — the compressed form
    of a hung accelerator/tunnel (block, then fail)."""

    kind = "wedge"


@dataclass(frozen=True)
class FaultRule:
    """One parsed plan rule (see the module grammar)."""

    site: str
    trigger: str                    # nth | step | p | always
    value: float = 0.0              # N for nth/step, F for p
    error: str = "transient"        # transient | oom | wedge
    times: int = 1                  # total injections this rule allows
    match_len: Optional[int] = None  # only ctx n_tokens == this matches
    secs: float = 2.0               # wedge hold seconds

    def describe(self) -> str:
        trig = (self.trigger if self.trigger == "always"
                else f"{self.trigger}={self.value:g}")
        extra = "" if self.match_len is None \
            else f":match_len={self.match_len}"
        if self.error == "wedge":
            # keep the echo a faithful spec: a re-parsed describe()
            # must hold the same wedge duration
            extra += f":secs={self.secs:g}"
        return f"{self.site}:{trig}:{self.error}:times={self.times}{extra}"


@dataclass
class FaultPlan:
    """A parsed --fault-plan: rules + the determinism seed."""

    rules: List[FaultRule] = field(default_factory=list)
    seed: int = 0

    @classmethod
    def parse(cls, spec: Optional[str]) -> Optional["FaultPlan"]:
        """Parse a spec string; None/empty => no plan (the injection
        plane stays a no-op). Raises ValueError on any malformed rule —
        a chaos experiment that silently injects nothing is worse than
        a loud config error."""
        if spec is None:
            return None
        spec = spec.strip()
        if not spec:
            return None
        seed = 0
        rules: List[FaultRule] = []
        parts = [p.strip() for p in spec.split(";") if p.strip()]
        if not parts:
            return None
        if parts and parts[0].startswith("seed="):
            seed = _parse_int(parts[0][5:], "seed")
            parts = parts[1:]
        if not parts:
            raise ValueError("fault plan has a seed but no rules")
        for raw in parts:
            rules.append(_parse_rule(raw))
        return cls(rules=rules, seed=seed)

    def describe(self) -> str:
        return f"seed={self.seed};" + ";".join(r.describe()
                                               for r in self.rules)


def _parse_int(s: str, what: str) -> int:
    try:
        v = int(s)
    except ValueError:
        raise ValueError(f"fault plan: {what} takes an integer, "
                         f"got {s!r}")
    return v


def _parse_float(s: str, what: str) -> float:
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"fault plan: {what} takes a number, got {s!r}")


def _parse_rule(raw: str) -> FaultRule:
    fields = [f.strip() for f in raw.split(":") if f.strip()]
    if len(fields) < 2:
        raise ValueError(
            f"fault rule {raw!r} needs at least site:trigger:error "
            "(see cake_tpu/faults/plan.py for the grammar)")
    site = fields[0]
    if site not in SITES:
        raise ValueError(
            f"fault rule {raw!r}: unknown site {site!r} "
            f"(known: {', '.join(sorted(SITES))})")
    trigger: Optional[str] = None
    value = 0.0
    error: Optional[str] = None
    times = 1
    match_len: Optional[int] = None
    secs = 2.0
    for f in fields[1:]:
        key, _, val = f.partition("=")
        if key in ("nth", "step", "p", "always"):
            if trigger is not None:
                raise ValueError(
                    f"fault rule {raw!r}: more than one trigger "
                    f"({trigger!r} and {key!r})")
            trigger = key
            if key == "always":
                if val:
                    raise ValueError(
                        f"fault rule {raw!r}: 'always' takes no value")
            elif key == "p":
                value = _parse_float(val, "p")
                if not 0.0 < value <= 1.0:
                    raise ValueError(
                        f"fault rule {raw!r}: p must be in (0, 1]")
            else:
                value = _parse_int(val, key)
                if value < 1:
                    raise ValueError(
                        f"fault rule {raw!r}: {key} must be >= 1")
        elif key in ERRORS:
            if val:
                raise ValueError(
                    f"fault rule {raw!r}: error kind {key!r} takes no "
                    "value")
            if error is not None:
                raise ValueError(
                    f"fault rule {raw!r}: more than one error kind "
                    f"({error!r} and {key!r})")
            error = key
        elif key == "times":
            times = _parse_int(val, "times")
            if times < 1:
                raise ValueError(
                    f"fault rule {raw!r}: times must be >= 1")
        elif key == "match_len":
            match_len = _parse_int(val, "match_len")
            if match_len < 0:
                raise ValueError(
                    f"fault rule {raw!r}: match_len must be >= 0")
        elif key == "secs":
            secs = _parse_float(val, "secs")
            if secs < 0:
                raise ValueError(
                    f"fault rule {raw!r}: secs must be >= 0")
        else:
            raise ValueError(
                f"fault rule {raw!r}: unknown field {f!r}")
    if trigger is None:
        raise ValueError(
            f"fault rule {raw!r}: needs a trigger "
            "(nth=N | step=N | p=F | always)")
    if error is None:
        raise ValueError(
            f"fault rule {raw!r}: needs an error kind "
            "(transient | oom | wedge)")
    if trigger == "step" and site in NO_STEP_SITES:
        raise ValueError(
            f"fault rule {raw!r}: site {site!r} carries no engine "
            "step counter — a step= trigger there would never fire "
            "(use nth=, p= or always)")
    if match_len is not None and site not in MATCH_LEN_SITES:
        raise ValueError(
            f"fault rule {raw!r}: only "
            f"{', '.join(sorted(MATCH_LEN_SITES))} carries n_tokens "
            "context — match_len= on this site would never fire")
    return FaultRule(site=site, trigger=trigger, value=value, error=error,
                     times=times, match_len=match_len, secs=secs)
