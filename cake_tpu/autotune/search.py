"""Offline joint configuration search -> piecewise policy table.

The Sandwich result (PAPERS.md): the best serving configuration is a
function of offered load, so instead of one tuned config the server
carries a small *policy table* — offered-load regime -> best measured
config — fitted OFFLINE from measurements and consulted ONLINE by the
controller (controller.py). This module owns the table format and the
fitter; ``tools/autotune_fit.py`` is the CLI front end.

Inputs the fitter understands:

  * **observation records** — dicts with a ``config`` (EngineConfig
    JSON) plus measured ``tok_s`` and the ``offered_rps`` the
    measurement was taken under. ``extract_observations`` walks any
    JSON document (BENCH_*.json round files, ``bench.py --autotune``
    tier lines, hand-built sweep files) and collects every such record
    wherever it nests, so bench output is ingestible as-is.
  * **step-log JSONL** (the ``--step-log`` flight recorder): has no
    config column — the whole log was captured under ONE config the
    caller names — so ``observations_from_step_log`` slices it into
    time windows and emits one observation per window (offered load =
    admissions/s from prefill-side records, achieved = generated
    tokens/s from decode-side records).

Policy file format (``--autotune-policy``)::

    {"version": 2,
     "regimes": [
       {"max_offered_rps": 2.0,  "config": {"slots": 8, ...},
        "max_ttft_p99_s": {"interactive": 0.2},
        "min_attainment": 0.95},
       {"max_offered_rps": null, "config": {"slots": 32, ...}}]}

Regimes are sorted by ascending boundary; ``lookup(offered_rps)``
returns the first regime whose boundary covers the load (``null`` =
catch-all). The fitter guarantees a catch-all regime so lookup is
total.

Version 2 adds optional per-regime **quality guards** (the goodput
layer, obs/slo.py): ``max_ttft_p99_s`` and ``min_attainment``, each a
bare number (applies to every class the live signals report) or a
``{class: bound}`` mapping. A regime whose offered-load boundary covers
the current load but whose quality guards FAIL is skipped — lookup
falls through toward the catch-all, so a server missing its interactive
TTFT target escalates to a bigger config even while offered rps alone
says the small one suffices. Version-1 files (no guards) load
unchanged.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from cake_tpu.autotune.space import EngineConfig, config_key, validate_config

log = logging.getLogger(__name__)

POLICY_VERSION = 2
# version-1 files (no quality guards) read identically; writes are
# always the current version
READABLE_VERSIONS = (1, 2)

# the per-regime quality-guard keys and their comparison direction
# (True = the live value must stay BELOW the bound)
_GUARD_KEYS = (("max_ttft_p99_s", True), ("min_attainment", False))

# step-record kinds that generate tokens / admit prompts — mirrors the
# obs/steps.py flight-recorder vocabulary
_DECODE_KINDS = ("decode", "decode_scan", "spec", "mixed")


@dataclass
class Observation:
    """One measured (config, load) -> throughput point."""

    config: EngineConfig
    offered_rps: float
    tok_s: float
    ttft_p99_s: Optional[float] = None
    # worst-class SLO attainment over the observation window (0..1],
    # from obs/slo.py — feeds the auto-fitted min_attainment guard
    attainment: Optional[float] = None

    def to_dict(self) -> dict:
        out = {"config": self.config.to_dict(),
               "offered_rps": round(self.offered_rps, 4),
               "tok_s": round(self.tok_s, 4)}
        if self.ttft_p99_s is not None:
            out["ttft_p99_s"] = round(self.ttft_p99_s, 6)
        if self.attainment is not None:
            out["attainment"] = round(self.attainment, 6)
        return out


@dataclass
class PolicyTable:
    """Piecewise offered-load -> EngineConfig policy."""

    regimes: List[dict] = field(default_factory=list)

    def __post_init__(self):
        # normalize: parse configs, sort ascending with the catch-all
        # (None boundary) last, so lookup() is a linear scan
        regs = []
        for r in self.regimes:
            cfg = r["config"]
            if not isinstance(cfg, EngineConfig):
                cfg = EngineConfig.from_dict(dict(cfg))
            regs.append({**r, "config": cfg})
        regs.sort(key=lambda r: (r.get("max_offered_rps") is None,
                                 r.get("max_offered_rps") or 0.0))
        self.regimes = regs

    def validate(self, max_seq_len: Optional[int] = None) -> "PolicyTable":
        if not self.regimes:
            raise ValueError("policy table has no regimes")
        if self.regimes[-1].get("max_offered_rps") is not None:
            raise ValueError(
                "policy table needs a catch-all regime "
                '("max_offered_rps": null) so every load maps somewhere')
        for r in self.regimes:
            validate_config(r["config"], max_seq_len=max_seq_len)
            for key, _below in _GUARD_KEYS:
                g = r.get(key)
                if g is None:
                    continue
                vals = (g.values() if isinstance(g, dict) else (g,))
                if not all(isinstance(v, (int, float))
                           and not isinstance(v, bool) and v > 0
                           for v in vals):
                    raise ValueError(
                        f"policy regime {key} must be a positive "
                        "number or a {class: number} mapping, got "
                        f"{g!r}")
        return self

    @staticmethod
    def _guards_ok(regime: dict,
                   ttft_p99_by_class: Optional[Dict[str, float]],
                   attainment: Optional[Dict[str, float]]) -> bool:
        """Whether the live quality signals let this regime hold. A
        guard with no corresponding live signal passes — quality can
        only ESCALATE a lookup, never block it on missing data."""
        for key, below, live in (
                ("max_ttft_p99_s", True, ttft_p99_by_class),
                ("min_attainment", False, attainment)):
            g = regime.get(key)
            if g is None or not live:
                continue
            bounds = g if isinstance(g, dict) else {c: g for c in live}
            for cls, bound in bounds.items():
                v = live.get(cls)
                if v is None:
                    continue
                if (v > bound) if below else (v < bound):
                    return False
        return True

    def lookup(self, offered_rps: float,
               ttft_p99_by_class: Optional[Dict[str, float]] = None,
               attainment: Optional[Dict[str, float]] = None
               ) -> EngineConfig:
        """First regime whose offered-load boundary covers the load AND
        whose quality guards pass against the live signals (obs/slo.py
        attainment + TTFT p99 by class, via AutotuneSignals). A
        covering regime failing its guards is skipped — the lookup
        escalates toward the catch-all, which is returned
        unconditionally (lookup stays total even when every guard
        fails: there is no bigger config to escalate to)."""
        for r in self.regimes[:-1]:
            bound = r.get("max_offered_rps")
            if bound is not None and offered_rps > bound:
                continue
            if self._guards_ok(r, ttft_p99_by_class, attainment):
                return r["config"]
        return self.regimes[-1]["config"]

    def to_dict(self) -> dict:
        return {"version": POLICY_VERSION,
                "regimes": [{**r, "config": r["config"].to_dict()}
                            for r in self.regimes]}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyTable":
        if d.get("version") not in READABLE_VERSIONS:
            raise ValueError(
                f"unsupported policy version {d.get('version')!r} "
                f"(this build reads versions "
                f"{', '.join(map(str, READABLE_VERSIONS))})")
        return cls(regimes=list(d.get("regimes", ())))

    @classmethod
    def load(cls, path: str) -> "PolicyTable":
        with open(path) as f:
            return cls.from_dict(json.load(f)).validate()


# -- ingestion --------------------------------------------------------------


def extract_observations(obj) -> List[Observation]:
    """Walk any JSON structure and collect observation records: dicts
    carrying a ``config`` mapping plus ``tok_s`` (and optionally
    ``offered_rps``/``ttft_p99_s``/``attainment``). Records that fail
    config parsing are skipped with a warning — a BENCH file holds many
    shapes of line, and one malformed record must not abort a fit."""
    out: List[Observation] = []
    if isinstance(obj, dict):
        if isinstance(obj.get("config"), dict) and "tok_s" in obj:
            try:
                att = obj.get("attainment")
                if isinstance(att, dict):
                    # per-class mapping (obs/slo.py shape): the guard
                    # tracks the worst class
                    att = min(att.values()) if att else None
                out.append(Observation(
                    config=EngineConfig.from_dict(dict(obj["config"])),
                    offered_rps=float(obj.get("offered_rps", 0.0)),
                    tok_s=float(obj["tok_s"]),
                    ttft_p99_s=(float(obj["ttft_p99_s"])
                                if obj.get("ttft_p99_s") is not None
                                else None),
                    attainment=(float(att) if att is not None
                                else None)))
            except (ValueError, TypeError) as e:
                log.warning("skipping malformed observation: %s", e)
        for v in obj.values():
            out.extend(extract_observations(v))
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out.extend(extract_observations(v))
    return out


def observations_from_step_log(path: str, config: EngineConfig,
                               window_s: float = 10.0
                               ) -> List[Observation]:
    """One observation per `window_s` slice of a --step-log JSONL
    capture, all under the caller-named `config` (the flight recorder
    has no config column — one log file is one config's flight)."""
    from cake_tpu.obs.jsonl import read_jsonl

    recs = [r for r in read_jsonl(path)
            if isinstance(r.get("ts"), (int, float))]
    if not recs:
        return []
    t0 = min(r["ts"] for r in recs)
    w = max(1e-3, float(window_s))
    # one linear pass bucketing by floor((ts - t0) / w): an hour-long
    # capture at a 10s window is O(records), not O(windows x records)
    buckets: Dict[int, List[float]] = {}   # idx -> [tokens, admits]
    for r in recs:
        b = buckets.setdefault(int((r["ts"] - t0) // w), [0.0, 0.0])
        kind = r.get("kind")
        if kind in _DECODE_KINDS:
            b[0] += int(r.get("tokens", 0))
        if kind == "prefill":
            # one prefill record per admission group; rows carries the
            # group size on the batched path
            b[1] += max(1, int(r.get("rows", 1)))
        elif kind == "mixed":
            # mixed batching (the paged default) admits prompts as
            # chunk rows inside mixed steps — there are NO standalone
            # prefill records, so the admission proxy is the prefill-
            # side row activity (an upper proxy: a long prompt's
            # chunks count once per step, but the load axis only
            # needs a monotone proxy, and without this every
            # mixed-mode window would read offered_rps = 0)
            b[1] += int(r.get("rows_prefill") or 0)
    return [Observation(config=config, offered_rps=admits / w,
                        tok_s=toks / w)
            for _idx, (toks, admits) in sorted(buckets.items())]


# -- fitting ----------------------------------------------------------------


def fit(observations: Sequence[Observation],
        max_regimes: int = 4,
        emit_guards: bool = True,
        ttft_headroom: float = 1.5,
        attainment_margin: float = 0.9) -> PolicyTable:
    """Fit a piecewise policy: bucket the observed offered-load axis
    into up to `max_regimes` quantile bins, pick the config with the
    best mean tok/s inside each bin, and merge adjacent bins that chose
    the same config. The last regime is always the catch-all.

    When `emit_guards` is set (the default), each non-catch-all regime
    additionally carries auto-fitted quality guards derived from the
    winning config's own observation windows: `max_ttft_p99_s` is the
    worst observed TTFT p99 times `ttft_headroom` (live TTFT drifting
    past what the config ever delivered — plus headroom — escalates the
    lookup), and `min_attainment` is the worst observed SLO attainment
    times `attainment_margin`. Regimes whose observations carry no
    quality signal get no guard, and the catch-all never does (lookup
    returns it unconditionally — a guard there would be dead)."""
    obs = [o for o in observations if o.tok_s > 0]
    if not obs:
        raise ValueError("no usable observations (tok_s > 0) to fit")
    uniq = sorted({o.offered_rps for o in obs})
    n_bins = max(1, min(int(max_regimes), len(uniq)))
    # quantile edges over the DISTINCT observed loads: regimes cover
    # where data exists instead of slicing an empty axis evenly, and
    # every bin is guaranteed non-empty (edges are upper-inclusive)
    edges = [uniq[(i + 1) * len(uniq) // n_bins - 1]
             for i in range(n_bins - 1)]

    def bin_of(load: float) -> int:
        for i, e in enumerate(edges):
            if load <= e:
                return i
        return n_bins - 1

    regimes: List[dict] = []
    for b in range(n_bins):
        members = [o for o in obs if bin_of(o.offered_rps) == b]
        if not members:
            continue
        # mean tok/s per config key inside the bin; best config wins
        by_cfg: Dict[tuple, List[Observation]] = {}
        for o in members:
            by_cfg.setdefault(config_key(o.config), []).append(o)
        best = max(by_cfg.values(),
                   key=lambda os: sum(o.tok_s for o in os) / len(os))
        bound = edges[b] if b < n_bins - 1 else None
        regimes.append({
            "max_offered_rps": bound,
            "config": best[0].config,
            "expected_tok_s": round(
                sum(o.tok_s for o in best) / len(best), 2),
            "n_observations": len(members),
            "_winners": best,  # stripped before return
        })
    # merge adjacent regimes that picked the same config (the boundary
    # between them carries no information)
    merged: List[dict] = []
    for r in regimes:
        if merged and (config_key(merged[-1]["config"])
                       == config_key(r["config"])):
            merged[-1]["max_offered_rps"] = r["max_offered_rps"]
            merged[-1]["n_observations"] += r["n_observations"]
            merged[-1]["_winners"] = merged[-1]["_winners"] + r["_winners"]
        else:
            merged.append(r)
    if merged:
        merged[-1]["max_offered_rps"] = None  # guarantee a catch-all
    for r in merged:
        winners = r.pop("_winners")
        if not emit_guards or r["max_offered_rps"] is None:
            continue
        ttfts = [o.ttft_p99_s for o in winners
                 if o.ttft_p99_s is not None and o.ttft_p99_s > 0]
        g = round(float(ttft_headroom) * max(ttfts), 6) if ttfts else 0
        if g > 0:
            r["max_ttft_p99_s"] = g
        attains = [o.attainment for o in winners
                   if o.attainment is not None and o.attainment > 0]
        g = (round(min(1.0, float(attainment_margin) * min(attains)), 6)
             if attains else 0)
        if g > 0:
            r["min_attainment"] = g
    return PolicyTable(regimes=merged).validate()
