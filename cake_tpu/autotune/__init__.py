"""Online autotuner: joint config search + live hot-switching.

Three parts (ISSUE 9 / ROADMAP item 3, after Sandwich in PAPERS.md):

  * ``space``      — the declarative ``EngineConfig`` point, validity
                     rules (reusing args.py validation) and the
                     switch-legality guard;
  * ``search``     — the offline fitter: BENCH / step-log measurements
                     -> a piecewise ``PolicyTable`` (offered-load
                     regime -> best config), written to the
                     ``--autotune-policy`` file by tools/autotune_fit;
  * ``controller`` — the online loop: sliding-window signals with
                     hysteresis + cooldown + a one-shot rollback
                     guard, driving ``engine.reconfigure()`` between
                     iterations.

The hot-switch seam itself lives in serve/engine.py
(``InferenceEngine.reconfigure``): in-flight requests fold their
generated tokens into their prompts (exactly the PR 8 recovery path
minus backoff and crash implication), the jitted step fns + KV pool
rebuild under the new config, and everything requeues with seniority,
class and preempt budget preserved — greedy streams complete
token-identical at f32 KV across a switch.
"""

from cake_tpu.autotune.controller import (  # noqa: F401
    CONFIG_INFO, ROLLBACKS, SWITCH_SECONDS, SWITCHES, AutotuneController,
    AutotuneSignals, ControllerConfig, set_config_info,
)
from cake_tpu.autotune.search import (  # noqa: F401
    Observation, PolicyTable, extract_observations, fit,
    observations_from_step_log,
)
from cake_tpu.autotune.space import (  # noqa: F401
    EngineConfig, config_key, switch_guard, validate_config,
)
from cake_tpu.autotune.spec import (  # noqa: F401
    SpecGammaTuner, SpecTunerConfig,
)
