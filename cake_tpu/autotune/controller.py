"""Online autotune controller: sliding-window signals -> switch/rollback.

The engine thread drives this between iterations (engine._autotune_tick):
every ``interval_s`` it gathers one ``AutotuneSignals`` sample from the
telemetry the repo already has — step MFU / HBM utilization (obs/steps
flight recorder), page-pool occupancy, per-class queue depth and shed
rate (cake_tpu/sched), arrival TTFT percentiles (obs/tracing) — and asks
``decide()`` whether to move. The controller is pure host-side state (no
device work, no threads of its own), so tests drive it on synthetic
signal streams with a fake clock.

Decision discipline (the reason this is safe to run against live load):

  * **hysteresis** — a target config must win ``hold`` CONSECUTIVE
    samples before a switch is proposed; one noisy window moves nothing.
  * **cooldown** — at least ``cooldown_s`` between switches; a switch
    pays a fold-and-re-prefill of every in-flight stream, so flapping
    is strictly worse than either config.
  * **rollback guard** — after an autonomous switch the controller
    compares the measured service rate over the next
    ``rollback_window`` samples against the pre-switch window; if it
    dropped below ``rollback_frac`` of the old regime's rate, it
    reverts ONCE and pins the offending config (never re-proposed) —
    the policy table was fitted offline and can be wrong online.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

from cake_tpu.autotune.search import PolicyTable
from cake_tpu.autotune.space import EngineConfig, config_key
from cake_tpu.obs import metrics as obs_metrics

# the cake_autotune_* families (README "Autotuning" metrics rows;
# tools/lint_metrics.py --readme enforces them)
SWITCHES = obs_metrics.counter(
    "cake_autotune_switches_total",
    "Live engine config switches, by reason (auto = policy-driven, "
    "manual = POST /api/v1/autotune, rollback = the guard reverting a "
    "switch whose measured service rate regressed)",
    labelnames=("reason",))
ROLLBACKS = obs_metrics.counter(
    "cake_autotune_rollbacks_total",
    "Autonomous switches reverted by the rollback guard (the offending "
    "config is pinned and never re-proposed)")
SWITCH_SECONDS = obs_metrics.histogram(
    "cake_autotune_switch_seconds",
    "Wall seconds for one live config switch: fold every in-flight "
    "stream into its prompt, rebuild step fns + KV pool, requeue")
CONFIG_INFO = obs_metrics.gauge(
    "cake_autotune_config_info",
    "Live effective engine config as key=value info labels (value 1 "
    "for the current config's pairs, 0 for superseded ones)",
    labelnames=("key",))


def set_config_info(cfg: EngineConfig) -> None:
    """Publish the live config through cake_autotune_config_info: each
    knob becomes a ``key="name=value"`` child set to 1; children from a
    superseded config drop to 0 (the Prometheus info-metric pattern —
    a scrape always shows exactly one live value per knob)."""
    live = {f"{k}={v}" for k, v in cfg.to_dict().items()}
    for (val,), _ in CONFIG_INFO.samples().items():
        if val not in live:
            CONFIG_INFO.labels(key=val).set(0)
    for val in sorted(live):
        CONFIG_INFO.labels(key=val).set(1)


@dataclass
class AutotuneSignals:
    """One sliding-window sample of the engine's load/health signals."""

    t: float
    offered_rps: float = 0.0      # request arrivals per second
    service_tps: float = 0.0      # generated tokens per second
    completed_rps: float = 0.0    # retirements per second
    queue_depth: int = 0
    queue_depth_by_class: Dict[str, int] = field(default_factory=dict)
    mfu: float = 0.0
    hbm_util: float = 0.0
    pages_in_use_frac: float = 0.0
    shed_rps: float = 0.0
    ttft_p99_s: Optional[float] = None
    # quality signals (obs/slo.py, via the engine's SLO accountant +
    # scheduler): per-class TTFT p99, per-class rolling SLO attainment
    # and the scheduler's aging pressure — what lets the policy lookup
    # and the rollback guard key on quality, not just offered rps
    ttft_p99_by_class: Dict[str, float] = field(default_factory=dict)
    attainment: Dict[str, float] = field(default_factory=dict)
    queue_pressure: float = 0.0

    def min_attainment(self) -> Optional[float]:
        """Worst-class attainment this sample, None without data —
        the rollback guard's scalar quality verdict input."""
        return min(self.attainment.values()) if self.attainment else None

    def to_dict(self) -> dict:
        out = {
            "t": round(self.t, 3),
            "offered_rps": round(self.offered_rps, 3),
            "service_tps": round(self.service_tps, 3),
            "completed_rps": round(self.completed_rps, 3),
            "queue_depth": self.queue_depth,
            "mfu": round(self.mfu, 4),
            "hbm_util": round(self.hbm_util, 4),
            "pages_in_use_frac": round(self.pages_in_use_frac, 4),
            "shed_rps": round(self.shed_rps, 3),
        }
        if self.queue_depth_by_class:
            out["queue_depth_by_class"] = dict(self.queue_depth_by_class)
        if self.ttft_p99_s is not None:
            out["ttft_p99_s"] = round(self.ttft_p99_s, 6)
        if self.ttft_p99_by_class:
            out["ttft_p99_by_class"] = {
                c: round(v, 6) for c, v in self.ttft_p99_by_class.items()}
        if self.attainment:
            out["attainment"] = {
                c: round(v, 4) for c, v in self.attainment.items()}
        if self.queue_pressure:
            out["queue_pressure"] = round(self.queue_pressure, 4)
        return out


@dataclass
class ControllerConfig:
    interval_s: float = 2.0       # engine sampling cadence
    window: int = 5               # samples per sliding decision window
    hold: int = 2                 # hysteresis: consecutive wins to switch
    cooldown_s: float = 30.0      # min seconds between switches
    rollback_window: int = 3      # post-switch samples before the verdict
    rollback_frac: float = 0.7    # revert when post < frac * pre rate
    log_size: int = 64            # retained decision-log entries
    # pool-pressure escalation: window-mean pages_in_use_frac at or
    # above this proposes narrowing an int8 pool to int4 (the one
    # switch direction that frees page capacity without shrinking the
    # pool; the widening direction stays illegal — space.switch_guard)
    page_pressure_frac: float = 0.95


class AutotuneController:
    """Policy-driven switch/rollback decisions over a signal window.

    Thread model: ``decide``/``on_switched``/``pin`` run on the engine
    thread; ``state()`` is read by API handler threads — one lock
    covers the mutable window/log."""

    # cakelint guards discipline: the one-shot rollback guard is only
    # armed across a policy switch — every dotted use is None-guarded
    OPTIONAL_PLANES = ("_guard",)

    def __init__(self, policy: PolicyTable, current: EngineConfig,
                 config: Optional[ControllerConfig] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.config = config or ControllerConfig()
        self._now = now_fn
        self._mu = threading.Lock()
        self._current = current
        self._window: deque = deque(maxlen=max(1, self.config.window))
        self._log: deque = deque(maxlen=max(1, self.config.log_size))
        self._target_key: Optional[tuple] = None
        self._streak = 0
        self._last_switch_t: Optional[float] = None
        self._pinned: set = set()
        # armed rollback guard: (previous config, pre-switch rate,
        # pre-switch worst-class attainment (None without SLO data),
        # samples seen since the switch)
        self._guard: Optional[tuple] = None
        # sentinel fusion (--sentinel-act, obs/actions.py): active
        # config-plane anomalies hold new policy switches; an anomaly
        # that fires while the guard is armed pins the rollback verdict
        # immediately ((kind, cause) consumed by the next decide())
        self._anomaly_active: Dict[str, Dict] = {}
        self._anomaly_rollback: Optional[tuple] = None

    # -- decisions (engine thread) ----------------------------------------

    def window_service_tps(self) -> float:
        with self._mu:
            xs = [s.service_tps for s in self._window]
        return sum(xs) / len(xs) if xs else 0.0

    def window_offered_rps(self) -> float:
        with self._mu:
            xs = [s.offered_rps for s in self._window]
        return sum(xs) / len(xs) if xs else 0.0

    def window_quality(self) -> Tuple[Dict[str, float], Dict[str, float]]:
        """(ttft_p99_by_class, attainment) aggregated over the window:
        per class the WORST value seen — max TTFT p99, min attainment —
        so one bad-but-real sample inside the window keeps escalating a
        quality-guarded lookup (hysteresis, not the aggregate, is the
        noise filter)."""
        ttft: Dict[str, float] = {}
        attain: Dict[str, float] = {}
        with self._mu:
            samples = list(self._window)
        for s in samples:
            for c, v in s.ttft_p99_by_class.items():
                ttft[c] = max(ttft.get(c, 0.0), v)
            for c, v in s.attainment.items():
                attain[c] = min(attain.get(c, 1.0), v)
        return ttft, attain

    def _window_page_pressure(self) -> float:
        """Mean page-pool occupancy fraction over the window — the
        pool-pressure escalation's trigger signal."""
        with self._mu:
            xs = [s.pages_in_use_frac for s in self._window]
        return sum(xs) / len(xs) if xs else 0.0

    def _window_min_attainment(self) -> Optional[float]:
        """Mean worst-class attainment over the window's samples that
        carry attainment data (None without any) — the pre/post series
        the rollback guard compares."""
        with self._mu:
            xs = [a for a in (s.min_attainment() for s in self._window)
                  if a is not None]
        return sum(xs) / len(xs) if xs else None

    def decide(self, sig: AutotuneSignals
               ) -> Optional[Tuple[EngineConfig, str]]:
        """Ingest one sample; return (target config, reason) when the
        engine should switch now, else None. reason is "auto" for a
        policy-driven move and "rollback" for the guard reverting."""
        with self._mu:
            self._window.append(sig)
        rb = self._check_rollback(sig)
        if rb is not None:
            return rb, "rollback"
        now = sig.t
        cfg = self.config
        if (self._last_switch_t is not None
                and now - self._last_switch_t < cfg.cooldown_s):
            return None
        if self._guard is not None:
            return None  # verdict pending: no new move until it rules
        with self._mu:
            if self._anomaly_active:
                # anomaly hold (--sentinel-act): a recompile storm or
                # step-time regression is live — this window's signals
                # indict the environment, not a regime boundary; no
                # new policy move until the sentinel clears
                return None
        ttft_by_cls, attain = self.window_quality()
        target = self.policy.lookup(self.window_offered_rps(),
                                    ttft_p99_by_class=ttft_by_cls,
                                    attainment=attain)
        # pool-pressure escalation (takes precedence over the fitted
        # table — a starving pool throttles every config the table
        # could name): an int8 pool running at >= page_pressure_frac
        # occupancy over the window proposes the SAME point at int4,
        # doubling page capacity in place. int4 is terminal: there is
        # no narrower pool, and widening back is gated by switch_guard,
        # so the escalation converges. Flows through the normal
        # hysteresis + pin + rollback-guard machinery.
        if (self._current.paged and self._current.kv_dtype == "int8"
                and self._window_page_pressure()
                >= cfg.page_pressure_frac):
            target = replace(self._current, kv_dtype="int4")
        tkey = config_key(target)
        if tkey == config_key(self._current) or tkey in self._pinned:
            self._target_key, self._streak = None, 0
            return None
        if tkey == self._target_key:
            self._streak += 1
        else:
            self._target_key, self._streak = tkey, 1
        if self._streak < cfg.hold:
            return None
        return target, "auto"

    def _check_rollback(self, sig: AutotuneSignals
                        ) -> Optional[EngineConfig]:
        if self._guard is None:
            with self._mu:
                # a rollback proposed in the race window after the
                # guard ruled has nothing left to revert: drop it
                self._anomaly_rollback = None
            return None
        with self._mu:
            pinned_by = self._anomaly_rollback
            self._anomaly_rollback = None
        if pinned_by is not None:
            # anomaly evidence pins the verdict NOW (--sentinel-act):
            # a recompile storm / step-time regression right after an
            # autonomous switch indicts the new config — revert without
            # waiting out the rollback_window timer, and pin it
            kind, cause = pinned_by
            prev_cfg, pre_rate, _pre_attain, _seen = self._guard
            bad = self._current
            self._guard = None
            self._pinned.add(config_key(bad))
            self._note("rollback", frm=bad, to=prev_cfg,
                       pre_tps=pre_rate, cause=f"anomaly:{kind}",
                       anomaly=cause)
            return prev_cfg
        prev_cfg, pre_rate, pre_attain, seen = self._guard
        seen += 1
        self._guard = (prev_cfg, pre_rate, pre_attain, seen)
        if seen < self.config.rollback_window:
            return None
        with self._mu:
            post = list(self._window)[-self.config.rollback_window:]
        post_rate = (sum(s.service_tps for s in post) / len(post)
                     if post else 0.0)
        attains = [a for a in (s.min_attainment() for s in post)
                   if a is not None]
        post_attain = sum(attains) / len(attains) if attains else None
        bad = self._current
        self._guard = None
        rate_bad = (pre_rate > 0
                    and post_rate < self.config.rollback_frac * pre_rate)
        # quality verdict (obs/slo.py attainment riding the signals):
        # a switch that kept tok/s but collapsed SLO attainment — e.g.
        # bigger batches starving interactive TTFT — regressed the
        # thing serving exists for, and must revert just the same
        attain_bad = (pre_attain is not None and post_attain is not None
                      and pre_attain > 0
                      and post_attain
                      < self.config.rollback_frac * pre_attain)
        if rate_bad or attain_bad:
            # revert ONCE and pin: the fitted policy was wrong online
            # for this regime — never re-propose the offending config
            self._pinned.add(config_key(bad))
            self._note("rollback", frm=bad, to=prev_cfg,
                       pre_tps=pre_rate, post_tps=post_rate,
                       pre_attainment=pre_attain,
                       post_attainment=post_attain,
                       cause=("attainment" if attain_bad and not rate_bad
                              else "service_rate"))
            return prev_cfg
        self._note("accepted", frm=prev_cfg, to=bad,
                   pre_tps=pre_rate, post_tps=post_rate,
                   pre_attainment=pre_attain,
                   post_attainment=post_attain)
        return None

    def on_switched(self, new: EngineConfig, old: EngineConfig,
                    pre_rate: float, reason: str) -> None:
        """The engine completed a switch: update current, start the
        cooldown, and (for autonomous moves only) arm the rollback
        guard with the old regime's measured rate. Rollback and manual
        switches arm nothing — the guard fires exactly once."""
        self._current = new
        self._last_switch_t = self._now()
        self._target_key, self._streak = None, 0
        if reason == "auto":
            # the guard compares service rate AND worst-class SLO
            # attainment against the old regime's window
            self._guard = (old, pre_rate,
                           self._window_min_attainment(), 0)
        else:
            self._guard = None
        self._note("switch", frm=old, to=new, reason=reason,
                   pre_tps=pre_rate)

    def pin(self, cfg: EngineConfig, why: str = "switch failed") -> None:
        """Ban a config (e.g. the engine refused the switch because an
        in-flight stream cannot fit its pool)."""
        self._pinned.add(config_key(cfg))
        self._note("pinned", to=cfg, reason=why)

    # -- sentinel fusion (any thread; obs/actions.py) ----------------------

    @property
    def guard_armed(self) -> bool:
        return self._guard is not None

    def note_anomaly(self, kind: str, state: str, cause: Dict,
                     *, allow_switch: bool = True) -> Optional[str]:
        """A sentinel transition as a first-class controller signal
        (--sentinel-act). Thread-safe: called from the sentinel thread;
        it only flips host-side intent that decide() consumes on the
        engine thread.

        Returns the proposal this transition produced: ``"rollback"``
        (the post-switch guard is armed and this anomaly pins its
        verdict — the next decide() reverts through the existing
        reconfigure() seam), ``"hold"`` (no new policy switches while
        the anomaly is active), ``"resume"`` (the last active anomaly
        cleared — normal deciding resumes), or None (a clear with other
        anomalies still active). `allow_switch=False` (the action
        plane's rate bound) downgrades a would-be rollback to a plain
        hold."""
        if state not in ("fired", "cleared"):
            raise ValueError(f"state {state!r} must be fired or cleared")
        with self._mu:
            if state == "fired":
                self._anomaly_active[kind] = dict(cause)
                if (self._guard is not None and allow_switch
                        and self._anomaly_rollback is None):
                    self._anomaly_rollback = (kind, dict(cause))
                    proposal = "rollback"
                else:
                    proposal = "hold"
            else:
                self._anomaly_active.pop(kind, None)
                proposal = ("resume" if not self._anomaly_active
                            else None)
        if proposal is not None:
            self._note("anomaly", kind=kind, state=state,
                       proposal=proposal)
        return proposal

    # -- introspection (any thread) ---------------------------------------

    def _note(self, action: str, frm: Optional[EngineConfig] = None,
              to: Optional[EngineConfig] = None, **fields) -> None:
        entry = {"t": round(time.time(), 3), "action": action, **fields}
        if frm is not None:
            entry["from"] = frm.to_dict()
        if to is not None:
            entry["to"] = to.to_dict()
        with self._mu:
            self._log.append(entry)

    def decision_log(self) -> List[dict]:
        with self._mu:
            return list(self._log)

    def state(self) -> dict:
        with self._mu:
            window = [s.to_dict() for s in self._window]
            log = list(self._log)
            anomaly_hold = sorted(self._anomaly_active)
        return {
            "current": self._current.to_dict(),
            "anomaly_hold": anomaly_hold,
            "window": window,
            "offered_rps": round(self.window_offered_rps(), 3),
            "service_tps": round(self.window_service_tps(), 3),
            "cooldown_s": self.config.cooldown_s,
            "hold": self.config.hold,
            "pinned": len(self._pinned),
            "guard_armed": self._guard is not None,
            "decisions": log,
        }
