"""The autotuner's configuration space (cake_tpu/autotune).

BENCH_MEASURED shows the optimal engine configuration is load-dependent
and *moves*: 16 slots was the v5e sweet spot at 408-441 tok/s, then
after continuous batching the peak migrated to 32-64 slots while 32
slots had previously thrashed HBM at 151 tok/s. No static
--max-slots/--decode-scan/--kv-pages choice is right across offered
loads, so the autotuner treats those knobs as a declarative point in a
config space:

  * ``EngineConfig`` — one point: the engine knobs that can be switched
    LIVE (serve/engine.reconfigure) without reloading weights: decode
    slots, decode-scan burst length, page pool geometry, KV storage
    dtype, mixed batching, and the paged attention impl. Everything
    else (model, max_seq_len, sampling defaults, scheduling policy) is
    engine identity and never moves.
  * ``validate_config`` — per-flavor validity rules REUSING args.py
    validation (the CLI and the autotuner cannot drift on what a legal
    config is), plus the engine-level geometry rules.
  * ``switch_guard`` — the legality of a LIVE transition between two
    valid points. The one gated direction: an int8 pool cannot hot-
    switch to a float pool, because the emitted history was sampled
    under quantized KV numerics and the fold-tokens-into-prompt resume
    would re-derive exact-KV logits that need not agree with the tokens
    already streamed — the greedy token-identity contract cannot be
    honored, so the switch is refused loudly instead of silently
    changing mid-stream semantics.
  * ``config_key`` — the canonical comparison key: ``auto`` knobs
    resolve (backend-dependent) and dense-irrelevant paged knobs are
    dropped, so "the same config spelled differently" never triggers a
    pointless switch.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, fields
from typing import Optional, Tuple

# knob names, in the order operators read them (health/autotune JSON)
CONFIG_KEYS = ("slots", "decode_scan", "kv_pages", "kv_page_size",
               "kv_dtype", "mixed_batch", "paged_attn")


@dataclass(frozen=True)
class EngineConfig:
    """One switchable engine configuration point.

    ``kv_pages is None`` selects the dense engine (one [L, B, T] cache);
    a value selects the paged engine with that pool geometry. Field
    defaults mirror args.Args so a config built from partial JSON means
    the same thing the CLI flags would."""

    slots: int = 8
    decode_scan: int = 1
    kv_pages: Optional[int] = None
    kv_page_size: int = 128
    kv_dtype: Optional[str] = None
    mixed_batch: str = "auto"
    paged_attn: str = "auto"

    @property
    def paged(self) -> bool:
        return self.kv_pages is not None

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "EngineConfig":
        known = {f.name for f in fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(
                f"unknown engine config keys {unknown}; the switchable "
                f"knobs are {list(CONFIG_KEYS)}")
        kw = {}
        for f in fields(cls):
            if f.name not in d or d[f.name] is None:
                continue
            v = d[f.name]
            if f.name in ("slots", "decode_scan", "kv_pages",
                          "kv_page_size"):
                v = int(v)
            kw[f.name] = v
        return cls(**kw)


def resolve_paged_attn(paged_attn: Optional[str]) -> str:
    """THE paged_attn auto-resolution rule — pallas on a real TPU,
    fold elsewhere (interpret-mode pallas on CPU is slow) — shared by
    the engine's dispatch setup (serve/engine._setup_paged_exec) and
    config_key, so the comparison key can never resolve "auto"
    differently from the engine. Non-auto names pass through
    unvalidated (the engine validates at dispatch setup)."""
    impl = paged_attn or "auto"
    if impl == "auto":
        try:
            import jax
            impl = "pallas" if jax.default_backend() == "tpu" else "fold"
        except Exception:  # noqa: BLE001 — comparison key, not dispatch
            impl = "fold"
    return impl


def _canon_kv_dtype(name: Optional[str]) -> Optional[str]:
    """Spelling-normalized storage dtype: "f32"/"float32" and friends
    map to one canonical string; the quantized-pool names (int8/int4)
    and None (follow the engine's cache dtype) pass through."""
    if name is None or name in ("int8", "int4"):
        return name
    try:
        import numpy as np

        from cake_tpu.utils.devices import resolve_kv_dtype
        return np.dtype(resolve_kv_dtype(name)).name
    except Exception:  # noqa: BLE001 — comparison key, not dispatch
        return name


def config_key(cfg: EngineConfig,
               default_kv_dtype: Optional[str] = None) -> Tuple:
    """Canonical comparison key: ``auto`` knobs resolved the way the
    engine would resolve them, dtype spellings normalized, paged-only
    knobs dropped for dense points (a dense config's
    kv_page_size/paged_attn/kv_dtype select nothing, so two spellings
    must compare equal).

    default_kv_dtype: what an UNSET kv_dtype resolves to (the engine's
    base cache dtype). The engine passes it so a policy spelling the
    default explicitly ("bf16" on a bf16-cache engine) compares equal
    to one omitting it — without the context, callers that cannot know
    the default (the controller) leave None distinct."""
    if not cfg.paged:
        return ("dense", cfg.slots, cfg.decode_scan)
    mixed = (cfg.mixed_batch or "auto") != "off"
    kd = _canon_kv_dtype(cfg.kv_dtype)
    if kd is None and default_kv_dtype is not None:
        kd = _canon_kv_dtype(default_kv_dtype)
    return ("paged", cfg.slots, cfg.decode_scan, cfg.kv_pages,
            cfg.kv_page_size, kd,
            resolve_paged_attn(cfg.paged_attn), mixed)


def validate_config(cfg: EngineConfig,
                    max_seq_len: Optional[int] = None) -> EngineConfig:
    """Per-flavor validity rules. Deliberately REUSES args.Args.validate
    (the single source of CLI-level config legality) by projecting the
    point onto the matching flags, then adds the engine geometry rules
    args.py leaves to the engine."""
    from cake_tpu.args import Args

    # args.validate covers: paged_attn/mixed_batch enums, kv_dtype name
    # resolution, int8-requires-pages, max_slots/decode_scan >= 1
    Args(model="", max_slots=cfg.slots, decode_scan=cfg.decode_scan,
         kv_pages=cfg.kv_pages, kv_page_size=cfg.kv_page_size,
         kv_dtype=cfg.kv_dtype, mixed_batch=cfg.mixed_batch,
         paged_attn=cfg.paged_attn).validate()
    if cfg.mixed_batch == "on" and not cfg.paged:
        raise ValueError(
            "mixed_batch=on requires kv_pages: the mixed ragged step "
            "dispatches over the paged pool")
    if cfg.paged and (cfg.kv_pages < 1 or cfg.kv_page_size < 1):
        raise ValueError(
            f"kv_pages {cfg.kv_pages} / kv_page_size "
            f"{cfg.kv_page_size} must be >= 1")
    # NOTE deliberately NO pool-vs-max_seq_len floor: the engine itself
    # accepts pools smaller than one max-length stream (submit()
    # fail-fasts requests that can never fit), so the autotuner must
    # not be stricter than the CLI — a live switch instead refuses any
    # pool an IN-FLIGHT stream does not fit (engine._reconfigure_sync;
    # max_seq_len is accepted for future geometry rules).
    del max_seq_len
    return cfg


def _dtype_rank(name: Optional[str]) -> int:
    """Precision rank of a KV storage dtype: int4 < int8 < float. A
    live switch may only hold precision or NARROW it — widening would
    re-derive in-flight transcripts at higher-precision KV."""
    return {"int4": 0, "int8": 1}.get(_canon_kv_dtype(name), 2)


def switch_guard(old: EngineConfig, new: EngineConfig) -> Optional[str]:
    """Reason a LIVE old -> new switch is refused, or None when legal.

    Any precision-WIDENING direction (int8 -> float, int4 -> int8,
    int4 -> float) is gated off: streams already served from the
    quantized pool emitted tokens sampled under QUANTIZED KV numerics,
    and the hot-switch resume re-prefills their transcripts at the
    wider KV — the continuation can disagree with the history the
    client already received, so the greedy token-identity contract
    (tests/test_autotune_engine.py pins it for every allowed switch at
    f32 KV) cannot be honored in this direction. Quantizing FORWARD
    (float -> int8 -> int4) is the autotuner's memory-pressure
    response and stays allowed: no identity claim is made for a
    quantized target."""
    ro, rn = _dtype_rank(old.kv_dtype), _dtype_rank(new.kv_dtype)
    if ro < rn:
        names = {0: "int4-pool", 1: "int8-pool", 2: "float-pool"}
        return (
            f"refusing the {names[ro]} -> {names[rn]} hot switch: "
            "in-flight streams were decoded against quantized KV, and "
            "the fold-tokens-into-prompt resume would re-prefill their "
            "transcripts at wider KV — continuations could diverge "
            "from the already-streamed history, breaking the greedy "
            "token-identity contract. Drain the engine and restart "
            f"with the {names[rn]} instead.")
    return None
