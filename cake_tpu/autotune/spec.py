"""Gamma tuner: the closed loop over paged speculative acceptance.

The spec plane (cake_tpu/spec/state.py) feeds this controller the
engine-wide acceptance EMA after every batched round; the tuner's one
autonomous move is NARROWING — when acceptance stays under the shrink
threshold after warmup it halves the live gamma (gamma = max(1,
gamma // 2)), trading speculative depth for fewer wasted draft steps.
It never grows gamma back and never disables speculation engine-wide:
per-stream disable is the engine's call (acceptance-collapse /
spec.verify-fault policy in spec/state.py), and re-widening would need
the PolicyTable treatment (ROADMAP item 3) rather than a greedy flip.

Hysteresis follows the AutotuneController discipline in miniature:
``hold`` consecutive below-threshold rounds to move, a round-counted
cooldown after each move, and the warmup keeps the cold EMA from
condemning gamma before it has seen real acceptance. Round-counted
(not wall-clock) so behaviour is deterministic under test.

The engine publishes the move as a ``spec_degraded`` event with
action="shrink_gamma" and bumps cake_spec_degraded_total — the tuner
itself only decides.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SpecGammaTuner", "SpecTunerConfig"]


@dataclass(frozen=True)
class SpecTunerConfig:
    # engine-wide acceptance EMA below this is "gamma too deep"
    shrink_below: float = 0.3
    # rounds observed before the tuner may move at all
    warmup_rounds: int = 8
    # hysteresis: consecutive below-threshold rounds to shrink
    hold: int = 3
    # rounds after a shrink before the next one may trigger
    cooldown_rounds: int = 8


class SpecGammaTuner:
    """Narrowing-only gamma controller (engine thread, between steps)."""

    def __init__(self, gamma: int, config: SpecTunerConfig | None = None):
        self.config = config or SpecTunerConfig()
        self.gamma = int(gamma)          # the tuner's view of live gamma
        self.rounds = 0
        self._below = 0                  # consecutive below-threshold rounds
        self._cooldown = 0               # rounds left before next move
        self.shrinks = 0

    def note_round(self, accept_ema: float | None) -> None:
        """Fold one batched round's engine-wide acceptance EMA."""
        self.rounds += 1
        if self._cooldown > 0:
            self._cooldown -= 1
        if accept_ema is not None and accept_ema < self.config.shrink_below:
            self._below += 1
        else:
            self._below = 0

    def maybe_shrink(self) -> int | None:
        """New (smaller) gamma if the loop says narrow, else None.

        The caller owns the live gamma; on a non-None return it must
        adopt the value (the tuner assumes it did — its cooldown arms
        either way)."""
        cfg = self.config
        if self.gamma <= 1 or self.rounds < cfg.warmup_rounds:
            return None
        if self._cooldown > 0 or self._below < cfg.hold:
            return None
        self.gamma = max(1, self.gamma // 2)
        self.shrinks += 1
        self._below = 0
        self._cooldown = cfg.cooldown_rounds
        return self.gamma
