"""cake-tpu: a TPU-native distributed inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of `shurizzle/cake`
(distributed Llama-3 + Stable Diffusion inference): instead of a master/worker
TCP pipeline shipping hidden states between heterogeneous devices, cake-tpu
compiles the whole model as SPMD programs over a `jax.sharding.Mesh`, with
`topology.yml` mapping contiguous transformer-block ranges onto pipeline
stages and XLA collectives (ICI) doing the transport.

Layer map (bottom → top), mirroring SURVEY.md §1:
  ops/       — RoPE, RMSNorm, attention (XLA + Pallas flash), sampling
  models/    — Llama-3 family, Stable Diffusion, chat templating
  parallel/  — mesh construction, stage assignment, pjit/shard_map pipelines
  utils/     — device + dtype policy, safetensors loading
  topology   — YAML topology with `model.layers.N-M` range expansion
  api/       — OpenAI-compatible REST serving
  tools/     — weight splitting, introspection
"""

__version__ = "0.1.0"

import cake_tpu.utils.compat  # noqa: F401  (jax API shims, side-effect)
from cake_tpu.topology import Topology, Node  # noqa: F401
from cake_tpu.args import Args, SDArgs, ImageGenerationArgs  # noqa: F401
