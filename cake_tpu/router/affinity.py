"""Prefix-affinity keys and the consistent-hash ring.

The affinity KEY is the page-aligned prefix fingerprint of a request's
shareable head — the SAME rounding rule as the paged engine's
`register_prefix` (serve/engine.py): the head is rounded DOWN to a page
boundary, because the partial last page never enters the shared prefix
registry. Two prompts identical through the aligned head therefore hash
identically even when their partial tail pages differ, which is exactly
the population that can share pool pages on one replica.

The RING is a classic consistent hash (vnodes per replica on a 2^64
circle): adding or removing one replica of N remaps only ~1/N of the
key population (pinned by a property test over 1k synthetic prefixes),
so a scale-out event invalidates a bounded slice of the fleet's warm
prefix pages instead of reshuffling everything.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterator, List, Optional, Sequence, Tuple


def prefix_fingerprint(ids: Sequence[int],
                       page_size: int) -> Optional[str]:
    """Page-aligned fingerprint of a token-id head, or None when the
    head is shorter than one page (nothing shareable — the same refusal
    register_prefix makes)."""
    if page_size < 1:
        raise ValueError(f"page_size {page_size} must be >= 1")
    aligned = (len(ids) // page_size) * page_size
    if aligned == 0:
        return None
    h = hashlib.sha1()
    for t in ids[:aligned]:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


def text_fingerprint(text: str) -> Optional[str]:
    """Degraded-mode key for a tokenizer-less router: a stable hash of
    the rendered head TEXT. Affinity still converges (one system prompt
    -> one replica) but without page alignment two prompts differing
    only inside the partial last page hash apart — run the router with
    the model's tokenizer to get the aligned behavior."""
    if not text:
        return None
    return hashlib.sha1(text.encode("utf-8")).hexdigest()


def _point(data: str) -> int:
    return int.from_bytes(
        hashlib.sha1(data.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over replica names (vnodes on a 2^64
    circle). Membership changes are copy-on-write: add/remove publish a
    fresh points list wholesale, so an in-flight nodes_for iterator
    (router handler threads) walks the ring it started on while fleet
    discovery joins/forgets replicas concurrently. Vnode points are
    deterministic per NAME, so a departed replica that rejoins lands on
    exactly its old ring positions — the moved-key population of a
    depart+rejoin cycle is the ~1/N of the depart alone, not 2x."""

    def __init__(self, nodes: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes {vnodes} must be >= 1")
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []
        for n in nodes:
            self.add(n)

    def __len__(self) -> int:
        return len({name for _, name in self._points})

    def nodes(self) -> List[str]:
        return sorted({name for _, name in self._points})

    def add(self, node: str) -> None:
        pts = self._points
        if any(name == node for _, name in pts):
            return
        pts = list(pts)
        for i in range(self.vnodes):
            bisect.insort(pts, (_point(f"{node}#{i}"), node))
        self._points = pts

    def remove(self, node: str) -> None:
        self._points = [(p, n) for p, n in self._points if n != node]

    def nodes_for(self, key: str) -> Iterator[str]:
        """Distinct replicas in ring order starting at the key's point —
        the first is the affinity target, the rest the bounded-load
        spill order (deterministic per key, so a spilled tenant keeps
        landing on the SAME second-choice replica and can warm it)."""
        pts = self._points   # one snapshot: membership may change mid-walk
        if not pts:
            return
        start = bisect.bisect_left(pts, (_point(key), ""))
        seen = set()
        n = len(pts)
        for i in range(n):
            _, name = pts[(start + i) % n]
            if name not in seen:
                seen.add(name)
                yield name

    def node_for(self, key: str) -> Optional[str]:
        return next(self.nodes_for(key), None)
