"""Routing policy: sticky idempotency keys -> prefix affinity with a
bounded-load spill -> least-loaded healthy.

Pick order for one request:

1. **Sticky idempotency key.** A retried `x-cake-idempotency-key`
   routes to the replica that first admitted it, so the PR 12 attach
   semantics (never double-admit; Last-Event-ID exact-suffix resume)
   hold across the fleet. Only when that replica is EJECTED does the
   key fall through to re-admission elsewhere — a draining home still
   serves attaches (the key names existing work; `engine.submit`
   checks the key before the drain gate).
2. **Prefix affinity.** The consistent-hash target for the request's
   page-aligned prefix fingerprint — unless it is over the load
   watermark, in which case the request SPILLS to the next ring node
   (bounded load: a hot tenant saturating its home replica overflows
   deterministically instead of queueing behind itself) and the miss
   is recorded.
3. **Least-loaded** healthy, admitting replica (no fingerprint, or the
   whole ring is uneligible).

A request no replica can take raises NoReplicaError. Its retry-after,
when present, is a REPLICA-computed drain ETA — the router never
invents a Retry-After of its own (the PR 5/12 honest-backpressure
contract).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Set

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.router.affinity import HashRing
from cake_tpu.router.replicas import ReplicaState, ReplicaTracker

_AFFINITY = obs_metrics.counter(
    "cake_router_affinity_total",
    "Routing decisions by affinity outcome: hit (ring target taken), "
    "spill (target over the load watermark or uneligible), sticky "
    "(idempotency-key home), none (no shareable prefix)",
    labelnames=("outcome",))
_FAILOVERS = obs_metrics.counter(
    "cake_router_failovers_total",
    "Requests re-routed away from their first-choice replica",
    labelnames=("reason",))


class NoReplicaError(Exception):
    """No replica can admit this request. retry_after_s, when not None,
    is a replica-computed drain ETA (propagated, never invented)."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class Decision:
    """One routing decision (also the router's JSONL decision-log
    record via to_json)."""

    __slots__ = ("replica", "outcome", "sticky", "spill_reason")

    def __init__(self, replica: str, outcome: str, sticky: bool,
                 spill_reason: Optional[str] = None):
        self.replica = replica
        self.outcome = outcome   # hit | spill | sticky | none
        self.sticky = sticky
        # why an affinity key did not land on its ring target:
        # "saturated" (the target is over the load watermark — the
        # bounded-load spill) or "uneligible" (ejected / draining /
        # excluded by this request's retry loop). None on hit/sticky/
        # keyless picks. Feeds the router event ring's affinity_miss /
        # spill_to_secondary causes (ISSUE 15).
        self.spill_reason = spill_reason

    def to_json(self) -> dict:
        out = {"replica": self.replica, "outcome": self.outcome,
               "sticky": self.sticky}
        if self.spill_reason is not None:
            out["spill_reason"] = self.spill_reason
        return out


class RoutingPolicy:
    """Pure pick logic over a ReplicaTracker + HashRing; thread-safe
    (HTTP handler threads route concurrently)."""

    def __init__(self, tracker: ReplicaTracker,
                 ring: Optional[HashRing] = None,
                 load_watermark: int = 8,
                 mode: str = "affinity",
                 sticky_cap: int = 4096):
        if mode not in ("affinity", "round_robin"):
            raise ValueError(f"unknown router policy mode {mode!r} "
                             "(choose affinity or round_robin)")
        if load_watermark < 1:
            raise ValueError(
                f"load_watermark {load_watermark} must be >= 1")
        self.tracker = tracker
        self.ring = ring if ring is not None else HashRing(
            tracker.names())
        self.load_watermark = load_watermark
        self.mode = mode
        self._mu = threading.Lock()
        # bounded key -> home-replica map (LRU): sticky failover state
        self._sticky: OrderedDict[str, str] = OrderedDict()
        self._sticky_cap = sticky_cap
        self._rr = 0   # round_robin cursor (the bench strawman)
        # placement de-weighting: replica -> composed weight in
        # (0, 1]. Effective load = load / weight, so a de-weighted
        # replica reads as saturated (affinity spills away,
        # least-loaded stops picking it) but stays ELIGIBLE — never
        # ejected on a stale anomaly window. The composed weight is
        # the PRODUCT of named factors (set_factor: "anomaly" from the
        # obs/actions.py actuator, "headroom"/"attainment" from pushed
        # fleet telemetry, router/discovery.py), floored at 0.05, with
        # per-factor provenance kept for /api/v1/fleet audit. Empty by
        # default: report-only behavior is bit-identical to weightless
        # routing.
        self._weights: dict = {}
        # replica -> {source: {"weight": w, "cause": str|None}}
        self._factors: dict = {}

    # -- sticky map ------------------------------------------------------

    def note_admitted(self, idem_key: Optional[str], replica: str,
                      trace: Optional[str] = None) -> None:
        """Record the replica that admitted a keyed request (retries
        route back to it — attach — until it is ejected) and the trace
        id it ran under, so a keyed reconnect CONTINUES the same
        distributed trace instead of starting a fresh one (the
        failover-resumed stream is one story across replicas)."""
        if idem_key is None:
            return
        with self._mu:
            prev = self._sticky.get(idem_key)
            if trace is None and prev is not None:
                trace = prev[1]
            self._sticky[idem_key] = (replica, trace)
            self._sticky.move_to_end(idem_key)
            while len(self._sticky) > self._sticky_cap:
                self._sticky.popitem(last=False)

    def sticky_home(self, idem_key: Optional[str]) -> Optional[str]:
        if idem_key is None:
            return None
        with self._mu:
            entry = self._sticky.get(idem_key)
            return entry[0] if entry is not None else None

    def sticky_trace(self, idem_key: Optional[str]) -> Optional[str]:
        """The trace id the keyed request first admitted under (None =
        unknown key, or it was admitted without trace context)."""
        if idem_key is None:
            return None
        with self._mu:
            entry = self._sticky.get(idem_key)
            return entry[1] if entry is not None else None

    # -- composed placement weights --------------------------------------

    def set_weight(self, replica: str, weight: float) -> None:
        """Back-compat seam for the closed-loop anomaly actuator
        (obs/actions.py): sets the "anomaly" FACTOR, leaving factors
        other sources own (headroom, attainment) intact — an anomaly
        clearing must not also clear a memory-pressure de-weight."""
        self.set_factor(replica, "anomaly", weight)

    def set_factor(self, replica: str, source: str, weight: float,
                   cause: Optional[str] = None) -> None:
        """Set one source's weight factor for a replica. A factor at
        (or above) 1.0 clears that source's entry — the common case
        stays an empty dict and a single load comparison. The composed
        weight is the product of the surviving factors, floored at
        0.05: a zero weight would be a de-facto ejection, which the
        de-weighting contract forbids. `cause` is the human-readable
        provenance ("pool free 0.06 < 0.25") surfaced by
        weight_provenance() and GET /api/v1/fleet."""
        with self._mu:
            facs = self._factors.setdefault(replica, {})
            if weight >= 1.0:
                facs.pop(source, None)
            else:
                facs[source] = {"weight": max(0.05, float(weight)),
                                "cause": cause}
            if not facs:
                self._factors.pop(replica, None)
                self._weights.pop(replica, None)
            else:
                w = 1.0
                for f in facs.values():
                    w *= f["weight"]
                self._weights[replica] = max(0.05, w)

    def clear_factors(self, replica: str) -> None:
        """Drop every factor for a replica (it was forgotten by fleet
        discovery — a future replica reusing the name starts clean)."""
        with self._mu:
            self._factors.pop(replica, None)
            self._weights.pop(replica, None)

    def weight(self, replica: str) -> float:
        with self._mu:
            return self._weights.get(replica, 1.0)

    def weights(self) -> dict:
        """Current non-1.0 composed weights (the /api/v1/anomalies and
        state export)."""
        with self._mu:
            return dict(self._weights)

    def weight_provenance(self, replica: str) -> dict:
        """The composed weight AND where it came from: per-factor
        weight + cause. {"weight": 1.0, "factors": {}} for an
        unweighted replica."""
        with self._mu:
            facs = self._factors.get(replica, {})
            return {"weight": self._weights.get(replica, 1.0),
                    "factors": {src: dict(f)
                                for src, f in facs.items()}}

    def _load_of(self, st: ReplicaState) -> float:
        """Placement load: reported load divided by the replica's
        weight (a 0.25-weight replica with 1 in flight competes like 4
        in flight)."""
        with self._mu:
            w = self._weights.get(st.name)
        return st.load if w is None else st.load / w

    # -- the pick --------------------------------------------------------

    def _eligible(self, exclude: Set[str]) -> List[ReplicaState]:
        out = [s for s in self.tracker.admitting()
               if s.name not in exclude]
        # route AROUND a replica reporting a live config hot-switch
        # (the compile wall behind a fold-everything switch would eat
        # this request's TTFT; proxying into it just earns a 409 roam)
        # — but ONLY while another eligible replica exists: a fleet
        # that is all mid-switch still serves, it never strands
        # traffic. Restore is automatic: the next doc without the flag
        # (the epoch landed) puts the replica straight back.
        steady = [s for s in out if not s.switch_in_flight]
        return steady if steady else out

    def route(self, key: Optional[str] = None,
              idem_key: Optional[str] = None,
              exclude: Optional[Set[str]] = None) -> Decision:
        """Pick a replica. `exclude` holds replicas already tried this
        request (the proxy's failover loop). Raises NoReplicaError when
        nothing can admit."""
        exclude = exclude or set()
        # 1. sticky home: attaches must land where the work lives,
        # draining or not — but never on an ejected corpse, and never
        # on a replica this request already failed against
        home = self.sticky_home(idem_key)
        if home is not None:
            st = self.tracker.get(home)
            usable = (st is not None and not st.ejected and st.polled
                      and not st.breaker_tripped
                      and st.doc.get("status") == "ok")
            if usable and home not in exclude:
                _AFFINITY.labels(outcome="sticky").inc()
                return Decision(home, "sticky", sticky=True)
            if not usable:
                # the home is GONE (not merely excluded by this
                # request's retry loop): re-admission elsewhere
                _FAILOVERS.labels(reason="home_ejected").inc()

        eligible = self._eligible(exclude)
        if not eligible:
            # propagate a replica-computed drain ETA when one exists;
            # otherwise the 503 carries NO Retry-After (the router
            # never invents one)
            etas = [s.drain_eta_s for s in self.tracker.states()
                    if s.draining and s.drain_eta_s is not None]
            raise NoReplicaError(
                "no replica can admit this request "
                f"(tried: {sorted(exclude) or 'none'}; "
                f"replicas: {self.tracker.snapshot()})",
                retry_after_s=min(etas) if etas else None)

        if self.mode == "round_robin":
            with self._mu:
                self._rr += 1
                pick = eligible[self._rr % len(eligible)]
            return Decision(pick.name, "none", sticky=False)

        # 2. affinity with bounded-load spill. `reason` remembers WHY
        # the ring primary was bypassed — "saturated" (bounded-load
        # spill) vs "uneligible" (ejected/draining/excluded) — for the
        # spill Decision's cause attribution
        reason = None
        if key is not None:
            first = True
            for name in self.ring.nodes_for(key):
                st = next((s for s in eligible if s.name == name), None)
                if st is None:
                    if first:
                        reason = "uneligible"
                    first = False   # ring target uneligible -> spill
                    continue
                if (self._load_of(st) >= self.load_watermark
                        and not first):
                    # later ring nodes only take spill when under the
                    # watermark too; past them we fall to least-loaded
                    first = False
                    continue
                if first and self._load_of(st) < self.load_watermark:
                    _AFFINITY.labels(outcome="hit").inc()
                    return Decision(st.name, "hit", sticky=False)
                if first:
                    # the affinity target is saturated: spill
                    reason = "saturated"
                    first = False
                    continue
                _AFFINITY.labels(outcome="spill").inc()
                return Decision(st.name, "spill", sticky=False,
                                spill_reason=reason)
            _AFFINITY.labels(outcome="spill").inc()

        # 3. least-loaded healthy (by weight-adjusted load)
        pick = min(eligible, key=lambda s: (self._load_of(s), s.name))
        if key is None:
            _AFFINITY.labels(outcome="none").inc()
        return Decision(pick.name,
                        "spill" if key is not None else "none",
                        sticky=False,
                        spill_reason=reason if key is not None else None)
