"""Router-side distributed tracing: per-request hop records.

The front door is where a request's end-to-end story STARTS — admit,
replica pick (with the affinity verdict), proxy connect, first byte,
failover resume, retire — yet PR 14 left it the one tier with no
request-linked telemetry. This module is the router's counterpart of
obs/tracing.py: a bounded ring of `HopRecord`s keyed by the
``x-cake-trace`` id the router mints (or propagates), each holding
wall-clock hop spans and the per-replica attempt list.

Contracts:

  * spans carry WALL-CLOCK timestamps directly (no perf_counter
    anchoring): the federated timeline merges them with clock-offset-
    corrected replica spans by plain sort;
  * a trace REACTIVATES on a keyed reconnect (`begin` with a known
    trace id appends to the same record, pulling it back out of the
    finished ring if needed) — a failover-resumed stream is ONE story
    across two replicas, not two records;
  * `find_by_rid` resolves a replica-local rid to its trace record
    through the attempt list — the router's
    ``GET /api/v1/requests/{rid}/timeline`` lookup;
  * rolling first-byte-latency and pick-outcome samples feed the
    sentinel's router detectors (obs/sentinel.attach_router_sentinel)
    with zero extra instrumentation;
  * with an events path set (``--trace-events`` on the router role),
    every span appends as one JSON line through the shared
    obs/jsonl.py writer, exactly like the engine tracer's audit log.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from cake_tpu.obs import metrics as _m
from cake_tpu.obs.jsonl import JsonlAppender

# terminal hop statuses (ok/retire = relay completed; relayed = a
# non-200 relayed verbatim; midstream = the stream broke after bytes
# reached the client; shed = the router could not place the request)
HOP_TERMINAL = ("retire", "relayed", "midstream", "shed", "error")

_HOP_FIRST_BYTE = _m.histogram(
    "cake_router_hop_first_byte_seconds",
    "Router-observed pick-to-first-byte latency per traced hop "
    "(router/tracing.py; the replica dimension rides the hop records "
    "served at GET /api/v1/requests/{rid}/timeline, never a label)")


@dataclass
class HopRecord:
    """One trace's router-side story. attempts: one row per replica
    pick (`{"replica", "outcome", "rid": int|None}`; rid filled when
    that replica admitted)."""

    trace: str
    cls: str = "standard"
    stream: bool = False
    hop: int = 1
    status: str = "active"
    wall_start: float = 0.0
    spans: List[Dict] = field(default_factory=list)
    attempts: List[Dict] = field(default_factory=list)

    def to_dict(self) -> Dict:
        return {
            "trace": self.trace,
            "class": self.cls,
            "stream": self.stream,
            "hop": self.hop,
            "status": self.status,
            "submitted_at": round(self.wall_start, 6),
            "spans": [dict(sp) for sp in self.spans],
            "attempts": [dict(a) for a in self.attempts],
        }


class HopTracer:
    """Bounded-ring hop recorder, safe from the router's handler
    threads. capacity bounds the FINISHED ring; active records are
    bounded by in-flight client connections."""

    # cakelint guards discipline: the JSONL appender is optional
    OPTIONAL_PLANES = ("_events",)

    def __init__(self, capacity: int = 256,
                 events_path: Optional[str] = None,
                 wall=time.time, mono=time.monotonic):
        self._lock = threading.Lock()
        self._active: Dict[str, HopRecord] = {}
        self._done: deque = deque(maxlen=max(1, int(capacity)))
        self._events = (JsonlAppender(events_path)
                        if events_path else None)
        self._wall = wall
        self._mono = mono
        # rolling sentinel feeds: (mono_t, replica, first-byte seconds)
        # and (mono_t, affinity outcome) — bounded, appended at the
        # span sites below, windowed by the router detectors
        self._ttfts: deque = deque(maxlen=2048)
        self._outcomes: deque = deque(maxlen=4096)

    # -- lifecycle (handler threads) --------------------------------------

    def begin(self, trace: str, *, cls: str = "standard",
              stream: bool = False, hop: int = 1) -> HopRecord:
        """Open (or REACTIVATE) the trace's record and span its
        admission at this tier. A keyed reconnect reuses its original
        trace id (the sticky map remembers it), so the resumed leg
        appends to the same story."""
        now = self._wall()
        with self._lock:
            rec = self._active.get(trace)
            if rec is None:
                rec = next((r for r in self._done if r.trace == trace),
                           None)
                if rec is not None:
                    # reactivation: pull the finished record back — the
                    # failover-resumed leg continues the same story
                    self._done.remove(rec)
                    rec.status = "active"
                    self._active[trace] = rec
            if rec is None:
                rec = HopRecord(trace=trace, cls=cls, stream=stream,
                                hop=hop, wall_start=now)
                self._active[trace] = rec
            rec.spans.append({"name": "admit", "t": now, "hop": hop})
        self._jsonl(rec, "admit", hop=hop, cls=cls)
        return rec

    def span(self, trace: str, name: str, **fields) -> None:
        now = self._wall()
        clean = {k: v for k, v in fields.items() if v is not None}
        with self._lock:
            rec = self._active.get(trace)
            if rec is None:
                return
            rec.spans.append({"name": name, "t": now, **clean})
            if name == "pick" and "outcome" in clean:
                self._outcomes.append((self._mono(), clean["outcome"]))
            if name == "first_byte" and "ttft_s" in clean \
                    and "replica" in clean:
                self._ttfts.append((self._mono(), clean["replica"],
                                    float(clean["ttft_s"])))
        if name == "first_byte" and "ttft_s" in clean:
            _HOP_FIRST_BYTE.observe(float(clean["ttft_s"]))
        self._jsonl(rec, name, **clean)

    def attempt(self, trace: str, replica: str, outcome: str) -> None:
        """Record one replica pick (the span rides along via span())."""
        with self._lock:
            rec = self._active.get(trace)
            if rec is None:
                return
            rec.attempts.append({"replica": replica, "outcome": outcome,
                                 "rid": None})

    def admitted(self, trace: str, replica: str,
                 rid: Optional[int]) -> None:
        """The replica 200'd: bind its echoed x-cake-rid to the
        newest attempt on that replica (the federated timeline's
        rid -> replica join key)."""
        now = self._wall()
        with self._lock:
            rec = self._active.get(trace)
            if rec is None:
                return
            for a in reversed(rec.attempts):
                if a["replica"] == replica:
                    a["rid"] = rid
                    break
            rec.spans.append({"name": "admitted", "t": now,
                              "replica": replica,
                              **({"rid": rid} if rid is not None
                                 else {})})
        self._jsonl(rec, "admitted", replica=replica, rid=rid)

    def finish(self, trace: str, status: str, **fields) -> None:
        """Terminal transition: span + move to the finished ring."""
        if status not in HOP_TERMINAL:
            raise ValueError(f"not a terminal hop status: {status!r}")
        now = self._wall()
        clean = {k: v for k, v in fields.items() if v is not None}
        with self._lock:
            rec = self._active.pop(trace, None)
            if rec is None:
                return
            rec.status = status
            rec.spans.append({"name": status, "t": now, **clean})
            self._done.append(rec)
        self._jsonl(rec, status, **clean)

    # -- export -----------------------------------------------------------

    def get(self, trace: str) -> Optional[Dict]:
        with self._lock:
            rec = self._active.get(trace)
            if rec is None:
                rec = next((r for r in self._done if r.trace == trace),
                           None)
            return rec.to_dict() if rec is not None else None

    def find_by_rid(self, rid: int) -> Optional[Dict]:
        """Newest record any of whose attempts admitted as `rid` on
        some replica — the /api/v1/requests/{rid}/timeline lookup.
        (rids are replica-LOCAL; collisions across replicas resolve
        newest-first, and the record names its replicas either way.)"""
        with self._lock:
            pools = (self._active.values(), reversed(self._done))
            newest = None
            for pool in pools:
                for rec in pool:
                    if any(a.get("rid") == rid for a in rec.attempts):
                        if newest is None or (rec.wall_start
                                              > newest.wall_start):
                            newest = rec
            return newest.to_dict() if newest is not None else None

    def dump(self, limit: Optional[int] = None) -> List[Dict]:
        """Records newest first: active, then the finished ring."""
        with self._lock:
            recs = (sorted(self._active.values(),
                           key=lambda r: r.wall_start, reverse=True)
                    + list(reversed(self._done)))
        if limit is not None:
            recs = recs[:max(0, int(limit))]
        return [r.to_dict() for r in recs]

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    # -- sentinel feeds ---------------------------------------------------

    def ttft_by_replica(self, window_s: float,
                        now: Optional[float] = None
                        ) -> Dict[str, List[float]]:
        """replica -> first-byte latencies observed inside the window
        (the replica-skew detector's input)."""
        now = self._mono() if now is None else now
        out: Dict[str, List[float]] = {}
        with self._lock:
            for t, rep, v in self._ttfts:
                if now - t <= window_s:
                    out.setdefault(rep, []).append(v)
        return out

    def outcome_counts(self, window_s: float,
                       now: Optional[float] = None) -> Dict[str, int]:
        """Affinity pick outcomes inside the window (hit / spill /
        sticky / none — the affinity-collapse detector's input)."""
        now = self._mono() if now is None else now
        out: Dict[str, int] = {}
        with self._lock:
            for t, outcome in self._outcomes:
                if now - t <= window_s:
                    out[outcome] = out.get(outcome, 0) + 1
        return out

    def close(self) -> None:
        if self._events is not None:
            self._events.close()

    # -- JSONL audit log --------------------------------------------------

    def _jsonl(self, rec: HopRecord, event: str, **fields) -> None:
        if self._events is None:
            return
        line = {"ts": round(self._wall(), 6), "trace": rec.trace,
                "event": event}
        line.update({k: v for k, v in fields.items() if v is not None})
        self._events.append(line)
